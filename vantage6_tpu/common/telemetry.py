"""Unified telemetry registry: one named counter/gauge/histogram API.

Before this module, every perf PR grew its own island of counters —
`serialization.WIRE_STATS`, `rest.REST_STATS`, executor inflight counts,
EventHub eviction tracking, AuthCache hit rates — each with its own
snapshot shape and no single place to read them. The registry absorbs
them all behind one API and renders the whole set as Prometheus text
(`GET /api/metrics` on the server serves exactly `render_prometheus()`).

Two ways in:

- **Owned instruments** — `REGISTRY.counter/gauge/histogram(name)` for
  code that wants to increment/observe directly (the WSGI layer's request
  counter + latency histogram live here). Get-or-create and thread-safe;
  re-requesting a name returns the same instrument, requesting it as a
  different kind raises.
- **Collectors** — `REGISTRY.register_collector(key, fn)` for the
  existing stat islands: `fn()` returns `{metric_name: value}` and is
  called at render/snapshot time. Keyed registration means a rebindable
  source (a new ServerApp in the same process) REPLACES its predecessor
  instead of double-reporting; a collector that raises is skipped for
  that render, never fatal.

Every name any of this may emit is declared in `KNOWN_METRICS` — the one
table `tools/check_collect.py` audits for uniqueness and snake_case, and
the HELP/TYPE source for the Prometheus render. Emitting an undeclared
name is allowed at runtime (rendered untyped) but the audit exists so the
declared surface stays the documented one.
"""
from __future__ import annotations

import re
import threading
from typing import Any, Callable

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# the one exposition content-type, shared by every /api/metrics handler
# (server AND node proxy) so a format change can't drift between them
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# name -> (kind, help). THE declarative metric surface: check_collect
# audits this table (unique, snake_case), /metrics renders HELP/TYPE from
# it. Add new metrics HERE first.
KNOWN_METRICS: list[tuple[str, str, str]] = [
    # wire (common.serialization.WIRE_STATS)
    ("v6t_wire_encode_calls_total", "counter", "serialize() calls"),
    ("v6t_wire_encode_bytes_total", "counter", "bytes produced by serialize()"),
    ("v6t_wire_encode_seconds_total", "counter", "seconds spent in serialize()"),
    ("v6t_wire_decode_calls_total", "counter", "deserialize() calls"),
    ("v6t_wire_decode_bytes_total", "counter", "bytes consumed by deserialize()"),
    ("v6t_wire_decode_seconds_total", "counter", "seconds spent in deserialize()"),
    ("v6t_wire_broadcasts_total", "counter", "broadcast encrypt calls"),
    ("v6t_wire_broadcast_recipients_total", "counter",
     "recipients across broadcast encrypts"),
    ("v6t_wire_broadcast_dedup_hits_total", "counter",
     "full AES passes avoided by single-pass broadcast"),
    # REST transport (common.rest.REST_STATS)
    ("v6t_rest_calls_total", "counter", "HTTP requests over the pooled transport"),
    ("v6t_rest_errors_total", "counter", "HTTP requests that errored (>=400 or raised)"),
    ("v6t_rest_stale_retries_total", "counter",
     "requests retried once on a stale keep-alive socket"),
    ("v6t_rest_bytes_sent_total", "counter", "request body bytes sent"),
    ("v6t_rest_bytes_received_total", "counter", "response body bytes received"),
    ("v6t_rest_seconds_total", "counter", "seconds spent in HTTP requests"),
    # HTTP server (server.web.App — also counts the node proxy's relay)
    ("v6t_http_requests_total", "counter", "WSGI requests handled"),
    ("v6t_http_errors_total", "counter", "WSGI responses with status >= 500"),
    ("v6t_http_request_seconds", "histogram", "WSGI request handling latency"),
    # event hub (server.events.EventHub via the ServerApp collector)
    ("v6t_event_hub_buffer_len", "gauge", "events currently buffered for replay"),
    ("v6t_event_hub_cursor", "gauge", "sequence number of the newest event"),
    ("v6t_event_hub_evicted_through", "gauge",
     "newest event sequence the bounded buffer has dropped"),
    ("v6t_event_hub_subscribers", "gauge", "in-process push subscribers"),
    ("v6t_event_truncated_total", "counter",
     "event fetches answered truncated: the consumer's cursor was behind "
     "the ring's eviction horizon"),
    # server hot-path caches (server.cache)
    ("v6t_auth_cache_hits_total", "counter", "token->principal cache hits"),
    ("v6t_auth_cache_misses_total", "counter", "token->principal cache misses"),
    ("v6t_auth_cache_entries", "gauge", "cached token->principal entries"),
    ("v6t_visibility_cache_hits_total", "counter",
     "org->collaborations visibility cache hits"),
    ("v6t_visibility_cache_misses_total", "counter",
     "org->collaborations visibility cache misses"),
    ("v6t_visibility_cache_entries", "gauge", "cached org->collaborations entries"),
    # server app
    ("v6t_server_uptime_seconds", "gauge", "seconds since ServerApp start"),
    # host-path executor pool (runtime.executor)
    ("v6t_executor_pools", "gauge", "live StationExecutor pools in this process"),
    ("v6t_executor_inflight_items", "gauge",
     "run items queued or executing across live pools"),
    ("v6t_executor_capacity", "gauge",
     "total worker slots across live pools (queue_buildup denominator)"),
    # gradient compression (fed.compression — docs/compression.md)
    ("v6t_compress_calls_total", "counter",
     "delta compress operations (one per station uplink)"),
    ("v6t_compress_raw_bytes_total", "counter",
     "dense f32 bytes entering the compressor"),
    ("v6t_compress_wire_bytes_total", "counter",
     "bytes actually shipped after quantization/sparsification"),
    ("v6t_decompress_calls_total", "counter",
     "delta decompress operations (server-side reconstructions)"),
    ("v6t_compress_ratio", "gauge",
     "raw/wire on-wire reduction of the latest compress"),
    ("v6t_compress_ef_norm", "gauge",
     "L2 norm of the most recent error-feedback accumulator"),
    # learning plane (runtime.learning — docs/observability.md "learning
    # plane"): convergence + per-station update-quality gauges; the
    # station gauges summarize the LATEST recorded round (the full
    # per-station table lives at GET /api/rounds/<task_id>)
    ("v6t_round_updates_total", "counter",
     "federated rounds recorded by the learning-plane observatory"),
    ("v6t_round_update_norm", "gauge",
     "L2 norm of the latest recorded pooled (global) update"),
    ("v6t_round_loss", "gauge",
     "mean training loss of the latest recorded round"),
    ("v6t_round_norm_decay", "gauge",
     "latest pooled update norm / peak norm so far (1.0 = not decaying)"),
    ("v6t_station_update_norm_max", "gauge",
     "largest per-station update L2 norm in the latest recorded round"),
    ("v6t_station_cos_min", "gauge",
     "smallest station cosine-to-pooled-update in the latest recorded "
     "round"),
    ("v6t_station_ef_norm_max", "gauge",
     "largest per-station error-feedback mass in the latest recorded "
     "round (compression armed)"),
    # tracing health (runtime.tracing)
    ("v6t_trace_spans_recorded_total", "counter", "spans recorded to the ring buffer"),
    ("v6t_trace_spans_dropped_total", "counter",
     "spans evicted from the full ring buffer"),
    ("v6t_trace_sink_errors_total", "counter",
     "JSONL sink write failures (sink disabled after the first)"),
    ("v6t_trace_buffer_len", "gauge", "spans currently buffered"),
    ("v6t_trace_enabled", "gauge", "1 when tracing collection is enabled"),
    # watchdog / alerting (runtime.watchdog — docs/observability.md)
    ("v6t_alerts_active", "gauge", "watchdog alerts currently active"),
    ("v6t_alerts_raised_total", "counter",
     "alert raise transitions (inactive -> active)"),
    ("v6t_alerts_cleared_total", "counter",
     "alert clear transitions (active -> resolved)"),
    ("v6t_watchdog_evaluations_total", "counter",
     "watchdog rule-evaluation passes"),
    ("v6t_watchdog_last_eval_unixtime", "gauge",
     "wall-clock of the last watchdog evaluation"),
    ("v6t_watchdog_feed_errors_total", "counter",
     "watchdog feed/rule callbacks that raised (skipped, never fatal)"),
    ("v6t_health_degraded", "gauge",
     "1 when the health verdict is degraded (component self-check failure "
     "or critical alert active)"),
    # node daemon resilience (node.daemon)
    ("v6t_daemon_backoff_total", "counter",
     "event-poll failures that entered the capped exponential backoff"),
    ("v6t_daemon_rotation_total", "counter",
     "full replica-URL rotations that found no reachable server (each "
     "enters the capped jittered backoff)"),
    # async buffered aggregation (runtime.federation.run_buffered)
    ("v6t_async_rounds_total", "counter",
     "buffered-async federated rounds orchestrated"),
    ("v6t_async_stragglers_killed_total", "counter",
     "straggler runs killed at quorum/deadline by buffered-async rounds"),
    # autopilot remediation engine (runtime.autopilot —
    # docs/OPERATOR_GUIDE.md "autopilot")
    ("v6t_autopilot_actions_total", "counter",
     "remediation actions applied by the autopilot"),
    ("v6t_autopilot_reverts_total", "counter",
     "autopilot actions reverted on alert clear"),
    ("v6t_autopilot_suppressed_total", "counter",
     "autopilot actions suppressed by dry-run mode or a missing actuator "
     "capability"),
    ("v6t_autopilot_engaged", "gauge",
     "autopilot actions currently applied and not yet reverted"),
    # flight recorder (common.flight)
    ("v6t_flight_records", "gauge",
     "entries currently buffered across the flight-recorder rings"),
    ("v6t_flight_dumps_total", "counter", "flight-recorder bundles written"),
    # device observatory (runtime.profiling — docs/observability.md
    # "device plane"): every jit entry point's compile/retrace economics
    ("v6t_jit_dispatches_total", "counter",
     "calls dispatched through observed jit functions"),
    ("v6t_jit_compiles_total", "counter",
     "XLA lower+compile events recorded by the device observatory"),
    ("v6t_jit_lower_seconds_total", "counter",
     "seconds spent in jax lowering across observed compiles"),
    ("v6t_jit_compile_seconds_total", "counter",
     "seconds spent in XLA compilation across observed compiles"),
    ("v6t_jit_retraces_total", "counter",
     "retraces: an observed function compiled against a NEW abstract "
     "signature (recompile_storm's series)"),
    ("v6t_jit_static_sweeps_total", "counter",
     "compiles differing from a seen signature only in declared sweep "
     "statics (the fused program's n_rounds) — planned executables, "
     "excluded from the retrace series"),
    ("v6t_jit_fallbacks_total", "counter",
     "observed dispatches that fell back to plain jax.jit (tracer args, "
     "sharding mismatch, AOT-unloweable call)"),
    ("v6t_jit_cache_evictions_total", "counter",
     "compiled executables evicted from observed functions' bounded "
     "signature caches"),
    ("v6t_jit_functions", "gauge",
     "functions registered with the device observatory"),
    ("v6t_jit_signatures", "gauge",
     "live compiled signatures across observed functions"),
    ("v6t_jit_compile_temp_bytes", "gauge",
     "temp bytes of the most recent observed compile (memory_analysis)"),
    ("v6t_jit_compile_flops", "gauge",
     "flops estimate of the most recent observed compile (cost_analysis)"),
    # fingerprint-keyed runner caches (glm/quantile/device_engine via
    # runtime.profiling.engine_cache_event)
    ("v6t_engine_cache_hits_total", "counter",
     "mesh.fingerprint()-keyed runner cache hits"),
    ("v6t_engine_cache_misses_total", "counter",
     "mesh.fingerprint()-keyed runner cache misses (fresh compiles)"),
    ("v6t_engine_cache_entries", "gauge",
     "live entries across the fingerprint-keyed runner caches"),
    # fused multi-round device program (fed.fedavg.run_rounds /
    # run_rounds_async — docs/device_speed.md): how many logical rounds
    # each host dispatch amortizes
    ("v6t_fused_dispatches_total", "counter",
     "fused K-round program dispatches (one per run_rounds call)"),
    ("v6t_fused_rounds_total", "counter",
     "logical federated rounds executed inside fused dispatches"),
    ("v6t_fused_rounds_per_dispatch", "gauge",
     "K of the most recent fused dispatch (rounds amortized per host "
     "round-trip)"),
    # per-device memory (runtime.profiling device_mem collector; absent
    # on backends reporting no memory stats, e.g. CPU)
    ("v6t_device_count", "gauge",
     "local devices visible to this process"),
    ("v6t_device_mem_bytes_in_use", "gauge",
     "device memory in use, summed over local devices"),
    ("v6t_device_mem_peak_bytes", "gauge",
     "worst-device peak bytes in use across local devices"),
    # fleet telemetry fabric (common.fleet push path + server.fleet store
    # — docs/observability.md "fleet fabric")
    ("v6t_fleet_pushes_total", "counter",
     "telemetry snapshots shipped to POST /api/telemetry"),
    ("v6t_fleet_push_errors_total", "counter",
     "fleet pushes that failed (server unreachable or rejected)"),
    ("v6t_fleet_push_unsupported_total", "counter",
     "fleet pushes pinned off against a pre-fleet server (404/405)"),
    ("v6t_fleet_ingests_total", "counter",
     "fleet snapshots accepted by POST /api/telemetry on this replica"),
    ("v6t_fleet_ingest_rejects_total", "counter",
     "telemetry push bodies rejected as undecodable"),
    ("v6t_fleet_ingest_rows_total", "counter",
     "metric sample rows appended to the fleet store by ingests"),
    ("v6t_fleet_pruned_rows_total", "counter",
     "fleet store rows deleted by the retention pruner"),
    ("v6t_fleet_sources", "gauge",
     "distinct telemetry sources in the fleet store's retention window"),
    ("v6t_fleet_stale_sources", "gauge",
     "fleet sources whose newest snapshot is past the staleness window"),
    # the dispatch-latency SLO's series: observed server-side at the
    # run start transition, and mirrored as per-event samples into the
    # fleet store so burn rates survive replica restarts
    ("v6t_run_dispatch_seconds", "histogram",
     "assigned->started dispatch latency of runs (the dispatch SLO's "
     "subject series)"),
    # SLO engine (runtime.watchdog SloRule — docs/observability.md "SLO
    # burn-rate alerting")
    ("v6t_slo_evaluations_total", "counter",
     "SLO burn-rate rule evaluations"),
    ("v6t_slo_burning", "gauge",
     "SLO rules currently alerting (burn over threshold in both windows)"),
]

_KNOWN: dict[str, tuple[str, str]] = {
    name: (kind, help_) for name, kind, help_ in KNOWN_METRICS
}


def metric_kind(name: str) -> str | None:
    """Declared kind ("counter"/"gauge"/"histogram") of a KNOWN_METRICS
    name, None for undeclared series."""
    entry = _KNOWN.get(name)
    return entry[0] if entry else None


def validate_metric_name(name: str) -> None:
    if not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} must be snake_case "
            "([a-z][a-z0-9_]*)"
        )


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


# latency-shaped defaults: 1ms .. ~30s
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0,
)


class Histogram:
    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._count += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "buckets": dict(zip(self.buckets, self._counts)),
                "sum": self._sum,
                "count": self._count,
            }


class TelemetryRegistry:
    """Named instruments + keyed collectors, rendered as Prometheus text."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Any] = {}
        self._collectors: dict[str, Callable[[], dict[str, float]]] = {}

    # --------------------------------------------------------- instruments
    def _get_or_create(self, name: str, kind: type, **kw: Any) -> Any:
        validate_metric_name(name)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, kind):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}, not {kind.__name__}"
                    )
                return existing
            metric = kind(name, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(
        self, name: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(name, Histogram, buckets=buckets)

    # ---------------------------------------------------------- collectors
    def register_collector(
        self, key: str, fn: Callable[[], dict[str, float]]
    ) -> None:
        """Register (or REPLACE — same key) a snapshot source. Keyed
        replacement is the rebinding story: a fresh ServerApp re-registers
        "server" and the closure over the closed one is gone."""
        with self._lock:
            self._collectors[key] = fn

    def unregister_collector(
        self, key: str, fn: Callable[[], dict[str, float]] | None = None
    ) -> None:
        """Remove a collector; with `fn`, only if it is still the one
        registered (a replaced source must not evict its replacement)."""
        with self._lock:
            if fn is None or self._collectors.get(key) == fn:
                self._collectors.pop(key, None)

    # -------------------------------------------------------------- output
    def snapshot(self) -> dict[str, Any]:
        """Every current value as one flat dict (histograms nested)."""
        with self._lock:
            metrics = dict(self._metrics)
            collectors = dict(self._collectors)
        out: dict[str, Any] = {}
        for name, metric in metrics.items():
            out[name] = (
                metric.snapshot()
                if isinstance(metric, Histogram)
                else metric.value
            )
        for key, fn in collectors.items():
            try:
                vals = fn()
            except Exception:
                continue  # a dead source must not break the scrape
            for name, value in (vals or {}).items():
                out[name] = value
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (v0.0.4): HELP/TYPE from
        KNOWN_METRICS, untyped for anything undeclared."""
        lines: list[str] = []
        snap = self.snapshot()
        for name in sorted(snap):
            value = snap[name]
            kind, help_ = _KNOWN.get(name, ("untyped", ""))
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            if isinstance(value, dict) and "buckets" in value:
                # bucket counts are already cumulative (observe()
                # increments every bucket whose bound >= value)
                for bound, count in sorted(value["buckets"].items()):
                    lines.append(f'{name}_bucket{{le="{bound}"}} {count}')
                lines.append(
                    f'{name}_bucket{{le="+Inf"}} {value["count"]}'
                )
                lines.append(f"{name}_sum {_fmt(value['sum'])}")
                lines.append(f"{name}_count {value['count']}")
            else:
                lines.append(f"{name} {_fmt(value)}")
        return "\n".join(lines) + "\n"


def _fmt(v: Any) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


REGISTRY = TelemetryRegistry()


# ------------------------------------------------- process-wide collectors
# The pre-existing stat islands, absorbed. Imports are lazy inside each
# collector so importing telemetry stays dependency-free; a collector for
# a module never imported reports its zeros by importing it then.


def _wire_collector() -> dict[str, float]:
    from vantage6_tpu.common.serialization import WIRE_STATS

    s = WIRE_STATS.snapshot()
    return {
        "v6t_wire_encode_calls_total": s["encode_calls"],
        "v6t_wire_encode_bytes_total": s["encode_bytes"],
        "v6t_wire_encode_seconds_total": s["encode_s"],
        "v6t_wire_decode_calls_total": s["decode_calls"],
        "v6t_wire_decode_bytes_total": s["decode_bytes"],
        "v6t_wire_decode_seconds_total": s["decode_s"],
        "v6t_wire_broadcasts_total": s["broadcasts"],
        "v6t_wire_broadcast_recipients_total": s["broadcast_recipients"],
        "v6t_wire_broadcast_dedup_hits_total": s["broadcast_dedup_hits"],
    }


def _rest_collector() -> dict[str, float]:
    from vantage6_tpu.common.rest import REST_STATS

    s = REST_STATS.snapshot()
    return {
        "v6t_rest_calls_total": s["calls"],
        "v6t_rest_errors_total": s["errors"],
        "v6t_rest_stale_retries_total": s["stale_retries"],
        "v6t_rest_bytes_sent_total": s["bytes_sent"],
        "v6t_rest_bytes_received_total": s["bytes_received"],
        "v6t_rest_seconds_total": s["seconds"],
    }


def _executor_collector() -> dict[str, float]:
    from vantage6_tpu.runtime.executor import _LIVE_POOLS

    pools = list(_LIVE_POOLS)
    return {
        "v6t_executor_pools": len(pools),
        "v6t_executor_inflight_items": sum(p.inflight for p in pools),
        "v6t_executor_capacity": sum(p.workers for p in pools),
    }


REGISTRY.register_collector("wire", _wire_collector)
REGISTRY.register_collector("rest", _rest_collector)
REGISTRY.register_collector("executor", _executor_collector)
