"""Fail-soft environment-variable parsing.

One home for the stance the observability plane takes on tuning knobs
(V6T_TRACE_SAMPLE, V6T_WATCHDOG_INTERVAL, V6T_FLIGHT_BUFFER, ...): a
typo'd value falls back to the documented default instead of killing
every process that imports the module — same contract as a malformed
traceparent being ignored, not fatal. Keeping the helpers here stops the
tracer/watchdog/flight copies drifting apart.
"""
from __future__ import annotations

import os


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        return default
