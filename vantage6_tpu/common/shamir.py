"""Shamir secret sharing over GF(256) — the recovery primitive of the
Bonawitz secure-aggregation protocol (common.secureagg_bonawitz).

Byte-wise (t, n) sharing: each byte of the secret is the constant term of an
independent degree-(t-1) polynomial over GF(2^8) (AES polynomial 0x11B);
share for party x is the polynomial evaluated at x (1-based — x=0 IS the
secret and is never issued). Any t shares reconstruct by Lagrange
interpolation at 0; fewer than t reveal nothing (every byte's remaining
polynomial is uniform). Vectorized over the secret's bytes with numpy table
lookups, so sharing a 32-byte seed among 64 parties is microseconds.

Original implementation of the textbook scheme (Shamir 1979); the reference
project has no counterpart (secure aggregation lives in its algorithm repos,
SURVEY.md §2.3).
"""
from __future__ import annotations

from typing import Mapping

import numpy as np

# ---------------------------------------------------------- GF(256) tables
_EXP = np.zeros(510, np.uint8)
_LOG = np.zeros(256, np.uint8)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    # multiply by the generator 3: x*2 (mod 0x11B) xor x
    _x2 = ((_x << 1) & 0xFF) ^ (0x1B if _x & 0x80 else 0)
    _x = _x2 ^ _x
_EXP[255:] = _EXP[:255]
del _x, _x2, _i


def _gf_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a = np.asarray(a, np.uint8)
    b = np.asarray(b, np.uint8)
    out = _EXP[_LOG[a].astype(np.int32) + _LOG[b].astype(np.int32)]
    return np.where((a == 0) | (b == 0), np.uint8(0), out)


def _gf_inv(a: np.ndarray) -> np.ndarray:
    a = np.asarray(a, np.uint8)
    if np.any(a == 0):
        raise ZeroDivisionError("GF(256) inverse of 0")
    return _EXP[255 - _LOG[a].astype(np.int32)]


# ------------------------------------------------------------------ scheme
def share_secret(
    secret: bytes, n: int, threshold: int, coeff_stream: bytes
) -> list[bytes]:
    """Split ``secret`` into ``n`` shares, any ``threshold`` of which
    reconstruct it. Returns share bytes for parties x = 1..n (callers map
    party index i -> share [i], i.e. x = i + 1).

    ``coeff_stream`` supplies the (t-1)*len(secret) random polynomial
    coefficient bytes. It MUST be uniformly random and secret (callers
    derive it from a keyed PRF — deterministic per station+tag, so the
    stateless protocol rounds re-derive identical shares); predictable
    coefficients collapse the scheme to plaintext.
    """
    if not 1 <= threshold <= n:
        raise ValueError(f"need 1 <= threshold({threshold}) <= n({n})")
    if n > 255:
        raise ValueError("GF(256) sharing supports at most 255 parties")
    m = len(secret)
    need = (threshold - 1) * m
    if len(coeff_stream) < need:
        raise ValueError(f"coeff_stream too short: {len(coeff_stream)} < {need}")
    sec = np.frombuffer(secret, np.uint8)
    coeffs = np.frombuffer(coeff_stream[:need], np.uint8).reshape(
        threshold - 1, m
    )
    shares = []
    for x in range(1, n + 1):
        xv = np.uint8(x)
        acc = np.zeros(m, np.uint8)
        for c in coeffs[::-1]:  # Horner: (((a_{t-1})x + a_{t-2})x + ...)x + s
            acc = _gf_mul(acc, xv) ^ c
        acc = _gf_mul(acc, xv) ^ sec
        shares.append(acc.tobytes())
    return shares


def reconstruct_secret(
    shares: Mapping[int, bytes], threshold: int
) -> bytes:
    """Lagrange-interpolate at 0 from ``shares`` (party index i -> share,
    evaluated at x = i + 1). Needs at least ``threshold`` entries; uses the
    first ``threshold`` in index order (any subset works)."""
    if len(shares) < threshold:
        raise ValueError(
            f"need {threshold} shares to reconstruct, have {len(shares)}"
        )
    picked = sorted(shares.items())[:threshold]
    xs = [np.uint8(i + 1) for i, _ in picked]
    m = len(picked[0][1])
    out = np.zeros(m, np.uint8)
    for a, (i, share) in enumerate(picked):
        y = np.frombuffer(share, np.uint8)
        if len(y) != m:
            raise ValueError("inconsistent share lengths")
        # l_a(0) = prod_{b != a} x_b / (x_b ^ x_a)
        num = np.uint8(1)
        den = np.uint8(1)
        for b2, x_b in enumerate(xs):
            if b2 == a:
                continue
            num = _gf_mul(num, x_b)
            den = _gf_mul(den, x_b ^ xs[a])
        out ^= _gf_mul(y, _gf_mul(num, _gf_inv(den)))
    return out.tobytes()
