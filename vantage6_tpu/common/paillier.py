"""Paillier additively-homomorphic encryption (host-side, pure python).

Parity: the reference's secure-sum story is Paillier inside algorithm repos
(SURVEY.md §2.3 "secure aggregation"; §7 hard part 2). Homomorphic bigint is
the wrong tool on an MXU, so the TPU-native fast path is additive masking
(fed.collectives.secure_sum on-pod, vantage6_tpu.native cross-host) — and
THIS module exists so the two can be proven equivalent: the parity tests in
tests/test_paillier.py aggregate the same quantized vectors through both
paths and compare exactly. It is also a complete, usable implementation for
deployments that require the classical scheme (station encrypts, untrusted
server adds ciphertexts, only the key holder decrypts the sum).

Scheme (Paillier 1999), with the standard g = n + 1 simplification:
  keygen:  n = p*q (p, q safe-size primes), λ = lcm(p-1, q-1), μ = λ⁻¹ mod n
  encrypt: c = (1 + m·n) · rⁿ  mod n²       (r random in Z*_n)
  add:     c₁·c₂ mod n²  decrypts to m₁+m₂  (the homomorphism)
  decrypt: m = L(c^λ mod n²) · μ mod n,  L(x) = (x-1)/n

Signed values are encoded into Z_n by wrap-around: plaintexts in
(-n/2, n/2) survive any number of additions that keep the true sum inside
that range — the same fixed-point contract as the masking path's int32.

Security note: textbook Paillier is IND-CPA under DCRA; this implementation
targets correctness/parity, uses `secrets` for all randomness, and does NOT
attempt side-channel hardening (python bigints are not constant-time).
"""
from __future__ import annotations

import dataclasses
import math
import secrets
from typing import Iterable, Sequence

import numpy as np

# Miller-Rabin rounds: error < 4^-64 per prime, plenty beyond any test need.
_MR_ROUNDS = 64

_SMALL_PRIMES = [
    2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53, 59, 61, 67,
    71, 73, 79, 83, 89, 97, 101, 103, 107, 109, 113, 127, 131, 137, 139, 149,
]


def _is_probable_prime(n: int) -> bool:
    if n < 2:
        return False
    for p in _SMALL_PRIMES:
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for _ in range(_MR_ROUNDS):
        a = secrets.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = (x * x) % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _random_prime(bits: int) -> int:
    while True:
        cand = secrets.randbits(bits) | (1 << (bits - 1)) | 1
        if _is_probable_prime(cand):
            return cand


@dataclasses.dataclass(frozen=True)
class PublicKey:
    n: int

    @property
    def n_sq(self) -> int:
        return self.n * self.n

    @property
    def max_abs_plaintext(self) -> int:
        """Signed plaintexts must stay strictly inside ±n/2."""
        return self.n // 2

    def encrypt(self, m: int, r: int | None = None) -> int:
        """Encrypt a signed int; r (blinding) is drawn from Z*_n if omitted."""
        m = int(m)
        if abs(m) >= self.max_abs_plaintext:
            raise ValueError(
                f"plaintext magnitude {m} outside ±n/2 — pick a larger key "
                "or smaller fixed-point scale"
            )
        n, n_sq = self.n, self.n_sq
        if r is None:
            while True:
                r = secrets.randbelow(n - 1) + 1
                if math.gcd(r, n) == 1:
                    break
        elif not (0 < r < n) or math.gcd(r, n) != 1:
            raise ValueError("r must be in Z*_n")
        # g = n+1 => g^m = 1 + m*n (mod n^2): one mulmod instead of a powmod
        return ((1 + (m % n) * n) % n_sq) * pow(r, n, n_sq) % n_sq

    def add(self, c1: int, c2: int) -> int:
        """Ciphertext of m1 + m2."""
        return (c1 * c2) % self.n_sq

    def add_plain(self, c: int, m: int) -> int:
        """Ciphertext of m_c + m (no fresh blinding needed for parity use)."""
        return c * (1 + (int(m) % self.n) * self.n) % self.n_sq

    def mul_plain(self, c: int, k: int) -> int:
        """Ciphertext of k * m_c (k signed)."""
        k = int(k) % self.n
        return pow(c, k, self.n_sq)

    def encrypt_vector(self, values: Iterable[int]) -> list[int]:
        return [self.encrypt(int(v)) for v in values]

    def add_vectors(self, a: Sequence[int], b: Sequence[int]) -> list[int]:
        if len(a) != len(b):
            raise ValueError("length mismatch")
        return [self.add(x, y) for x, y in zip(a, b)]


@dataclasses.dataclass(frozen=True)
class PrivateKey:
    public: PublicKey
    lam: int   # λ = lcm(p-1, q-1)
    mu: int    # λ⁻¹ mod n

    def decrypt(self, c: int) -> int:
        """Decrypt to a SIGNED int in (-n/2, n/2]."""
        n, n_sq = self.public.n, self.public.n_sq
        if not (0 < c < n_sq):
            raise ValueError("ciphertext out of range")
        m = ((pow(c, self.lam, n_sq) - 1) // n) * self.mu % n
        return m - n if m > n // 2 else m

    def decrypt_vector(self, cts: Iterable[int]) -> list[int]:
        return [self.decrypt(c) for c in cts]


def keygen(bits: int = 2048) -> tuple[PublicKey, PrivateKey]:
    """Generate a keypair with an n of ~`bits` bits.

    512 is fine for tests; use >= 2048 for anything real.
    """
    if bits < 64:
        raise ValueError("key too small to be meaningful")
    while True:
        p = _random_prime(bits // 2)
        q = _random_prime(bits - bits // 2)
        if p != q:
            n = p * q
            if n.bit_length() >= bits:
                break
    lam = (p - 1) * (q - 1) // math.gcd(p - 1, q - 1)
    pk = PublicKey(n=n)
    return pk, PrivateKey(public=pk, lam=lam, mu=pow(lam, -1, n))


# ----------------------------------------------------- fixed-point vectors
# The same quantization contract as the masking path (vantage6_tpu.native):
# float -> round(x * scale) as exact ints, so a Paillier-aggregated sum and a
# masking-aggregated sum of identical inputs are EQUAL integers, not merely
# close floats — that equality is what the parity tests assert.


def quantize(x: np.ndarray, scale: float) -> list[int]:
    """np.rint fixed-point, matching native.quantize bit-for-bit (then lifted
    to python ints, where Paillier has no 32-bit wrap to worry about)."""
    return [int(v) for v in np.rint(
        np.ascontiguousarray(x, np.float32) * np.float32(scale)
    ).astype(np.int64)]


def dequantize(values: Sequence[int], scale: float) -> np.ndarray:
    return (np.asarray(values, np.float64) / float(scale)).astype(np.float32)


def secure_sum_paillier(
    pk: PublicKey,
    sk: PrivateKey,
    station_vectors: Sequence[np.ndarray],
    scale: float = 2.0**16,
) -> np.ndarray:
    """Reference-shaped secure sum: each station encrypts its quantized
    vector; the (untrusted) aggregator multiplies ciphertexts element-wise;
    only the key holder decrypts the total. Returns the dequantized sum."""
    if not station_vectors:
        raise ValueError("no stations")
    encrypted = [
        pk.encrypt_vector(quantize(np.asarray(v), scale))
        for v in station_vectors
    ]
    agg = encrypted[0]
    for ct in encrypted[1:]:
        agg = pk.add_vectors(agg, ct)   # the aggregator's entire job
    return dequantize(sk.decrypt_vector(agg), scale)
