"""Flight recorder: bounded in-process rings of recent activity + crash dump.

A failed federated round used to be diagnosable only by re-running it
under `V6T_TRACE` with a JSONL sink configured — the evidence of the
FIRST failure was gone. This module keeps the evidence, always:

- **Rings** — every process holds bounded deques of its recent activity:
  log records (tapped by `common.log`'s `_FlightTapHandler`), finished
  spans (a `runtime.tracing` tap, registered on import), free-form
  ops notes (REST failures, event-poll errors, watchdog alerts — see
  :meth:`FlightRecorder.note`), and telemetry snapshots (the watchdog
  appends one per evaluation). Appends are O(1) deque pushes; the rings
  cost memory, never latency.
- **Dump** — :meth:`FlightRecorder.dump` serializes everything into ONE
  JSONL bundle (`{"type": "log"|"span"|"note"|"metrics"|...}` per line)
  plus a fresh telemetry snapshot and, when a watchdog is live, its
  active alerts. Triggered by: a fatal error (sys/threading excepthook,
  via :func:`install`), `kill -USR2` (same), `POST /api/debug/dump` on
  the server, or an explicit call.
- **Doctor** — `tools/doctor.py` merges a bundle into one correlated
  timeline: logs interleaved with spans by trace_id/wall-clock, alerts
  explained against the watchdog rule catalog.

Env knobs: `V6T_FLIGHT_DIR` (bundle directory, default the system temp
dir), `V6T_FLIGHT_BUFFER` (per-ring capacity, default 2048).
"""
from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
from collections import deque
from typing import Any

from vantage6_tpu.common.env import env_int


class FlightRecorder:
    """Per-process bounded recording of logs, spans, notes and metrics."""

    def __init__(self, capacity: int | None = None):
        cap = max(64, capacity if capacity is not None
                  else env_int("V6T_FLIGHT_BUFFER", 2048))
        self.capacity = cap
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._logs: deque[dict[str, Any]] = deque(maxlen=cap)
        self._spans: deque[dict[str, Any]] = deque(maxlen=cap)
        self._notes: deque[dict[str, Any]] = deque(maxlen=cap)
        # metric snapshots are heavyweight relative to the others: a much
        # smaller ring still gives the dump a before/after trajectory
        self._metrics: deque[dict[str, Any]] = deque(maxlen=max(8, cap // 64))
        self.service = os.environ.get("V6T_TRACE_SERVICE", "v6t")
        self.dumps_written = 0
        self.dump_errors = 0

    # -------------------------------------------------------------- feeders
    def record_log(self, rec: dict[str, Any]) -> None:
        with self._lock:
            self._logs.append(rec)

    def record_span(self, rec: dict[str, Any]) -> None:
        with self._lock:
            self._spans.append(rec)

    def note(self, kind: str, **fields: Any) -> None:
        """Record one ops event (REST failure, event-poll error, alert
        transition, request anomaly). `kind` is a short snake_case tag the
        doctor groups by."""
        rec = {"ts": time.time(), "kind": kind, **fields}
        with self._lock:
            self._notes.append(rec)

    def snapshot_metrics(self, snap: dict | None = None) -> None:
        """Append a unified-telemetry snapshot to the metrics ring (the
        watchdog calls this once per evaluation, giving dumps a short
        metric history, not just the final state). Pass ``snap`` to reuse
        an already-taken snapshot — every collector callback runs under
        its component's lock, so a caller that just snapshotted should
        not pay (or inflict) that twice per tick."""
        if snap is None:
            try:
                from vantage6_tpu.common.telemetry import REGISTRY

                snap = REGISTRY.snapshot()
            except Exception:  # pragma: no cover - must not break taps
                return
        with self._lock:
            self._metrics.append({"ts": time.time(), "values": snap})

    # ------------------------------------------------------------- consumers
    def recent_notes(
        self, since: float = 0.0, limit: int = 256
    ) -> list[dict[str, Any]]:
        """Notes strictly newer than ``since`` (oldest-first, bounded) —
        the fleet push path's delta read. The rings stay private; this is
        the one sanctioned incremental reader beside dump()."""
        with self._lock:
            out = [r for r in self._notes if r.get("ts", 0.0) > since]
        return out[-limit:]

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "logs": len(self._logs),
                "spans": len(self._spans),
                "notes": len(self._notes),
                "metrics": len(self._metrics),
                "dumps_written": self.dumps_written,
                "dump_errors": self.dump_errors,
            }

    def clear(self) -> None:
        with self._lock:
            for ring in (self._logs, self._spans, self._notes, self._metrics):
                ring.clear()

    def dump(
        self,
        path: str | None = None,
        reason: str = "manual",
        detail: str = "",
    ) -> str | None:
        """Write the bundle; returns its path, or None when even the dump
        failed (counted — a recorder that cannot write must not crash the
        crashing process it is documenting)."""
        if path is None:
            base = os.environ.get("V6T_FLIGHT_DIR") or None
            if base is None:
                import tempfile

                base = tempfile.gettempdir()
            os.makedirs(base, exist_ok=True)
            safe_service = re.sub(r"[^A-Za-z0-9._-]+", "_", self.service)
            path = os.path.join(
                base,
                f"v6t-flight-{safe_service}-{os.getpid()}-"
                f"{int(time.time() * 1000)}-{reason}.jsonl",
            )
        with self._lock:
            logs = list(self._logs)
            spans = list(self._spans)
            notes = list(self._notes)
            metrics = list(self._metrics)
        records: list[dict[str, Any]] = [{
            "type": "flight_header",
            "ts": time.time(),
            "service": self.service,
            "pid": os.getpid(),
            "reason": reason,
            "detail": detail,
            "counts": {
                "log": len(logs), "span": len(spans), "note": len(notes),
                "metrics": len(metrics),
            },
        }]
        records += [{"type": "log", **r} for r in logs]
        records += [{"type": "span", **r} for r in spans]
        records += [{"type": "note", **r} for r in notes]
        records += [{"type": "metrics", **r} for r in metrics]
        # final-state extras, best-effort: a fresh telemetry snapshot and
        # the watchdog's alert state (only when those modules are live —
        # the recorder itself depends on neither)
        try:
            from vantage6_tpu.common.telemetry import REGISTRY

            records.append({
                "type": "metrics", "ts": time.time(), "final": True,
                "values": REGISTRY.snapshot(),
            })
        except Exception:
            pass
        try:
            from vantage6_tpu.runtime import watchdog as _wd

            for alert in _wd.WATCHDOG.active_alerts():
                records.append({"type": "alert", **alert})
        except Exception:
            pass
        # learning-plane final state: each tracked task's convergence
        # summary (the per-round evidence rides the notes ring; this
        # survives even when the ring evicted the early rounds) — what
        # the doctor's learning digest anchors its trajectory on
        try:
            from vantage6_tpu.runtime.learning import LEARNING

            for summary in LEARNING.summaries():
                if summary.get("rounds"):
                    records.append({
                        "type": "learning", "ts": time.time(), **summary,
                    })
        except Exception:
            pass
        try:
            with open(path, "w") as fh:
                for rec in records:
                    fh.write(json.dumps(rec, default=str) + "\n")
        except OSError:
            with self._lock:
                self.dump_errors += 1
            return None
        with self._lock:
            self.dumps_written += 1
        return path


FLIGHT = FlightRecorder()


def read_bundle(path: str) -> list[dict[str, Any]]:
    """Read a dump bundle, skipping blank/torn lines (same stance as
    `tracing.read_spans`: a dump interrupted mid-write must still yield
    the records that DID land)."""
    out: list[dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "type" in rec:
                out.append(rec)
    return out


# ------------------------------------------------------- process-level hooks

_INSTALL_LOCK = threading.Lock()
_installed = False
_service_named = False
_usr2_armed = False


def install(service: str | None = None) -> FlightRecorder:
    """Arm the process-level dump triggers (idempotent). The FIRST caller
    to pass a service names the process-wide recorder; later installers
    (e.g. daemons started inside a server process in tests/benches) keep
    the original label instead of last-writer-wins mislabeling bundles.

    - `sys.excepthook` / `threading.excepthook`: dump on any uncaught
      exception, then chain to the previous hook — the bundle exists
      BEFORE the traceback scrolls away.
    - `SIGUSR2`: dump on demand from outside (`kill -USR2 <pid>`), the
      classic "what is this process doing right now" probe. Skipped
      quietly off the main thread or on platforms without the signal —
      and retried on the next install() call, so a background-thread
      first installer (a daemon starting inside an embedder) doesn't
      permanently disarm the probe for a later main-thread one.

    Servers arm this in `run_server`, daemons in `NodeDaemon.start`; bare
    library use stays un-hooked unless the embedder opts in.
    """
    global _installed, _service_named, _usr2_armed
    if service:
        with _INSTALL_LOCK:
            if not _service_named:
                FLIGHT.service = service
                _service_named = True
    with _INSTALL_LOCK:
        if not _usr2_armed:
            try:
                import signal

                def _usr2(_signum, _frame):
                    # dump from a WORKER thread: the handler interrupts
                    # the main thread between bytecodes, possibly inside
                    # record_log/note with the non-reentrant FLIGHT._lock
                    # held — dumping inline would deadlock the very
                    # process the probe is meant to diagnose
                    threading.Thread(
                        target=lambda: FLIGHT.dump(reason="sigusr2"),
                        daemon=True, name="v6t-flight-usr2",
                    ).start()

                signal.signal(signal.SIGUSR2, _usr2)
                _usr2_armed = True
            except (ValueError, AttributeError, OSError):
                # not the main thread, or no SIGUSR2 on this platform
                pass
        if _installed:
            return FLIGHT
        _installed = True

        prev_excepthook = sys.excepthook

        def _fatal_hook(exc_type, exc, tb):
            try:
                FLIGHT.dump(
                    reason="fatal",
                    detail=f"{exc_type.__name__}: {exc}",
                )
            except Exception:
                pass
            prev_excepthook(exc_type, exc, tb)

        sys.excepthook = _fatal_hook

        prev_thread_hook = threading.excepthook

        def _thread_hook(args):
            # SystemExit from a worker is shutdown, not a crash
            if args.exc_type is not SystemExit:
                try:
                    FLIGHT.dump(
                        reason="thread-fatal",
                        detail=(
                            f"{args.exc_type.__name__}: {args.exc_value} "
                            f"in {getattr(args.thread, 'name', '?')}"
                        ),
                    )
                except Exception:
                    pass
            prev_thread_hook(args)

        threading.excepthook = _thread_hook
    return FLIGHT


# ------------------------------------------------------------------ wiring
# span tap: every finished span joins the ring (keyed — a reload replaces
# itself instead of double-recording)
try:
    from vantage6_tpu.runtime.tracing import TRACER as _TRACER

    _TRACER.add_tap("flight", FLIGHT.record_span)
except Exception:  # pragma: no cover - tracing must stay optional here
    pass


def _flight_collector() -> dict[str, float]:
    s = FLIGHT.stats()
    return {
        "v6t_flight_records": float(
            s["logs"] + s["spans"] + s["notes"] + s["metrics"]
        ),
        "v6t_flight_dumps_total": float(s["dumps_written"]),
    }


try:
    from vantage6_tpu.common.telemetry import REGISTRY as _REGISTRY

    _REGISTRY.register_collector("flight", _flight_collector)
except Exception:  # pragma: no cover
    pass
