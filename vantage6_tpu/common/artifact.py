"""Algorithm artifact naming: parse/compare image-style references.

Parity: vantage6-common docker addons (SURVEY.md §2 item 25) — the reference
addresses algorithms by Docker image reference and checks digests before
running. Here an algorithm *artifact* keeps the same reference grammar
(``[registry/]name[:tag][@sha256:digest]``) but names a registered algorithm
module/package; digest checking becomes content-hash verification of the
registered code object or wheel.
"""
from __future__ import annotations

import dataclasses
import hashlib
import re

_REF_RE = re.compile(
    r"^(?:(?P<registry>[\w.\-]+(?::\d+)?)/)?"
    r"(?P<name>[a-z0-9][a-z0-9._\-/]*?)"
    r"(?::(?P<tag>[\w.\-]+))?"
    r"(?:@(?P<digest>sha256:[0-9a-f]{64}))?$"
)


@dataclasses.dataclass(frozen=True)
class ArtifactRef:
    """A parsed algorithm reference."""

    registry: str
    name: str
    tag: str
    digest: str  # "" or "sha256:<hex>"

    @property
    def full(self) -> str:
        s = f"{self.registry}/{self.name}" if self.registry else self.name
        if self.tag:
            s += f":{self.tag}"
        if self.digest:
            s += f"@{self.digest}"
        return s

    @property
    def without_digest(self) -> str:
        s = f"{self.registry}/{self.name}" if self.registry else self.name
        return f"{s}:{self.tag}" if self.tag else s


def parse_ref(ref: str) -> ArtifactRef:
    m = _REF_RE.match(ref)
    if not m:
        raise ValueError(f"invalid algorithm reference {ref!r}")
    d = m.groupdict()
    # "host.tld/name" vs "name:tag" ambiguity: a registry must contain a dot
    # or a port, like docker's own heuristic.
    registry = d["registry"] or ""
    name = d["name"]
    if registry and "." not in registry and ":" not in registry:
        name = f"{registry}/{name}"
        registry = ""
    return ArtifactRef(
        registry=registry,
        name=name,
        tag=d["tag"] or "",
        digest=d["digest"] or "",
    )


def content_digest(blob: bytes) -> str:
    """sha256 content digest in reference format."""
    return "sha256:" + hashlib.sha256(blob).hexdigest()


def digests_match(ref: str, blob: bytes) -> bool:
    """True when `ref` pins no digest or pins the digest of `blob`."""
    parsed = parse_ref(ref)
    return not parsed.digest or parsed.digest == content_digest(blob)


def same_artifact(a: str, b: str) -> bool:
    """Do two references address the same artifact (ignoring digests)?"""
    pa, pb = parse_ref(a), parse_ref(b)
    return (pa.registry, pa.name, pa.tag or "latest") == (
        pb.registry,
        pb.name,
        pb.tag or "latest",
    )
