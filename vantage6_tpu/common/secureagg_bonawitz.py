"""Dropout-recoverable secure aggregation — the full Bonawitz construction.

Upgrades `common.secureagg_dh` (per-pair X25519 masks, honest-but-curious
aggregator) with the two missing properties of Bonawitz et al., CCS'17
("Practical Secure Aggregation for Privacy-Preserving Machine Learning"),
the protocol class SURVEY.md:158 cites for this subsystem:

1. **Dropout recovery.** Every station Shamir-shares (common.shamir) the
   seed of its per-aggregation X25519 key among its peers. If a station
   advertises but never uploads, any `threshold` surviving peers can hand
   the aggregator the shares of THAT station's seed; the aggregator
   reconstructs its pairwise seeds and strips the orphaned masks, so the
   survivor-set sum completes instead of the round being garbage.
2. **The double mask.** Each station also adds a personal self-mask `b_i`
   (its seed equally Shamir-shared). For *survivors*, peers reveal the
   `b_i` shares (so self-masks can be removed from the total); for
   *dropped* stations they reveal the key-seed shares. A peer never
   reveals both for the same station — otherwise a lying aggregator could
   claim "station i dropped" AFTER receiving i's upload, strip i's
   pairwise masks, and read its plaintext. With the double mask, stripping
   the pairwise masks of a station that actually uploaded still leaves its
   self-mask in place.

Transport: the protocol is three task rounds through the normal control
plane (advertise [+signature — secureagg_dh.sign_advert], share, upload),
plus one reveal round among survivors on dropout. Share blobs relayed by
the server are encrypted to their recipient with a key only that pair can
derive (X25519 -> HMAC -> ChaCha20, authenticated with HMAC-SHA256/16) —
the relay sees nothing, exactly as it sees nothing of the masks.

All derivations are deterministic from (station_secret, tag): stateless
task rounds re-derive identical keys, shares and masks, like the rest of
the DH path. The per-aggregation `tag` domain-separates everything.
"""
from __future__ import annotations

import hashlib
import hmac
from typing import Iterable, Mapping

import numpy as np

from vantage6_tpu import native
from vantage6_tpu.common import shamir
from vantage6_tpu.common.secureagg_dh import (
    derive_keypair,
    keypair_from_ikm,
    keypair_ikm,
    mask_update_dh,
    pairwise_seed,
    _tag_bytes,
)

#: nonce peer-index for a station's SELF mask stream (never a real station)
_SELF = 0xFFFFFFFF
_MAC_LEN = 16


def default_threshold(n: int) -> int:
    """Majority threshold: tolerates up to n - (n//2 + 1) colluding-or-lost
    parties, the standard Bonawitz operating point."""
    return n // 2 + 1


def _check_threshold(n: int, t: int) -> int:
    """The 'never reveal both' invariant only holds for t > n/2: two
    disjoint groups of >= t stations cannot then exist, so a lying
    aggregator cannot collect t self-mask shares from one group AND t
    key-seed shares from another for the same uploaded station. Reject any
    weaker threshold at every share/reveal/recover entry point."""
    if not n // 2 < t <= n:
        raise ValueError(
            f"threshold {t} violates n//2 < t <= n (n={n}): a minority "
            "threshold lets a lying aggregator unmask an honest upload"
        )
    return t


def selfmask_seed(station_secret: bytes, tag) -> bytes:
    if len(station_secret) < 16:
        raise ValueError("station secret must be >= 16 bytes")
    return hmac.new(
        station_secret, b"v6t-selfmask-v1:" + _tag_bytes(tag), hashlib.sha256
    ).digest()


def _coeff_stream(station_secret: bytes, tag, purpose: bytes, n: int) -> bytes:
    """Deterministic uniform bytes for Shamir coefficients (keyed PRF)."""
    key = hmac.new(
        station_secret,
        b"v6t-shamir-coeff-v1:" + purpose + b":" + _tag_bytes(tag),
        hashlib.sha256,
    ).digest()
    words = native.chacha20_stream(key, bytes(12), (n + 3) // 4)
    return words.astype("<u4").tobytes()[:n]


def _wrap_key(pair_seed: bytes) -> bytes:
    return hmac.new(
        pair_seed, b"v6t-share-wrap-v1", hashlib.sha256
    ).digest()


def _xor_stream(key: bytes, nonce: bytes, data: bytes) -> bytes:
    words = native.chacha20_stream(key, nonce, (len(data) + 3) // 4)
    ks = words.astype("<u4").tobytes()[: len(data)]
    return bytes(a ^ b for a, b in zip(data, ks))


def _seal(pair_seed: bytes, i: int, j: int, data: bytes) -> bytes:
    """Encrypt-then-MAC `data` from station i to station j."""
    key = _wrap_key(pair_seed)
    ct = _xor_stream(key, native.pair_nonce(i, j), data)
    mac = hmac.new(key, b"%d:%d:" % (i, j) + ct, hashlib.sha256).digest()
    return ct + mac[:_MAC_LEN]


def _open(pair_seed: bytes, i: int, j: int, blob: bytes) -> bytes:
    key = _wrap_key(pair_seed)
    ct, mac = blob[:-_MAC_LEN], blob[-_MAC_LEN:]
    want = hmac.new(key, b"%d:%d:" % (i, j) + ct, hashlib.sha256).digest()
    if not hmac.compare_digest(mac, want[:_MAC_LEN]):
        raise ValueError(f"share blob from station {i} failed authentication")
    return _xor_stream(key, native.pair_nonce(i, j), ct)


# ------------------------------------------------------------------ station
def make_recovery_shares(
    station_secret: bytes,
    station: int,
    pubkeys: Mapping[int, str],
    tag,
    threshold: int | None = None,
) -> dict[int, str]:
    """Round 2 (after adverts): this station's encrypted share blobs.

    Returns {peer index -> hex blob}; each blob holds the peer's Shamir
    share of BOTH this station's X25519 key seed and its self-mask seed,
    sealed to that peer. Relayed through the server like any task result.
    """
    pubs = dict(pubkeys)
    n = len(pubs)
    t = _check_threshold(
        n, default_threshold(n) if threshold is None else threshold
    )
    priv, _ = derive_keypair(station_secret, tag)
    ikm = keypair_ikm(station_secret, tag)
    b_seed = selfmask_seed(station_secret, tag)
    order = sorted(pubs)  # share x-coordinate = 1 + rank in station order
    coeff_len = (t - 1) * 32
    priv_shares = shamir.share_secret(
        ikm, n, t, _coeff_stream(station_secret, tag, b"priv", coeff_len or 1)
    )
    b_shares = shamir.share_secret(
        b_seed, n, t, _coeff_stream(station_secret, tag, b"self", coeff_len or 1)
    )
    out: dict[int, str] = {}
    for rank, peer in enumerate(order):
        if peer == station:
            continue
        seed = pairwise_seed(priv, pubs[peer], station, peer, tag)
        blob = _seal(
            seed, station, peer, priv_shares[rank] + b_shares[rank]
        )
        out[peer] = blob.hex()
    return out


def mask_update_bonawitz(
    station_secret: bytes,
    station: int,
    pubkeys: Mapping[int, str],
    values: np.ndarray,
    scale: float = 2.0**16,
    tag=b"",
    identities: Mapping[int, str] | None = None,
    signatures: Mapping[int, str] | None = None,
) -> np.ndarray:
    """Round 3: the double-masked upload = quantize(values) + b_i stream
    + sum of signed pairwise streams (all mod 2^32)."""
    masked = mask_update_dh(
        station_secret, station, pubkeys, values, scale, tag,
        identities=identities, signatures=signatures,
    )
    b_seed = selfmask_seed(station_secret, tag)
    stream = native.chacha20_stream(
        b_seed, native.pair_nonce(station, _SELF), masked.size
    )
    with np.errstate(over="ignore"):
        out = masked.reshape(-1).astype(np.uint32) + stream
    return out.astype(np.int32).reshape(masked.shape)


def reveal_for_recovery(
    station_secret: bytes,
    station: int,
    pubkeys: Mapping[int, str],
    blobs_from: Mapping[int, str],
    survivors: Iterable[int],
    tag,
    threshold: int | None = None,
) -> dict[int, tuple[str, str]]:
    """Round 4 (run by each surviving station): open the share blobs peers
    sent me and reveal, per origin station, EITHER its self-mask share
    (origin survived — lets the aggregator strip self-masks) OR its key-seed
    share (origin dropped — lets the aggregator strip orphaned pairwise
    masks). Never both: that invariant is what stops a lying aggregator
    from unmasking an upload it already holds.

    Returns {origin -> ("b" | "priv", share hex)}.
    """
    pubs = dict(pubkeys)
    live = set(survivors)
    if station not in live:
        raise ValueError("a dropped station cannot run the reveal round")
    priv, _ = derive_keypair(station_secret, tag)
    out: dict[int, tuple[str, str]] = {}
    for origin, blob_hex in blobs_from.items():
        if origin == station:
            continue
        seed = pairwise_seed(priv, pubs[origin], origin, station, tag)
        data = _open(seed, origin, station, bytes.fromhex(blob_hex))
        priv_share, b_share = data[:32], data[32:64]
        if origin in live:
            out[origin] = ("b", b_share.hex())
        else:
            out[origin] = ("priv", priv_share.hex())
    # also reveal MY OWN self-mask share (re-derived — my blob to myself was
    # never sent): without it a survivor's b has only n_surv - 1 shares and
    # majority thresholds become unrecoverable after a single dropout. A
    # survivor revealing its own b-share is safe — b_me is *meant* to be
    # stripped from the total once my upload is in.
    n = len(pubs)
    t = _check_threshold(
        n, default_threshold(n) if threshold is None else threshold
    )
    order = sorted(pubs)
    my_rank = order.index(station)
    coeff_len = (t - 1) * 32
    own_b_shares = shamir.share_secret(
        selfmask_seed(station_secret, tag), n, t,
        _coeff_stream(station_secret, tag, b"self", coeff_len or 1),
    )
    out[station] = ("b", own_b_shares[my_rank].hex())
    return out


# --------------------------------------------------------------- aggregator
def recover_sum(
    uploads: Mapping[int, np.ndarray],
    pubkeys: Mapping[int, str],
    reveals: Mapping[int, Mapping[int, tuple[str, str]]],
    tag,
    threshold: int | None = None,
    scale: float = 2.0**16,
) -> np.ndarray:
    """The aggregator's recovery: exact sum of the SURVIVORS' values.

    uploads:  {station -> double-masked int32 vector} (survivor set)
    reveals:  {revealing station -> its reveal_for_recovery output}
    Works with zero dropouts too (then it only strips self-masks), so this
    is THE unmasking entry point for the Bonawitz path.
    """
    pubs = dict(pubkeys)
    n = len(pubs)
    t = _check_threshold(
        n, default_threshold(n) if threshold is None else threshold
    )
    order = sorted(pubs)
    rank = {s: r for r, s in enumerate(order)}
    survivors = sorted(uploads)
    dropped = sorted(set(pubs) - set(uploads))
    if len(survivors) < t:
        raise ValueError(
            f"only {len(survivors)} survivors < threshold {t}: unrecoverable"
        )

    # collect shares per origin, enforcing the either/or invariant
    b_shares: dict[int, dict[int, bytes]] = {s: {} for s in survivors}
    priv_shares: dict[int, dict[int, bytes]] = {d: {} for d in dropped}
    for revealer, per_origin in reveals.items():
        for origin, (kind, share_hex) in per_origin.items():
            share = bytes.fromhex(share_hex)
            if kind == "b":
                if origin in dropped:
                    continue  # useless: dropped stations need priv shares
                b_shares[origin][rank[revealer]] = share
            elif kind == "priv":
                if origin in uploads:
                    raise ValueError(
                        f"station {revealer} revealed the KEY share of "
                        f"surviving station {origin} — protocol violation "
                        "(would let the aggregator unmask an upload); abort"
                    )
                priv_shares[origin][rank[revealer]] = share
            else:
                raise ValueError(f"unknown reveal kind {kind!r}")

    stacked = np.stack([np.asarray(uploads[s]) for s in survivors])
    total = native.sum_wrapping(stacked)
    size = total.size
    flat = total.reshape(-1).astype(np.uint32)

    with np.errstate(over="ignore"):
        # 1) strip survivors' self-masks (reconstructed b_i)
        for s in survivors:
            seed = shamir.reconstruct_secret(b_shares[s], t)
            flat = flat - native.chacha20_stream(
                seed, native.pair_nonce(s, _SELF), size
            )
        # 2) strip dropped stations' orphaned pairwise masks: survivor u
        #    added sign(u, d) * stream_{u,d} that d never cancelled
        for d in dropped:
            ikm = shamir.reconstruct_secret(priv_shares[d], t)
            priv_d, pub_d_hex = keypair_from_ikm(ikm)
            if pub_d_hex != pubs[d]:
                raise ValueError(
                    f"reconstructed key for dropped station {d} does not "
                    "match its advert — bad shares or tampered advert"
                )
            for u in survivors:
                lo, hi = min(u, d), max(u, d)
                seed = pairwise_seed(priv_d, pubs[u], lo, hi, tag)
                stream = native.chacha20_stream(
                    seed, native.pair_nonce(lo, hi), size
                )
                # u contributed +stream if u == lo else -stream; remove it
                flat = flat - stream if u == lo else flat + stream
    return native.dequantize(
        flat.astype(np.int32).reshape(total.shape), scale
    )
