"""Shared REST session: bearer auth, error mapping, refresh, pagination.

One HTTP wrapper for every client in the stack (UserClient, NodeDaemon,
RestAlgorithmClient) so wire behavior — bearer header, JSON-or-empty bodies,
>=400 error mapping, 401 refresh retry, page draining — lives in one place.
(The node proxy is a *relay*, not a client: it forwards foreign tokens
verbatim and keeps its own thin forwarding code.)
"""
from __future__ import annotations

from typing import Any, Callable

import requests


class RestError(RuntimeError):
    """Server returned an error status."""

    def __init__(self, status: int, msg: str):
        super().__init__(f"HTTP {status}: {msg}")
        self.status = status
        self.msg = msg


class RestSession:
    """``request()`` + ``paginate()`` against one base URL.

    ``refresh`` (optional) is called on a 401; returning True retries the
    request once with whatever new token ``token_getter`` now yields.
    """

    def __init__(
        self,
        base_url: str,
        token_getter: Callable[[], str | None] = lambda: None,
        refresh: Callable[[], bool] | None = None,
    ):
        self.base_url = base_url.rstrip("/")
        self._token_getter = token_getter
        self._refresh = refresh
        self._session = requests.Session()

    def request(
        self,
        method: str,
        endpoint: str,
        json_body: Any = None,
        params: dict[str, Any] | None = None,
        _retry: bool = True,
    ) -> Any:
        headers = {}
        token = self._token_getter()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        resp = self._session.request(
            method,
            f"{self.base_url}/api/{endpoint.lstrip('/')}",
            json=json_body,
            params=params,
            headers=headers,
        )
        if (
            resp.status_code == 401
            and _retry
            and self._refresh is not None
            and self._refresh()
        ):
            return self.request(method, endpoint, json_body, params, False)
        body = resp.json() if resp.content else {}
        if resp.status_code >= 400:
            raise RestError(resp.status_code, body.get("msg", resp.text))
        return body

    def paginate(
        self, endpoint: str, params: dict[str, Any] | None = None
    ) -> list[dict[str, Any]]:
        """Drain ALL pages of a `{"data": [...], "pagination": {...}}`
        endpoint — silent first-page truncation loses runs/nodes."""
        params = dict(params or {})
        params.setdefault("per_page", 250)
        out: list[dict[str, Any]] = []
        page = 1
        while True:
            params["page"] = page
            body = self.request("GET", endpoint, params=params)
            data = body.get("data", [])
            out.extend(data)
            total = body.get("pagination", {}).get("total", len(out))
            if len(out) >= total or not data:
                return out
            page += 1
