"""Shared REST transport: bearer auth, error mapping, refresh, pagination,
and a process-wide keep-alive connection pool.

One HTTP wrapper for every client in the stack (UserClient, NodeDaemon,
RestAlgorithmClient) so wire behavior — bearer header, JSON-or-empty bodies,
>=400 error mapping, 401 refresh retry, page draining — lives in one place.
(The node proxy is a *relay*, not a client: it forwards foreign tokens
verbatim and keeps its own thin forwarding code — but it relays over the
same pooled transport via `pooled_request`.)

Connection pooling: `requests.Session` objects are checked out of a
per-host pool (`_SessionPool`) for the duration of one HTTP request and
returned afterwards, so every daemon/client call rides an already-open
keep-alive socket instead of paying TCP (+TLS) setup per call. Sessions
are never shared between threads concurrently — checkout IS the thread
ownership — and a request that dies on a stale keep-alive socket (the
server closed an idle persistent connection) is retried exactly once on a
fresh session; the stale one is discarded, not repooled.

Accounting: every request feeds `REST_STATS` (calls, request/response
bytes, seconds, stale-socket retries) — `runtime.metrics.rest_stats_snapshot`
exposes it to the bench/observability consumers; diff two snapshots to
scope the counters to one round or bench arm.
"""
from __future__ import annotations

import re
import threading
import time
from typing import Any, Callable
from urllib.parse import urlsplit

import requests

from vantage6_tpu.runtime.tracing import TRACER

# low-cardinality span names: /api/run/17 and /api/run/99 are the same hop
_ID_SEGMENT = re.compile(r"/\d+")


class RestError(RuntimeError):
    """Server returned an error status."""

    def __init__(self, status: int, msg: str):
        super().__init__(f"HTTP {status}: {msg}")
        self.status = status
        self.msg = msg


class RestStats:
    """Thread-safe process-wide REST accounting (shape mirrors
    serialization.WireStats so consumers diff snapshots the same way)."""

    _FIELDS = (
        "calls", "errors", "stale_retries",
        "bytes_sent", "bytes_received", "seconds",
    )

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            for f in self._FIELDS:
                setattr(self, f, 0.0 if f == "seconds" else 0)

    def record(
        self, sent: int, received: int, seconds: float,
        error: bool = False, stale_retry: bool = False,
    ) -> None:
        with self._lock:
            self.calls += 1
            self.errors += int(error)
            self.stale_retries += int(stale_retry)
            self.bytes_sent += int(sent)
            self.bytes_received += int(received)
            self.seconds += float(seconds)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {f: getattr(self, f) for f in self._FIELDS}


REST_STATS = RestStats()


class _SessionPool:
    """Per-host pool of `requests.Session` objects.

    `acquire` pops an idle session (or creates one); `release` repools it
    up to `max_idle` per host — beyond that the session is closed, so a
    burst of threads doesn't pin sockets forever. A session is owned by
    exactly one thread between acquire and release, which is what makes
    `requests.Session` reuse thread-safe here.
    """

    def __init__(self, max_idle: int = 8):
        self.max_idle = max_idle
        self._lock = threading.Lock()
        self._idle: dict[str, list[requests.Session]] = {}  # guarded-by: _lock

    @staticmethod
    def _key(url: str) -> str:
        parts = urlsplit(url)
        return f"{parts.scheme}://{parts.netloc}"

    def acquire(self, url: str) -> requests.Session:
        key = self._key(url)
        with self._lock:
            stack = self._idle.get(key)
            if stack:
                return stack.pop()
        return requests.Session()

    def release(self, url: str, session: requests.Session) -> None:
        key = self._key(url)
        with self._lock:
            stack = self._idle.setdefault(key, [])
            if len(stack) < self.max_idle:
                stack.append(session)
                return
        session.close()

    def discard(self, session: requests.Session) -> None:
        """A session whose socket went stale: close, never repool."""
        try:
            session.close()
        except Exception:
            pass

    def clear(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, {}
        for stack in idle.values():
            for s in stack:
                s.close()


POOL = _SessionPool()


def pooled_request(
    method: str,
    url: str,
    *,
    json_body: Any = None,
    params: dict[str, Any] | None = None,
    headers: dict[str, str] | None = None,
    timeout: float | None = None,
) -> requests.Response:
    """One HTTP request over the shared keep-alive pool.

    IDEMPOTENT requests (GET/HEAD/OPTIONS) retry exactly once on a stale
    keep-alive socket (ConnectionError): the server closing an idle
    persistent connection is an expected hazard of pooling, and the
    retried request rides a fresh socket. A second failure propagates —
    that is a *down* server, not a stale socket. POST/PATCH/DELETE never
    retry here: a connection that died mid-response may have been
    PROCESSED (ECONNRESET after commit is indistinguishable from a stale
    socket), and a silent re-send would duplicate the side effect — e.g.
    create a task fan-out twice.

    Tracing: when the calling thread is inside a sampled trace, the
    request carries a `traceparent` header (the server joins the trace)
    and the hop itself is recorded as a `rest` span — that is the
    client-encode→REST-hop attribution of docs/observability.md. Outside
    a trace this adds one thread-local read and nothing else.
    """
    ctx = TRACER.current_context()
    if ctx is not None:
        if ctx.sampled:
            path = _ID_SEGMENT.sub("/<id>", urlsplit(url).path)
            with TRACER.span(
                f"rest {method.upper()} {path}", kind="rest",
                attrs={"url_path": path},
            ):
                # inject INSIDE the span: the server's handler span must
                # parent on this REST hop (hop minus nested server span =
                # network/transport overhead), not on the outer caller
                hdrs = dict(headers or {})
                hdrs.setdefault(
                    "traceparent", TRACER.current_traceparent()
                )
                return _pooled_request_impl(
                    method, url, json_body=json_body, params=params,
                    headers=hdrs, timeout=timeout,
                )
        headers = dict(headers or {})
        headers.setdefault("traceparent", ctx.to_traceparent())
    return _pooled_request_impl(
        method, url, json_body=json_body, params=params,
        headers=headers, timeout=timeout,
    )


def _pooled_request_impl(
    method: str,
    url: str,
    *,
    json_body: Any = None,
    params: dict[str, Any] | None = None,
    headers: dict[str, str] | None = None,
    timeout: float | None = None,
) -> requests.Response:
    t0 = time.perf_counter()
    stale_retry = False
    session = POOL.acquire(url)
    try:
        try:
            resp = session.request(
                method, url, json=json_body, params=params,
                headers=headers, timeout=timeout,
            )
        except requests.exceptions.ConnectionError:
            POOL.discard(session)
            if method.upper() not in ("GET", "HEAD", "OPTIONS"):
                raise
            stale_retry = True
            session = POOL.acquire(url)
            resp = session.request(
                method, url, json=json_body, params=params,
                headers=headers, timeout=timeout,
            )
    except Exception:
        POOL.discard(session)
        REST_STATS.record(
            0, 0, time.perf_counter() - t0,
            error=True, stale_retry=stale_retry,
        )
        raise
    POOL.release(url, session)
    req_bytes = len(resp.request.body or b"") if resp.request is not None else 0
    REST_STATS.record(
        req_bytes, len(resp.content or b""), time.perf_counter() - t0,
        error=resp.status_code >= 400, stale_retry=stale_retry,
    )
    return resp


class RestSession:
    """``request()`` + ``paginate()`` against one base URL.

    ``refresh`` (optional) is called on a 401; returning True retries the
    request once with whatever new token ``token_getter`` now yields.

    The underlying sockets come from the process-wide pool, so two
    `RestSession` objects against the same host share warm connections —
    a daemon's short-lived re-auth sessions no longer pay TCP setup.
    """

    def __init__(
        self,
        base_url: str,
        token_getter: Callable[[], str | None] = lambda: None,
        refresh: Callable[[], bool] | None = None,
    ):
        self.base_url = base_url.rstrip("/")
        self._token_getter = token_getter
        self._refresh = refresh

    def request(
        self,
        method: str,
        endpoint: str,
        json_body: Any = None,
        params: dict[str, Any] | None = None,
        _retry: bool = True,
        timeout: float | None = None,
        raw: bool = False,
    ) -> Any:
        """JSON request/response; ``raw=True`` returns the response body
        as text instead (non-JSON endpoints: /api/metrics Prometheus
        exposition)."""
        # fault injection (V6T_FAULTS rest500): fail the request before it
        # touches the wire, so retry/rotation paths see a real RestError
        from vantage6_tpu.common.faults import FAULTS

        injected = FAULTS.rest_status(endpoint)
        if injected:
            raise RestError(
                injected, f"injected fault (V6T_FAULTS rest500) on {endpoint}"
            )
        headers = {}
        token = self._token_getter()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        resp = pooled_request(
            method,
            f"{self.base_url}/api/{endpoint.lstrip('/')}",
            json_body=json_body,
            params=params,
            headers=headers,
            timeout=timeout,
        )
        if (
            resp.status_code == 401
            and _retry
            and self._refresh is not None
            and self._refresh()
        ):
            return self.request(
                method, endpoint, json_body, params, False, timeout, raw
            )
        if raw:
            if resp.status_code >= 400:
                raise RestError(resp.status_code, resp.text[:200])
            return resp.text
        body = resp.json() if resp.content else {}
        if resp.status_code >= 400:
            raise RestError(resp.status_code, body.get("msg", resp.text))
        return body

    def paginate(
        self, endpoint: str, params: dict[str, Any] | None = None
    ) -> list[dict[str, Any]]:
        """Drain ALL pages of a `{"data": [...], "pagination": {...}}`
        endpoint — silent first-page truncation loses runs/nodes."""
        return _paginate_impl(self, endpoint, params)


def await_task_finished(
    client: Any,
    task_id: int,
    interval: float,
    timeout: float,
    wait_cap: float = 10.0,
) -> Any:
    """Block until `task_id` reaches a terminal status; returns the
    TaskStatus. Shared by UserClient and RestAlgorithmClient.

    Event-driven against a long-poll-capable server (or node proxy): each
    cycle re-checks the task (the anti-entropy truth — events can be
    evicted, and the caller's rooms may not cover the task's
    collaboration), then blocks on `GET event?since=<cursor>&wait=S`,
    waking the moment anything lands in the caller's rooms. Capability is
    probed once per client (`client._event_push`: None=unknown) via the
    response's `long_poll` flag; servers without it — or any event-fetch
    error — demote the client to fixed-`interval` sleeps, the previous
    behavior, permanently for that client object.
    """
    from vantage6_tpu.common.enums import TaskStatus

    deadline = time.time() + timeout
    cursor: int | None = None
    # empty-wait window: starts near `interval` and doubles per EMPTY
    # long poll up to wait_cap. When the caller's rooms cover the task
    # this never matters (the poll wakes on the event); when they DON'T
    # (an event-less finish is possible — e.g. rooms not covering the
    # collaboration), the window bounds the detection latency for short
    # tasks while still decaying the request rate for long ones.
    wait_base = max(0.2, min(interval, wait_cap))
    wait_cur = wait_base
    while True:
        task = client.request("GET", f"task/{task_id}")
        status = TaskStatus(task["status"])
        if status.is_finished:
            return status
        now = time.time()
        if now > deadline:
            raise TimeoutError(
                f"task {task_id} still {status.value} after {timeout}s"
            )
        if getattr(client, "_event_push", None) is False:
            time.sleep(max(0.05, min(interval, deadline - now)))
            continue
        try:
            if cursor is None:
                # cursor probe: tail from NOW, don't replay the buffer
                batch = client.request(
                    "GET", "event", params={"since": -1}, timeout=30.0
                )
            else:
                wait_s = max(0.2, min(wait_cur, wait_cap, deadline - now))
                batch = client.request(
                    "GET", "event",
                    # only run-status traffic should wake a result waiter
                    params={"since": cursor, "wait": wait_s,
                            "names": "status-update"},
                    timeout=wait_s + 30.0,
                )
            wait_cur = (
                wait_base if batch.get("data")
                else min(wait_cur * 2, wait_cap)
            )
        except Exception:
            client._event_push = False  # old server/proxy: poll instead
            continue
        if not batch.get("long_poll"):
            client._event_push = False
            continue
        client._event_push = True
        # adopt the server's cursor either way — a regression means a
        # restarted server (fresh sequence space), and the task GET at the
        # top of the loop is the ground truth regardless of event loss
        cursor = int(batch.get("cursor", 0))


def _paginate_impl(
    session: "RestSession", endpoint: str, params: dict[str, Any] | None
) -> list[dict[str, Any]]:
    params = dict(params or {})
    params.setdefault("per_page", 250)
    out: list[dict[str, Any]] = []
    page = 1
    while True:
        params["page"] = page
        body = session.request("GET", endpoint, params=params)
        data = body.get("data", [])
        out.extend(data)
        total = body.get("pagination", {}).get("total", len(out))
        if len(out) >= total or not data:
            return out
        page += 1
