"""Shared base layer (parity: vantage6-common, SURVEY.md §2 items 21-25)."""
from vantage6_tpu.common.enums import TaskStatus  # noqa: F401
