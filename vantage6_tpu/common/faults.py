"""Deterministic fault-injection harness (``V6T_FAULTS=`` spec).

The robustness loop (watchdog -> autopilot -> actuator) is only credible
if the failures it handles can be produced on demand, repeatably. This
module is that switchboard: a seedable plan of fault rules, parsed from
the ``V6T_FAULTS`` environment variable (or installed programmatically by
tests/bench), probed from a handful of fixed injection points:

- ``station_delay`` / ``drop_result`` — `Federation._run_host`: delay a
  station's host-mode execution, or swallow its result so the run wedges
  ACTIVE (the stuck_run / straggler food groups).
- ``daemon_crash``      — `node.daemon`: die mid-round WITHOUT the
  offline handshake (daemon_lapsed food group).
- ``rest_status``       — `common.rest.RestSession.request`: answer a
  burst of requests with an injected 5xx before touching the wire.
- ``poison_labels``     — label-flip poisoning for a station's targets
  (anomalous_station food group); callers opt in at data-prep time.
- ``wedge_seconds``     — `bench._run_worker`: wedge a named bench
  operation (e.g. the TPU probe) so the per-leg budget/checkpoint
  machinery can be exercised without real broken hardware.

Spec grammar — semicolon-separated rules, ``kind:key=value,...``::

    V6T_FAULTS="delay:station=0,seconds=0.3;rest500:count=3,seed=7"

kinds and their keys (all keys optional unless noted):

=========  ==============================================================
delay      station (int or ``*``), seconds (float, required), prob,
           limit, after
drop       station (int or ``*``), prob, limit, after
crash      prob, limit (default 1), after
rest500    status (default 500), endpoint (substring filter), count
           (alias for limit, default 3), prob, after
flip       station (int or ``*``), fraction (default 1.0)
wedge      op (substring filter on the operation name, e.g. ``probe``),
           seconds (float, required — how long the op hangs), prob,
           limit (default 1), after
=========  ==============================================================

``prob`` gates each opportunity through the rule's own ``random.Random``
seeded from ``seed`` (key or plan-level), so a given spec produces the
same firing sequence every run. ``limit`` caps total firings; ``after``
skips the first N opportunities (e.g. let two clean rounds pass first).

Everything is fail-soft at probe time: an empty plan answers every probe
with "no fault" at the cost of one attribute read, and a malformed env
spec logs and disables injection rather than taking the process down.
`FaultPlan.parse` itself is fail-loud (ValueError) so tests catch typos.
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any

log = logging.getLogger(__name__)

ENV_VAR = "V6T_FAULTS"

_KINDS = ("delay", "drop", "crash", "rest500", "flip", "wedge")

# per-kind key coercions; unknown keys are a parse error
_KEY_TYPES: dict[str, Any] = {
    "station": str,  # int index or "*"
    "seconds": float,
    "status": int,
    "endpoint": str,
    "fraction": float,
    "op": str,  # wedge: substring filter on the operation name
    "prob": float,
    "limit": int,
    "count": int,  # rest500 alias for limit
    "after": int,
    "seed": int,
}


@dataclass
class FaultRule:
    """One parsed rule plus its private RNG and firing counters."""

    kind: str
    station: str = "*"
    seconds: float = 0.0
    status: int = 500
    endpoint: str = ""
    op: str = ""
    fraction: float = 1.0
    prob: float = 1.0
    limit: int | None = None
    after: int = 0
    seed: int = 0
    seen: int = 0
    fired: int = 0
    _rng: random.Random = field(default=None, repr=False)  # type: ignore[assignment]

    def __post_init__(self) -> None:
        # kind folded into the seed so two rules sharing a plan seed
        # still draw independent streams; a STRING seed, not a tuple —
        # str seeding is deterministic across processes (tuple seeding
        # rides the salted hash() and is deprecated)
        self._rng = random.Random(f"{self.seed}:{self.kind}:{self.station}")

    def matches_station(self, station: int | None) -> bool:
        if self.station == "*":
            return True
        return station is not None and str(station) == self.station

    def fires(
        self, *, station: int | None = None, endpoint: str = "",
        op: str = "",
    ) -> bool:
        """One opportunity: match filters, then after/limit/prob gates.
        Counters advance only on matched opportunities so `after` means
        'skip the first N times this rule COULD have fired'."""
        if not self.matches_station(station):
            return False
        if self.endpoint and self.endpoint not in endpoint:
            return False
        if self.op and self.op not in op:
            return False
        self.seen += 1
        if self.seen <= self.after:
            return False
        if self.limit is not None and self.fired >= self.limit:
            return False
        if self.prob < 1.0 and self._rng.random() >= self.prob:
            return False
        self.fired += 1
        return True


def _parse_rule(chunk: str, plan_seed: int) -> FaultRule:
    head, _, tail = chunk.partition(":")
    kind = head.strip()
    if kind not in _KINDS:
        raise ValueError(
            f"unknown fault kind {kind!r} (expected one of {_KINDS})"
        )
    kw: dict[str, Any] = {"kind": kind, "seed": plan_seed}
    for part in filter(None, (p.strip() for p in tail.split(","))):
        key, eq, raw = part.partition("=")
        key = key.strip()
        if not eq or key not in _KEY_TYPES:
            raise ValueError(f"bad fault key {part!r} in {chunk!r}")
        try:
            value = _KEY_TYPES[key](raw.strip())
        except ValueError as e:
            raise ValueError(f"bad fault value {part!r} in {chunk!r}") from e
        if key == "count":  # rest500-friendly alias
            key = "limit"
        kw[key] = value
    if kind == "delay" and kw.get("seconds", 0.0) <= 0.0:
        raise ValueError(f"delay rule needs seconds>0: {chunk!r}")
    if kind == "rest500" and "limit" not in kw:
        kw["limit"] = 3  # a *burst*, not a permanent outage
    if kind == "crash" and "limit" not in kw:
        kw["limit"] = 1  # crash once by default
    if kind == "wedge":
        if kw.get("seconds", 0.0) <= 0.0:
            raise ValueError(f"wedge rule needs seconds>0: {chunk!r}")
        kw.setdefault("limit", 1)  # wedge once by default
    return FaultRule(**kw)


class FaultPlan:
    """A parsed set of rules; every probe is thread-safe."""

    def __init__(self, rules: list[FaultRule] | tuple[FaultRule, ...] = ()):
        self.rules = list(rules)
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        rules = [
            _parse_rule(chunk, seed)
            for chunk in filter(None, (c.strip() for c in spec.split(";")))
        ]
        return cls(rules)

    def _fire(self, kind: str, **match: Any) -> FaultRule | None:
        with self._lock:
            for rule in self.rules:
                if rule.kind == kind and rule.fires(**match):
                    return rule
        return None

    # ------------------------------------------------------------- probes
    def station_delay(self, station: int | None) -> float:
        rule = self._fire("delay", station=station)
        return rule.seconds if rule else 0.0

    def drop_result(self, station: int | None) -> bool:
        return self._fire("drop", station=station) is not None

    def daemon_crash(self) -> bool:
        return self._fire("crash") is not None

    def rest_status(self, endpoint: str) -> int | None:
        rule = self._fire("rest500", endpoint=endpoint)
        return rule.status if rule else None

    def wedge_seconds(self, op: str) -> float:
        """Seconds the named bench operation should hang (0.0 = no
        wedge). `op` is matched as a substring against the rule's
        ``op`` filter — an empty filter wedges every probed op."""
        rule = self._fire("wedge", op=op)
        return rule.seconds if rule else 0.0

    def flip_fraction(self, station: int | None) -> float:
        with self._lock:
            for rule in self.rules:
                if rule.kind == "flip" and rule.matches_station(station):
                    return rule.fraction
        return 0.0

    def snapshot(self) -> list[dict[str, Any]]:
        """Firing counts per rule — for assertions and flight notes."""
        with self._lock:
            return [
                {
                    "kind": r.kind,
                    "station": r.station,
                    "seen": r.seen,
                    "fired": r.fired,
                }
                for r in self.rules
            ]


class FaultInjector:
    """Process-global holder with a stable identity, so every injection
    point can ``from vantage6_tpu.common.faults import FAULTS`` once and
    see reconfigurations. Empty plan == injection disabled."""

    def __init__(self):
        self._plan = FaultPlan()

    @property
    def active(self) -> bool:
        return bool(self._plan.rules)

    def configure(self, spec: str | None, seed: int = 0) -> FaultPlan:
        """Install a plan from a spec string (None/"" clears). Returns
        the installed plan so tests can inspect firing counters."""
        self._plan = FaultPlan.parse(spec, seed=seed) if spec else FaultPlan()
        if self._plan.rules:
            log.warning(
                "fault injection ARMED: %d rule(s) from spec %r",
                len(self._plan.rules), spec,
            )
        return self._plan

    def clear(self) -> None:
        self._plan = FaultPlan()

    # --------------------------------------------- probes (all fail-soft)
    def sleep_station_delay(self, station: int | None) -> float:
        """Probe + perform a station delay; returns seconds slept."""
        if not self.active:
            return 0.0
        seconds = self._plan.station_delay(station)
        if seconds > 0.0:
            log.info("fault: delaying station %s by %.2fs", station, seconds)
            time.sleep(seconds)
        return seconds

    def drop_result(self, station: int | None) -> bool:
        return self.active and self._plan.drop_result(station)

    def daemon_crash(self) -> bool:
        return self.active and self._plan.daemon_crash()

    def rest_status(self, endpoint: str) -> int | None:
        if not self.active:
            return None
        return self._plan.rest_status(endpoint)

    def wedge_seconds(self, op: str) -> float:
        """How long the named bench operation should hang (0.0 = run
        normally). The CALLER sleeps — usually inside the wedged worker
        subprocess — so the parent's per-leg timeout machinery sees a
        realistic hang, not an instant failure."""
        if not self.active:
            return 0.0
        return self._plan.wedge_seconds(op)

    def poison_labels(self, y: Any, station: int | None) -> Any:
        """Sign-flip a deterministic `fraction` of labels when a ``flip``
        rule matches `station`; otherwise return `y` untouched. Works on
        anything numpy-like with fancy indexing."""
        if not self.active:
            return y
        fraction = self._plan.flip_fraction(station)
        if fraction <= 0.0:
            return y
        import numpy as np

        y = np.array(y, copy=True)
        n = int(y.shape[0])
        k = max(1, int(round(fraction * n)))
        idx = random.Random(f"flip:{station}:{n}").sample(range(n), k)
        y[idx] = -y[idx]
        log.info("fault: label-flipped %d/%d targets on station %s", k, n, station)
        return y

    def snapshot(self) -> list[dict[str, Any]]:
        return self._plan.snapshot()


FAULTS = FaultInjector()

_env_spec = os.environ.get(ENV_VAR)
if _env_spec:
    try:
        FAULTS.configure(_env_spec, seed=int(os.environ.get("V6T_FAULTS_SEED", "0")))
    except Exception:
        log.exception(
            "ignoring malformed %s=%r (fault injection disabled)",
            ENV_VAR, _env_spec,
        )
