"""Per-pair X25519 mask agreement — untrusted-aggregator secure aggregation.

This is the Bonawitz-et-al. (CCS'17) key-provisioning upgrade the threat
model (docs/THREAT_MODEL.md) names for `vantage6_tpu.native`'s additive
masking: instead of ONE shared seed (which whoever holds it — including the
central aggregator — can use to regenerate masks and unmask any upload),
every pair of stations agrees a pairwise secret via X25519 Diffie-Hellman.
Public keys travel through the server in the clear; the pairwise shared
secrets never exist anywhere but the two stations, so the server/aggregator,
holding ALL public material (every pubkey, every masked upload, the tag),
cannot reconstruct any individual update. Masks still cancel exactly in the
wrapping int32 sum, so the aggregate is exact.

Protocol (two task rounds through the normal control plane):
  1. advertise: each station derives an X25519 keypair from its LOCAL
     station secret + the aggregation tag and publishes the public key.
  2. upload: each station derives the pairwise seed for every peer
     (X25519(priv_i, pub_j) -> HMAC into a ChaCha20 key), masks its
     quantized vector with the native kernels, uploads.
  Aggregation is the plain wrapping sum (native.sum_wrapping) — the
  aggregator needs no keys at all.

Scope/honesty (same stance as the single-seed path's docs):

- against an HONEST-BUT-CURIOUS aggregator the masking alone suffices. An
  ACTIVE server could substitute its own keys in round 1 (classic DH MitM)
  and unmask; passing ``identities``/``signatures`` to ``mask_update_dh``
  closes this: adverts are RSA-PSS-signed with the organizations' identity
  keys (common.encryption.RSACryptor.sign_bytes) and verified before any
  pair seed is derived — see sign_advert/verify_adverts.
- dropout recovery for this module's two-round protocol lives in
  ``common.secureagg_bonawitz`` (Shamir-shared mask recovery, full
  Bonawitz); HERE, if a station that advertised fails to upload, its
  pairwise masks don't cancel and the round must be retried with the
  survivor set (the SPMD on-pod path never drops stations mid-round by
  construction).

Derivations (all tagged, versioned):
  keypair:   priv_i = clamp(HMAC-SHA256(station_secret_i,
                                        "v6t-x25519-mask-v1:" || tag))
  pair seed: s_ij   = HMAC-SHA256(X25519(priv_i, pub_j),
                                  "v6t-pair-mask-v1:" || i || j || tag)
  mask:      ChaCha20(key=s_ij, nonce=[i, j, 0]), station min(i,j) adds +,
             max(i,j) adds − (cancellation identical to the native contract)

The per-aggregation `tag` gives the same domain separation as
native.derive_mask_key: fresh keypairs AND fresh pair seeds every round,
so uploads from different aggregations can never be differenced.
"""
from __future__ import annotations

import hashlib
import hmac
from typing import Iterable, Mapping

import numpy as np

# `cryptography` is OPTIONAL (same stance as common.encryption): importing
# this module — and workloads.secure_average etc. that reach it — must work
# without the package; X25519 use fails loudly on first call instead.
try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )
    _CRYPTOGRAPHY_ERROR: Exception | None = None
except ModuleNotFoundError as _e:  # pragma: no cover - exercised in CI env
    X25519PrivateKey = X25519PublicKey = None  # type: ignore[assignment]
    Encoding = PublicFormat = None  # type: ignore[assignment]
    _CRYPTOGRAPHY_ERROR = _e


def _require_cryptography() -> None:
    if _CRYPTOGRAPHY_ERROR is not None:
        raise RuntimeError(
            "the 'cryptography' package is required for X25519 DH mask "
            "agreement but is not installed; install it or use the "
            "single-seed masking path (fed.collectives.secure_sum)"
        ) from _CRYPTOGRAPHY_ERROR


from vantage6_tpu import native
from vantage6_tpu.algorithm.context import current_environment


def _tag_bytes(tag: bytes | str | int) -> bytes:
    if isinstance(tag, int):
        tag = str(tag)
    if isinstance(tag, str):
        tag = tag.encode()
    return tag


def _resolve(value):
    """AlgorithmEnvironment identity fields may be values or zero-arg
    factories (lazy RSA keygen); resolve either."""
    return value() if callable(value) else value


def get_identity():
    """This station's org RSA identity cryptor from the run environment, or
    None when the runtime provisioned none (then adverts go unsigned and
    the guarantee stays honest-but-curious)."""
    return _resolve(current_environment().identity)


def get_org_identities() -> Mapping[int, str] | None:
    """The registered identity-pubkey roster (station -> base64 PEM), or
    None when the runtime provisioned none."""
    return _resolve(current_environment().org_identities)


def get_station_secret() -> bytes:
    """This station's LOCAL long-term secret from the active run environment.

    Provisioned per station (node config `station_secret`, or generated by
    the Federation runtime); it never leaves the station — only public keys
    derived from it do.
    """
    secret = current_environment().station_secret
    if not secret:
        raise RuntimeError(
            "this station has no station_secret provisioned — set "
            "`station_secret` in the node config (hex) to enable DH secure "
            "aggregation"
        )
    return secret


def keypair_ikm(station_secret: bytes, tag: bytes | str | int) -> bytes:
    """The 32 secret bytes the per-aggregation X25519 key derives from.

    Exposed (rather than buried in derive_keypair) because the Bonawitz
    dropout-recovery path Shamir-shares exactly these bytes among peers —
    reconstructing them reconstructs the dropped station's pairwise seeds.
    """
    if len(station_secret) < 16:
        raise ValueError("station secret must be >= 16 bytes")
    return hmac.new(
        station_secret,
        b"v6t-x25519-mask-v1:" + _tag_bytes(tag),
        hashlib.sha256,
    ).digest()


def derive_keypair(
    station_secret: bytes, tag: bytes | str | int
) -> tuple[X25519PrivateKey, str]:
    """Deterministic per-aggregation X25519 keypair -> (private, pub hex).

    Deterministic so the two protocol rounds (advertise, upload) re-derive
    the same key without any state carried between stateless task runs.
    X25519 clamps the 32 HMAC bytes into a valid scalar internally.
    """
    return keypair_from_ikm(keypair_ikm(station_secret, tag))


def keypair_from_ikm(ikm: bytes) -> "tuple[X25519PrivateKey, str]":
    _require_cryptography()
    priv = X25519PrivateKey.from_private_bytes(ikm)
    pub = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    return priv, pub.hex()


# ------------------------------------------------------- advert authentication
def advert_message(
    station: int, pub_hex: str, tag: bytes | str | int
) -> bytes:
    """Canonical bytes an advert signature covers: binds the X25519 pubkey
    to (station index, aggregation tag) so a signature can be replayed
    neither for another station nor for another aggregation."""
    return (
        b"v6t-advert-v1:%d:" % station
        + pub_hex.encode()
        + b":"
        + _tag_bytes(tag)
    )


def sign_advert(
    cryptor, station: int, pub_hex: str, tag: bytes | str | int
) -> str:
    """Sign this station's advert with its organization's RSA identity key
    (an ``encryption.RSACryptor``); returns hex."""
    return cryptor.sign_bytes(advert_message(station, pub_hex, tag)).hex()


def verify_adverts(
    pubkeys: Mapping[int, str],
    identities: Mapping[int, str],
    signatures: Mapping[int, str],
    tag: bytes | str | int,
) -> None:
    """Fail closed unless EVERY advert carries a valid signature from its
    organization's registered identity key. `identities` maps station index
    -> base64 PEM RSA public key (as registered with the server out-of-band
    / at onboarding — the trust root the relay cannot rewrite).

    `identities` is also the ROSTER: the relayed advert set must cover
    exactly these stations. Without this check an active relay could simply
    SHRINK the participant list it shows each station (every remaining
    advert validly signed) until a station has no peers left — at which
    point its 'masked' upload is the plaintext.
    """
    from vantage6_tpu.common.encryption import RSACryptor

    if set(pubkeys) != set(identities):
        missing = sorted(set(identities) - set(pubkeys))
        extra = sorted(set(pubkeys) - set(identities))
        raise ValueError(
            "advert roster does not match the registered identity roster "
            f"(missing adverts for {missing}, unregistered stations {extra})"
            " — a relay dropping participants would strip their masks; "
            "aborting"
        )
    for s, pub_hex in pubkeys.items():
        ident = identities.get(s)
        sig = signatures.get(s)
        if ident is None or sig is None:
            raise ValueError(
                f"station {s}: advert has no identity key/signature — "
                "refusing unauthenticated DH advert"
            )
        if not RSACryptor.verify_signature(
            ident, advert_message(s, pub_hex, tag), bytes.fromhex(sig)
        ):
            raise ValueError(
                f"station {s}: advert signature INVALID — possible "
                "key-substitution (MitM) by the relay; aborting aggregation"
            )


def pairwise_seed(
    priv: X25519PrivateKey,
    peer_pub_hex: str,
    i: int,
    j: int,
    tag: bytes | str | int,
) -> bytes:
    """32-byte ChaCha20 key both ends of pair (i, j) derive identically."""
    _require_cryptography()
    shared = priv.exchange(
        X25519PublicKey.from_public_bytes(bytes.fromhex(peer_pub_hex))
    )
    lo, hi = (i, j) if i < j else (j, i)
    info = b"v6t-pair-mask-v1:%d:%d:" % (lo, hi) + _tag_bytes(tag)
    return hmac.new(shared, info, hashlib.sha256).digest()


def mask_update_dh(
    station_secret: bytes,
    station: int,
    pubkeys: Mapping[int, str] | Iterable[tuple[int, str]],
    values: np.ndarray,
    scale: float = 2.0**16,
    tag: bytes | str | int = b"",
    identities: Mapping[int, str] | None = None,
    signatures: Mapping[int, str] | None = None,
) -> np.ndarray:
    """Quantize `values` and add this station's pairwise DH masks (mod 2^32).

    ``pubkeys`` maps station index -> X25519 public key hex for EVERY
    participant (own entry ignored). Uploads from all participants sum —
    wrapping — to the exact quantized total; no single party (aggregator
    included) can strip another station's masks.

    When ``identities`` (station -> base64 PEM RSA public key) is given,
    every advert must also carry a valid signature in ``signatures``
    (see verify_adverts) — upgrading the guarantee from honest-but-curious
    to active-MitM-resistant relays.
    """
    pubs = dict(pubkeys)
    if identities is not None:
        verify_adverts(pubs, identities, signatures or {}, tag)
    priv, own_pub = derive_keypair(station_secret, tag)
    if station in pubs and pubs[station] != own_pub:
        raise ValueError(
            f"advertised pubkey for station {station} does not match this "
            "station's secret+tag derivation — wrong secret or stale tag?"
        )
    q = native.quantize(np.asarray(values), scale)
    shape = q.shape
    acc = q.reshape(-1).astype(np.uint32)
    with np.errstate(over="ignore"):
        for other, pub_hex in sorted(pubs.items()):
            if other == station:
                continue
            lo, hi = min(station, other), max(station, other)
            seed = pairwise_seed(priv, pub_hex, lo, hi, tag)
            stream = native.chacha20_stream(
                seed, native.pair_nonce(lo, hi), acc.size
            )
            acc = acc + stream if station == lo else acc - stream
    return acc.astype(np.int32).reshape(shape)


def unmask_sum_dh(
    uploads: np.ndarray, scale: float = 2.0**16
) -> np.ndarray:
    """The aggregator's entire job: wrapping sum + dequantize. No keys."""
    return native.dequantize(native.sum_wrapping(np.asarray(uploads)), scale)
