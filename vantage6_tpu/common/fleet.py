"""Fleet telemetry push path: compact snapshots shipped to the server.

Every observability plane so far (tracing, watchdog/flight, device
observatory, learning plane) is process-local: each daemon and each
Federation process serves its own `/api/metrics` and keeps its own
flight rings, and `tools/doctor.py` only unifies the fleet *after the
fact* by merging dumped bundles. This module is the live half: any
process with a REST path to the server periodically ships a **compact
telemetry snapshot + flight-note deltas** to `POST /api/telemetry`,
where `server/fleet.py` lands them in the shared `fleet_metric` /
`fleet_event` tables — so N replicas over one store serve ONE coherent
fleet view at `GET /api/fleet`, and the watchdog's SLO engine evaluates
burn rates over cross-host history instead of one process's memory.

Pieces:

- :func:`build_snapshot` — source-stamped compact form of
  ``REGISTRY.snapshot()`` (scalars kept, histograms folded to their
  cumulative ``_sum``/``_count``) plus the flight notes newer than the
  previous push (the delta contract: notes ship once, not per push).
- :func:`encode_push` / :func:`decode_push` — the wire envelope. The
  snapshot is wire-v2 encoded (``serialization.serialize``) and rides
  base64 inside a JSON body: the pooled REST transport is JSON-only by
  design, and a base64 detour keeps the push on the same audited
  transport (auth, retries, fault injection) as every other call.
- :class:`FleetPusher` — the periodic client embedded in the daemon's
  ping/sync worker and the Federation round loop. Capability-pinned:
  the first 404/405 from an old server pins pushing off for the
  process lifetime (same idiom as the daemon's batch-claim pin), so a
  new daemon against a pre-fleet server degrades to a no-op instead of
  spamming errors.

Env knob: ``V6T_FLEET_PUSH_INTERVAL`` (seconds between pushes,
default 15; also the staleness unit the server-side freshness view is
calibrated against).
"""
from __future__ import annotations

import base64
import threading
import time
from typing import Any, Callable

from vantage6_tpu.common.env import env_float
from vantage6_tpu.common.telemetry import REGISTRY, metric_kind

DEFAULT_PUSH_INTERVAL = 15.0


def push_interval(default: float | None = None) -> float:
    """The configured push cadence (floor 0.05 s so tests can go fast
    without a zero-interval busy loop)."""
    base = default if default is not None else DEFAULT_PUSH_INTERVAL
    return max(0.05, env_float("V6T_FLEET_PUSH_INTERVAL", base))


def compact_metrics(snap: dict[str, Any] | None = None) -> dict[str, float]:
    """Flatten a registry snapshot to shippable scalars: counters and
    gauges as-is, histograms folded to cumulative ``_sum``/``_count``
    (the census and rate math downstream need totals, not buckets)."""
    if snap is None:
        snap = REGISTRY.snapshot()
    out: dict[str, float] = {}
    for name, value in snap.items():
        if isinstance(value, bool):
            continue
        if isinstance(value, (int, float)):
            out[name] = float(value)
        elif isinstance(value, dict) and "count" in value:
            out[name + "_sum"] = float(value.get("sum") or 0.0)
            out[name + "_count"] = float(value.get("count") or 0)
    return out


def sample_kind(name: str) -> str:
    """Declared kind of a compacted series; histogram-derived ``_sum``/
    ``_count`` series are cumulative, i.e. counters. Undeclared names
    default to gauge (the conservative merge: no cross-source summing)."""
    kind = metric_kind(name)
    if kind in ("counter", "gauge"):
        return kind
    if name.endswith(("_sum", "_count")) and metric_kind(
        name.rsplit("_", 1)[0]
    ) == "histogram":
        return "counter"
    return "gauge"


def build_snapshot(
    source: str,
    service: str,
    seq: int,
    notes_since: float = 0.0,
    snap: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """One push payload: who, how fresh, the compact metric census, and
    the flight-note delta since the previous push."""
    notes: list[dict[str, Any]] = []
    try:
        from vantage6_tpu.common.flight import FLIGHT

        notes = FLIGHT.recent_notes(since=notes_since)
    except Exception:  # the push must not depend on the recorder
        pass
    return {
        "source": source,
        "service": service,
        "seq": int(seq),
        "ts": time.time(),
        "metrics": compact_metrics(snap),
        "notes": notes,
    }


def encode_push(payload: dict[str, Any]) -> dict[str, Any]:
    """Wire-v2 encode the payload and wrap it for the JSON transport."""
    from vantage6_tpu.common.serialization import serialize

    return {
        "blob": base64.b64encode(serialize(payload)).decode("ascii"),
        "encoding": "wire+b64",
    }


def decode_push(body: dict[str, Any]) -> dict[str, Any]:
    """Inverse of :func:`encode_push`; raises ValueError on anything
    that does not decode to a source-stamped snapshot dict."""
    from vantage6_tpu.common.serialization import deserialize

    blob = body.get("blob") if isinstance(body, dict) else None
    if not isinstance(blob, str):
        raise ValueError("telemetry push body must carry a base64 'blob'")
    try:
        payload = deserialize(base64.b64decode(blob.encode("ascii")))
    except Exception as e:
        raise ValueError(f"undecodable telemetry blob: {e}") from None
    if not isinstance(payload, dict) or not payload.get("source"):
        raise ValueError("telemetry payload must be a dict with a 'source'")
    return payload


class FleetPusher:
    """Periodic snapshot shipper riding an existing request path.

    ``request`` is the embedder's REST callable — the daemon's
    replica-rotating :meth:`NodeDaemon.request`, or a bound
    ``RestSession.request`` — invoked as
    ``request("post", "telemetry", json_body=envelope)``. Everything
    here is fail-soft: a push failure is a counter + flight note, never
    an exception into the ping/sync loop that hosts us.
    """

    def __init__(
        self,
        source: str,
        service: str,
        request: Callable[..., Any],
        interval: float | None = None,
    ):
        self.source = source
        self.service = service
        self.interval = push_interval(interval)
        self._request = request
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock
        self._notes_since = 0.0  # guarded-by: _lock
        self._next_at = 0.0  # guarded-by: _lock (monotonic)
        # None = unknown, False = pinned off (pre-fleet server), True = ok
        self.supported: bool | None = None  # guarded-by: _lock
        self.last_error: str | None = None  # guarded-by: _lock

    def due(self, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            return self.supported is not False and now >= self._next_at

    def maybe_push(self) -> bool:
        """Push iff the interval elapsed and the server supports it."""
        if not self.due():
            return False
        return self.push()

    def push(self) -> bool:
        """One push now. Returns True on an accepted snapshot."""
        from vantage6_tpu.common.rest import RestError

        with self._lock:
            if self.supported is False:
                return False
            seq = self._seq
            notes_since = self._notes_since
            # schedule the next attempt up front: a crashing/slow server
            # must not turn every sync tick into a push retry
            self._next_at = time.monotonic() + self.interval
        payload = build_snapshot(
            self.source, self.service, seq, notes_since=notes_since
        )
        try:
            self._request("post", "telemetry", json_body=encode_push(payload))
        except RestError as e:
            if e.status in (404, 405):
                # pre-fleet server: pin off for the process lifetime
                # (same capability idiom as the daemon's batch-claim pin)
                with self._lock:
                    self.supported = False
                    self.last_error = f"pinned off: HTTP {e.status}"
                REGISTRY.counter("v6t_fleet_push_unsupported_total").inc()
                self._note("fleet_push_unsupported", status=e.status)
                return False
            self._record_error(f"HTTP {e.status}: {e.msg}")
            return False
        except Exception as e:
            self._record_error(f"{type(e).__name__}: {e}")
            return False
        newest = max(
            (n.get("ts", 0.0) for n in payload["notes"]), default=notes_since
        )
        with self._lock:
            self.supported = True
            self.last_error = None
            self._seq = seq + 1
            self._notes_since = max(self._notes_since, newest)
        REGISTRY.counter("v6t_fleet_pushes_total").inc()
        return True

    def _record_error(self, detail: str) -> None:
        with self._lock:
            self.last_error = detail
        REGISTRY.counter("v6t_fleet_push_errors_total").inc()
        self._note("fleet_push_failed", error=detail)

    def _note(self, kind: str, **fields: Any) -> None:
        try:
            from vantage6_tpu.common.flight import FLIGHT

            FLIGHT.note(kind, source=self.source, **fields)
        except Exception:  # pragma: no cover
            pass
