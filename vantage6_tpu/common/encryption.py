"""End-to-end payload encryption between organizations.

Parity: vantage6-common's encryption module (SURVEY.md §2 item 21) — the
reference encrypts task inputs/results end-to-end so the server only ever
relays ciphertext: a fresh symmetric key per payload, sealed with the
*recipient organization's* RSA public key, with a ``DummyCryptor`` drop-in
when a collaboration is not encrypted.

Scheme here: RSA-OAEP(SHA-256) seals a fresh 256-bit key; the payload itself
is AES-256-GCM (authenticated — tampering with a relayed blob is detected,
which the reference's CTR mode does not give).

Two wire framings (docs/wire_format.md):

- **legacy (v1)**: ``base64(sealed_key) $ base64(nonce) $ base64(ciphertext)``
  — printable JSON-safe strings, ~1.33x inflation on top of the payload.
- **binary (v2, default)**: ``b"V6TE\\x02" | u16 sealed_len | sealed_key |
  nonce(12) | ciphertext`` — zero inflation for file/bytes transports;
  string transports carry ``base64(frame)`` (single encoding, never the
  double base64 of v1-payload-inside-v1-cryptor).

Decryption auto-detects all of these, so old blobs keep decrypting;
``V6T_WIRE_FORMAT=v1`` pins the string API back to the legacy emission.

**Broadcast encryption**: an N-station fan-out of one payload costs ONE
AES-GCM pass — the ciphertext is computed once under a single session key
and only the RSA key-seal differs per recipient (`encrypt_bytes_broadcast`)
— instead of N full encrypt passes. Dedup hits are recorded on
`serialization.WIRE_STATS`.
"""
from __future__ import annotations

import base64
import os
import struct
from pathlib import Path

# `cryptography` is OPTIONAL: environments that never encrypt (CI, the SPMD
# simulator with DummyCryptor collaborations) must still be able to import
# this module — and everything that transitively imports it (node daemon,
# proxy, runtime) — without the package installed. Real crypto use fails
# loudly via _require_cryptography() on FIRST USE, not at import time.
try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
    _CRYPTOGRAPHY_ERROR: Exception | None = None
except ModuleNotFoundError as _e:  # pragma: no cover - exercised in CI env
    hashes = serialization = padding = rsa = None  # type: ignore[assignment]
    _CRYPTOGRAPHY_ERROR = _e


def _require_cryptography() -> None:
    if _CRYPTOGRAPHY_ERROR is not None:
        raise RuntimeError(
            "the 'cryptography' package is required for RSA/AES payload "
            "encryption but is not installed; install it or use "
            "DummyCryptor (unencrypted collaborations)"
        ) from _CRYPTOGRAPHY_ERROR


def _aesgcm():
    _require_cryptography()
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    return AESGCM


SEPARATOR = "$"

# binary cryptor frame: magic + version, then u16 sealed-key length
ENC_MAGIC = b"V6TE\x02"
_SEALED_LEN = struct.Struct("<H")
_NONCE_LEN = 12


def _binary_wire_default() -> bool:
    """Whether the string API emits base64(binary frame) (v2, default) or
    the legacy '$'-joined format — follows serialization's format switch."""
    from vantage6_tpu.common.serialization import default_format

    return default_format() == "v2"


class CryptorBase:
    """Common base: byte<->str helpers shared by real and dummy cryptors.

    The binary-native surface is ``encrypt_bytes`` / ``decrypt_bytes`` /
    ``encrypt_bytes_broadcast``; the ``*_to_str`` methods wrap it for
    string transports (REST JSON bodies, DB columns) and keep decoding
    every historical format.
    """

    @staticmethod
    def bytes_to_str(data: bytes) -> str:
        return base64.b64encode(data).decode("ascii")

    @staticmethod
    def str_to_bytes(data: str) -> bytes:
        return base64.b64decode(data.encode("ascii"))

    # ---------------------------------------------------- binary-native API
    def encrypt_bytes(self, data: bytes, pubkey_base64: str) -> bytes:
        raise NotImplementedError

    def decrypt_bytes(self, data: "bytes | str") -> bytes:
        raise NotImplementedError

    def encrypt_bytes_broadcast(
        self, data: bytes, pubkeys: "list[str]"
    ) -> "list[bytes]":
        """One blob per recipient. Subclasses override to share the AES
        pass; the base fallback is N independent encrypts."""
        return [self.encrypt_bytes(data, k) for k in pubkeys]

    # ------------------------------------------------------- string wrappers
    def encrypt_bytes_to_str(
        self, data: bytes, pubkey_base64: str, format: "str | None" = None
    ) -> str:
        raise NotImplementedError

    def decrypt_str_to_bytes(self, data: str) -> bytes:
        return self.decrypt_bytes(data)

    def encrypt_bytes_to_str_broadcast(
        self, data: bytes, pubkeys: "list[str]"
    ) -> "list[str]":
        return [
            self.bytes_to_str(b)
            for b in self.encrypt_bytes_broadcast(data, pubkeys)
        ]


class DummyCryptor(CryptorBase):
    """Pass-through 'cryptor' for unencrypted collaborations (the string
    wire stays base64 so its shape is identical either way; the bytes wire
    is the payload itself — zero inflation, zero copies)."""

    def encrypt_bytes(self, data: bytes, pubkey_base64: str = "") -> bytes:
        return bytes(data)

    def decrypt_bytes(self, data: "bytes | str") -> bytes:
        if isinstance(data, str):
            return self.str_to_bytes(data)
        return bytes(data)

    def encrypt_bytes_broadcast(
        self, data: bytes, pubkeys: "list[str]"
    ) -> "list[bytes]":
        blob = bytes(data)
        return [blob] * len(pubkeys)  # shared object — no copies at all

    def encrypt_bytes_to_str(
        self, data: bytes, pubkey_base64: str = "",
        format: "str | None" = None,
    ) -> str:
        return self.bytes_to_str(data)  # base64 either way — same shape

    def encrypt_bytes_to_str_broadcast(
        self, data: bytes, pubkeys: "list[str]"
    ) -> "list[str]":
        wire = self.bytes_to_str(data)  # encode once, share N times
        return [wire] * len(pubkeys)


class RSACryptor(CryptorBase):
    """Hybrid RSA-OAEP + AES-256-GCM cryptor bound to one private key.

    ``private_key`` may be an ``rsa.RSAPrivateKey``, a PEM ``bytes`` blob, or
    a path to a PEM file (created if missing — the reference generates a
    keypair on first node start the same way).
    """

    KEY_BITS = 4096

    def __init__(self, private_key: "rsa.RSAPrivateKey | bytes | str | Path"):
        _require_cryptography()
        if isinstance(private_key, rsa.RSAPrivateKey):
            self.private_key = private_key
        elif isinstance(private_key, bytes):
            self.private_key = serialization.load_pem_private_key(
                private_key, password=None
            )
        else:
            path = Path(private_key)
            if not path.exists():
                path.parent.mkdir(parents=True, exist_ok=True)
                key = self.create_new_rsa_key()
                pem = key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.PKCS8,
                    serialization.NoEncryption(),
                )
                # 0600 from the first instant — no world-readable window.
                fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
                with os.fdopen(fd, "wb") as f:
                    f.write(pem)
            self.private_key = serialization.load_pem_private_key(
                path.read_bytes(), password=None
            )

    @classmethod
    def create_new_rsa_key(cls) -> "rsa.RSAPrivateKey":
        _require_cryptography()
        return rsa.generate_private_key(
            public_exponent=65537, key_size=cls.KEY_BITS
        )

    # ------------------------------------------------------------- public key
    @property
    def public_key_bytes(self) -> bytes:
        return self.private_key.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )

    @property
    def public_key_str(self) -> str:
        return self.bytes_to_str(self.public_key_bytes)

    def verify_public_key(self, pubkey_base64: str) -> bool:
        """Does the (server-registered) public key match our private key?"""
        return pubkey_base64 == self.public_key_str

    # -------------------------------------------------------------- identity
    def sign_bytes(self, data: bytes) -> bytes:
        """RSA-PSS(SHA-256) signature binding ``data`` to this organization's
        identity key — used e.g. to authenticate secure-aggregation key
        adverts against an ACTIVE (key-substituting) relay."""
        return self.private_key.sign(
            data,
            padding.PSS(
                mgf=padding.MGF1(hashes.SHA256()),
                salt_length=padding.PSS.MAX_LENGTH,
            ),
            hashes.SHA256(),
        )

    # -------------------------------------------------------------- transport
    @staticmethod
    def _oaep() -> "padding.OAEP":
        return padding.OAEP(
            mgf=padding.MGF1(algorithm=hashes.SHA256()),
            algorithm=hashes.SHA256(),
            label=None,
        )

    def _seal_session_key(self, session_key: bytes, pubkey_base64: str) -> bytes:
        recipient = serialization.load_pem_public_key(
            self.str_to_bytes(pubkey_base64)
        )
        return recipient.encrypt(session_key, self._oaep())

    def encrypt_bytes(self, data: bytes, pubkey_base64: str) -> bytes:
        """Binary v2 frame: one AES-256-GCM pass + one RSA-OAEP key seal."""
        return self.encrypt_bytes_broadcast(data, [pubkey_base64])[0]

    def encrypt_bytes_broadcast(
        self, data: bytes, pubkeys: "list[str]"
    ) -> "list[bytes]":
        """Single-pass broadcast: encrypt ``data`` ONCE under one session
        key, then seal that key per recipient — an N-station broadcast costs
        1 AES-GCM pass + N RSA seals (+ N frame memcpys) instead of N full
        passes. Frames share the same nonce+ciphertext; the session key is
        broadcast-scoped exactly like a reference task's per-payload key.
        """
        if not pubkeys:
            return []
        AESGCM = _aesgcm()
        session_key = AESGCM.generate_key(bit_length=256)
        nonce = os.urandom(_NONCE_LEN)
        ciphertext = AESGCM(session_key).encrypt(nonce, bytes(data), None)
        out = []
        for pubkey in pubkeys:
            sealed = self._seal_session_key(session_key, pubkey)
            out.append(
                b"".join((
                    ENC_MAGIC,
                    _SEALED_LEN.pack(len(sealed)),
                    sealed,
                    nonce,
                    ciphertext,
                ))
            )
        if len(pubkeys) > 1:
            from vantage6_tpu.common.serialization import WIRE_STATS

            WIRE_STATS.record_broadcast(len(pubkeys))
        return out

    def encrypt_bytes_to_str(
        self, data: bytes, pubkey_base64: str, format: "str | None" = None
    ) -> str:
        """String transport: base64(binary frame) under the v2 default, or
        the legacy ``$``-joined format when ``V6T_WIRE_FORMAT=v1`` (or
        ``format="v1"`` per call — e.g. a node's wire_format policy)."""
        legacy = (
            not _binary_wire_default() if format is None
            else format.strip().lower() in ("v1", "json")
        )
        if legacy:
            return self._encrypt_legacy_str(data, pubkey_base64)
        return self.bytes_to_str(self.encrypt_bytes(data, pubkey_base64))

    def encrypt_bytes_to_str_broadcast(
        self, data: bytes, pubkeys: "list[str]"
    ) -> "list[str]":
        if _binary_wire_default():
            return [
                self.bytes_to_str(b)
                for b in self.encrypt_bytes_broadcast(data, pubkeys)
            ]
        return [self._encrypt_legacy_str(data, k) for k in pubkeys]

    def _encrypt_legacy_str(self, data: bytes, pubkey_base64: str) -> str:
        """The historical printable wire shape (kept for old peers and for
        the cross-format compat tests)."""
        AESGCM = _aesgcm()
        session_key = AESGCM.generate_key(bit_length=256)
        nonce = os.urandom(_NONCE_LEN)
        ciphertext = AESGCM(session_key).encrypt(nonce, data, None)
        sealed = self._seal_session_key(session_key, pubkey_base64)
        return SEPARATOR.join(
            self.bytes_to_str(part) for part in (sealed, nonce, ciphertext)
        )

    @staticmethod
    def verify_signature(
        pubkey_base64: str, data: bytes, signature: bytes
    ) -> bool:
        """Check an RSA-PSS(SHA-256) signature against an organization's
        registered public key (base64 PEM, as stored by the server)."""
        _require_cryptography()
        from cryptography.exceptions import InvalidSignature

        pub = serialization.load_pem_public_key(
            CryptorBase.str_to_bytes(pubkey_base64)
        )
        try:
            pub.verify(
                signature,
                data,
                padding.PSS(
                    mgf=padding.MGF1(hashes.SHA256()),
                    salt_length=padding.PSS.MAX_LENGTH,
                ),
                hashes.SHA256(),
            )
            return True
        except InvalidSignature:
            return False

    def decrypt_bytes(self, data: "bytes | str") -> bytes:
        """Decrypt any wire shape this cryptor ever emitted: the binary v2
        frame, base64(v2 frame) strings, and the legacy '$'-joined strings
        — auto-detected, so v1 blobs keep decrypting forever."""
        if isinstance(data, str):
            if SEPARATOR in data:
                return self._decrypt_legacy_str(data)
            try:
                data = self.str_to_bytes(data)
            except Exception as e:
                raise ValueError(
                    "malformed encrypted payload (neither '$'-separated "
                    "legacy format nor base64)"
                ) from e
        data = bytes(data)
        if not data.startswith(ENC_MAGIC):
            # legacy string blob that travelled as bytes
            try:
                text = data.decode("ascii")
            except UnicodeDecodeError:
                text = ""
            if SEPARATOR in text:
                return self._decrypt_legacy_str(text)
            raise ValueError(
                "malformed encrypted payload (no V6TE frame magic)"
            )
        head = len(ENC_MAGIC) + _SEALED_LEN.size
        if len(data) < head:
            raise ValueError("malformed encrypted payload (truncated frame)")
        (sealed_len,) = _SEALED_LEN.unpack(data[len(ENC_MAGIC):head])
        nonce_at = head + sealed_len
        ct_at = nonce_at + _NONCE_LEN
        if len(data) < ct_at:
            raise ValueError(
                "malformed encrypted payload (truncated sealed key/nonce)"
            )
        session_key = self.private_key.decrypt(
            data[head:nonce_at], self._oaep()
        )
        return _aesgcm()(session_key).decrypt(
            data[nonce_at:ct_at], data[ct_at:], None
        )

    def _decrypt_legacy_str(self, data: str) -> bytes:
        try:
            sealed_s, nonce_s, ct_s = data.split(SEPARATOR)
        except ValueError as e:
            raise ValueError(
                "malformed encrypted payload (expected 3 '$'-separated parts)"
            ) from e
        session_key = self.private_key.decrypt(
            self.str_to_bytes(sealed_s), self._oaep()
        )
        return _aesgcm()(session_key).decrypt(
            self.str_to_bytes(nonce_s), self.str_to_bytes(ct_s), None
        )
