"""End-to-end payload encryption between organizations.

Parity: vantage6-common's encryption module (SURVEY.md §2 item 21) — the
reference encrypts task inputs/results end-to-end so the server only ever
relays ciphertext: a fresh symmetric key per payload, sealed with the
*recipient organization's* RSA public key, with a ``DummyCryptor`` drop-in
when a collaboration is not encrypted.

Scheme here: RSA-OAEP(SHA-256) seals a fresh 256-bit key; the payload itself
is AES-256-GCM (authenticated — tampering with a relayed blob is detected,
which the reference's CTR mode does not give). Wire format is
``base64(sealed_key) $ base64(nonce) $ base64(ciphertext)`` so blobs remain
printable JSON-safe strings like the reference's.
"""
from __future__ import annotations

import base64
import os
from pathlib import Path

# `cryptography` is OPTIONAL: environments that never encrypt (CI, the SPMD
# simulator with DummyCryptor collaborations) must still be able to import
# this module — and everything that transitively imports it (node daemon,
# proxy, runtime) — without the package installed. Real crypto use fails
# loudly via _require_cryptography() on FIRST USE, not at import time.
try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
    _CRYPTOGRAPHY_ERROR: Exception | None = None
except ModuleNotFoundError as _e:  # pragma: no cover - exercised in CI env
    hashes = serialization = padding = rsa = None  # type: ignore[assignment]
    _CRYPTOGRAPHY_ERROR = _e


def _require_cryptography() -> None:
    if _CRYPTOGRAPHY_ERROR is not None:
        raise RuntimeError(
            "the 'cryptography' package is required for RSA/AES payload "
            "encryption but is not installed; install it or use "
            "DummyCryptor (unencrypted collaborations)"
        ) from _CRYPTOGRAPHY_ERROR


def _aesgcm():
    _require_cryptography()
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    return AESGCM


SEPARATOR = "$"


class CryptorBase:
    """Common base: byte<->str helpers shared by real and dummy cryptors."""

    @staticmethod
    def bytes_to_str(data: bytes) -> str:
        return base64.b64encode(data).decode("ascii")

    @staticmethod
    def str_to_bytes(data: str) -> bytes:
        return base64.b64decode(data.encode("ascii"))

    def encrypt_bytes_to_str(self, data: bytes, pubkey_base64: str) -> str:
        raise NotImplementedError

    def decrypt_str_to_bytes(self, data: str) -> bytes:
        raise NotImplementedError


class DummyCryptor(CryptorBase):
    """Pass-through 'cryptor' for unencrypted collaborations (base64 only,
    so the wire shape is identical either way)."""

    def encrypt_bytes_to_str(self, data: bytes, pubkey_base64: str = "") -> str:
        return self.bytes_to_str(data)

    def decrypt_str_to_bytes(self, data: str) -> bytes:
        return self.str_to_bytes(data)


class RSACryptor(CryptorBase):
    """Hybrid RSA-OAEP + AES-256-GCM cryptor bound to one private key.

    ``private_key`` may be an ``rsa.RSAPrivateKey``, a PEM ``bytes`` blob, or
    a path to a PEM file (created if missing — the reference generates a
    keypair on first node start the same way).
    """

    KEY_BITS = 4096

    def __init__(self, private_key: "rsa.RSAPrivateKey | bytes | str | Path"):
        _require_cryptography()
        if isinstance(private_key, rsa.RSAPrivateKey):
            self.private_key = private_key
        elif isinstance(private_key, bytes):
            self.private_key = serialization.load_pem_private_key(
                private_key, password=None
            )
        else:
            path = Path(private_key)
            if not path.exists():
                path.parent.mkdir(parents=True, exist_ok=True)
                key = self.create_new_rsa_key()
                pem = key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.PKCS8,
                    serialization.NoEncryption(),
                )
                # 0600 from the first instant — no world-readable window.
                fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
                with os.fdopen(fd, "wb") as f:
                    f.write(pem)
            self.private_key = serialization.load_pem_private_key(
                path.read_bytes(), password=None
            )

    @classmethod
    def create_new_rsa_key(cls) -> "rsa.RSAPrivateKey":
        _require_cryptography()
        return rsa.generate_private_key(
            public_exponent=65537, key_size=cls.KEY_BITS
        )

    # ------------------------------------------------------------- public key
    @property
    def public_key_bytes(self) -> bytes:
        return self.private_key.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )

    @property
    def public_key_str(self) -> str:
        return self.bytes_to_str(self.public_key_bytes)

    def verify_public_key(self, pubkey_base64: str) -> bool:
        """Does the (server-registered) public key match our private key?"""
        return pubkey_base64 == self.public_key_str

    # -------------------------------------------------------------- identity
    def sign_bytes(self, data: bytes) -> bytes:
        """RSA-PSS(SHA-256) signature binding ``data`` to this organization's
        identity key — used e.g. to authenticate secure-aggregation key
        adverts against an ACTIVE (key-substituting) relay."""
        return self.private_key.sign(
            data,
            padding.PSS(
                mgf=padding.MGF1(hashes.SHA256()),
                salt_length=padding.PSS.MAX_LENGTH,
            ),
            hashes.SHA256(),
        )

    # -------------------------------------------------------------- transport
    def encrypt_bytes_to_str(self, data: bytes, pubkey_base64: str) -> str:
        AESGCM = _aesgcm()
        recipient = serialization.load_pem_public_key(
            self.str_to_bytes(pubkey_base64)
        )
        session_key = AESGCM.generate_key(bit_length=256)
        nonce = os.urandom(12)
        ciphertext = AESGCM(session_key).encrypt(nonce, data, None)
        sealed = recipient.encrypt(
            session_key,
            padding.OAEP(
                mgf=padding.MGF1(algorithm=hashes.SHA256()),
                algorithm=hashes.SHA256(),
                label=None,
            ),
        )
        return SEPARATOR.join(
            self.bytes_to_str(part) for part in (sealed, nonce, ciphertext)
        )

    @staticmethod
    def verify_signature(
        pubkey_base64: str, data: bytes, signature: bytes
    ) -> bool:
        """Check an RSA-PSS(SHA-256) signature against an organization's
        registered public key (base64 PEM, as stored by the server)."""
        _require_cryptography()
        from cryptography.exceptions import InvalidSignature

        pub = serialization.load_pem_public_key(
            CryptorBase.str_to_bytes(pubkey_base64)
        )
        try:
            pub.verify(
                signature,
                data,
                padding.PSS(
                    mgf=padding.MGF1(hashes.SHA256()),
                    salt_length=padding.PSS.MAX_LENGTH,
                ),
                hashes.SHA256(),
            )
            return True
        except InvalidSignature:
            return False

    def decrypt_str_to_bytes(self, data: str) -> bytes:
        try:
            sealed_s, nonce_s, ct_s = data.split(SEPARATOR)
        except ValueError as e:
            raise ValueError(
                "malformed encrypted payload (expected 3 '$'-separated parts)"
            ) from e
        session_key = self.private_key.decrypt(
            self.str_to_bytes(sealed_s),
            padding.OAEP(
                mgf=padding.MGF1(algorithm=hashes.SHA256()),
                algorithm=hashes.SHA256(),
                label=None,
            ),
        )
        return _aesgcm()(session_key).decrypt(
            self.str_to_bytes(nonce_s), self.str_to_bytes(ct_s), None
        )
