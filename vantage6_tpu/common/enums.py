"""Status and policy enums.

Parity: vantage6-common/vantage6/common/enum.py (reference mount was empty;
member set reconstructed per SURVEY.md §2 item 23 — RunStatus lifecycle
PENDING..KILLED plus failure refinements).
"""
from __future__ import annotations

import enum


class TaskStatus(str, enum.Enum):
    """Lifecycle of a federated task (and of each per-station run).

    The reference drives these transitions over SocketIO + REST; here the
    orchestrator drives them in-process, but the state machine is identical so
    client code observing statuses ports unchanged.
    """

    PENDING = "pending"
    INITIALIZING = "initializing"
    ACTIVE = "active"
    COMPLETED = "completed"
    FAILED = "failed"
    CRASHED = "crashed"
    KILLED = "killed by user"
    NOT_ALLOWED = "not allowed"
    NO_IMAGE = "non-existing image"

    @classmethod
    def failed_statuses(cls) -> set["TaskStatus"]:
        return {cls.FAILED, cls.CRASHED, cls.KILLED, cls.NOT_ALLOWED, cls.NO_IMAGE}

    @property
    def has_failed(self) -> bool:
        return self in self.failed_statuses()

    @property
    def is_finished(self) -> bool:
        return self == TaskStatus.COMPLETED or self.has_failed


# The reference models per-station execution as a `Run` row with its own status
# mirroring the task statuses; keep the alias so both names resolve.
RunStatus = TaskStatus


class Scope(str, enum.Enum):
    """RBAC scope axis (scope x operation permission matrix)."""

    OWN = "own"
    ORGANIZATION = "organization"
    COLLABORATION = "collaboration"
    GLOBAL = "global"


class Operation(str, enum.Enum):
    """RBAC operation axis."""

    VIEW = "view"
    CREATE = "create"
    EDIT = "edit"
    DELETE = "delete"
    SEND = "send"
    RECEIVE = "receive"


class StationPolicy(str, enum.Enum):
    """Node/station-level execution policies (reference: NodePolicy)."""

    ALLOWED_ALGORITHMS = "allowed_algorithms"
    ALLOWED_USERS = "allowed_users"
    ALLOWED_ORGANIZATIONS = "allowed_organizations"
    REQUIRE_ALGORITHM_REVIEW = "require_algorithm_review"


class AggregationKind(str, enum.Enum):
    """How a central step combines per-station partials on-device."""

    SUM = "sum"
    MEAN = "mean"
    WEIGHTED_MEAN = "weighted_mean"
    SECURE_SUM = "secure_sum"
    CONCAT = "concat"
