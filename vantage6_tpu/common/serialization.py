"""Task payload (de)serialization — v1 JSON and the v2 binary wire format.

Parity: the reference serializes task input/results as JSON written to the
container's INPUT_FILE/OUTPUT_FILE (SURVEY.md §2 item 18), with numpy/jax
arrays and pandas objects in a tagged encoding so federated payloads (model
weights, statistics tables) round-trip without pickle.

Two wire formats share one `serialize`/`deserialize` surface:

- **v1 (json)**: the historical format — UTF-8 JSON, arrays embedded as
  base64'd `.npy` blobs. ~1.78x byte inflation once the cryptor base64s the
  whole thing again, and several full in-memory copies per hop.
- **v2 (binary, default)**: a framed container (docs/wire_format.md)::

      b"V6T\\x02" | u32 header_len (LE) | header JSON | aligned raw buffers

  The header carries the payload STRUCTURE (dicts/lists/scalars plus tagged
  placeholders); every ndarray/bytes leaf's raw bytes land in the buffer
  region, 64-byte aligned, **without base64 and without intermediate
  copies**: encode hands `memoryview`s straight to one final ``join``;
  decode wraps slices with zero-copy ``np.frombuffer`` (the resulting
  arrays are read-only views into the blob). Boundaries that hand arrays
  to algorithm/researcher code pass ``deserialize(..., writable=True)`` to
  materialize one copy with v1's writable ``np.load`` semantics.

``deserialize`` auto-detects the format from the magic, so v1 blobs (old
runs, old peers) always decode. Opt out of v2 with ``V6T_WIRE_FORMAT=v1``
(or per call via ``serialize(..., format="v1")``).

JSON-header semantics match v1 exactly: tuples decode as lists, dict keys
stringify, and ``np.float64`` scalars (a ``float`` subclass) ride as plain
floats on the v1 path. Narrower numpy scalars (``np.float32``,
``np.int64``, ...) are preserved through BOTH formats via the ``npscalar``
tag, and raw ``bytes`` payloads are first-class (``bytes`` tag) so
secure-aggregation key adverts no longer pre-encode by hand.

Every encode/decode also feeds `WIRE_STATS` (bytes + seconds, plus the
cryptor's broadcast dedup hits) — the per-round wire accounting surfaced by
``Federation.task_timing`` and `runtime.metrics`.
"""
from __future__ import annotations

import base64
import io
import json
import os
import struct
import threading
import time
from typing import Any

import numpy as np

# v2 frame magic: 3 ASCII bytes + format version.
MAGIC_V2 = b"V6T\x02"
_HEADER_LEN = struct.Struct("<I")
_ALIGN = 64  # buffer alignment inside the frame (TPU/XLA-friendly)

DEFAULT_FORMAT_ENV = "V6T_WIRE_FORMAT"
_V1_NAMES = ("v1", "json")
_V2_NAMES = ("v2", "binary")


def normalize_format(fmt: str) -> str:
    """Canonicalize a wire-format name to "v1"/"v2"; ValueError on typos —
    config surfaces (node policies) call this at STARTUP so a bad value
    fails the node, not every task."""
    low = fmt.strip().lower()
    if low in _V1_NAMES:
        return "v1"
    if low in _V2_NAMES:
        return "v2"
    raise ValueError(
        f"unknown wire format {fmt!r} (expected v1|json|v2|binary)"
    )


def default_format() -> str:
    """The process-wide wire format: ``V6T_WIRE_FORMAT`` env (v1|json|
    v2|binary), defaulting to v2."""
    fmt = os.environ.get(DEFAULT_FORMAT_ENV, "")
    if not fmt.strip():
        return "v2"
    try:
        return normalize_format(fmt)
    except ValueError as e:
        raise ValueError(f"{DEFAULT_FORMAT_ENV}: {e}") from e


# ------------------------------------------------------------------ metrics
class WireStats:
    """Thread-safe process-wide wire accounting.

    `serialize`/`deserialize` record bytes + seconds per call; the cryptor's
    broadcast path records how many full AES passes it AVOIDED
    (``broadcast_dedup_hits`` — N-1 per N-recipient broadcast). Snapshot via
    `snapshot()`; bench/metrics consumers diff snapshots around a round.
    """

    _FIELDS = (
        "encode_calls", "encode_bytes", "encode_s",
        "decode_calls", "decode_bytes", "decode_s",
        "broadcasts", "broadcast_recipients", "broadcast_dedup_hits",
    )

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            for f in self._FIELDS:
                setattr(self, f, 0 if not f.endswith("_s") else 0.0)

    def record_encode(self, nbytes: int, seconds: float) -> None:
        with self._lock:
            self.encode_calls += 1
            self.encode_bytes += int(nbytes)
            self.encode_s += float(seconds)

    def record_decode(self, nbytes: int, seconds: float) -> None:
        with self._lock:
            self.decode_calls += 1
            self.decode_bytes += int(nbytes)
            self.decode_s += float(seconds)

    def record_broadcast(self, n_recipients: int) -> None:
        with self._lock:
            self.broadcasts += 1
            self.broadcast_recipients += int(n_recipients)
            self.broadcast_dedup_hits += max(0, int(n_recipients) - 1)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {f: getattr(self, f) for f in self._FIELDS}


WIRE_STATS = WireStats()


# ----------------------------------------------------------- sparse buffers
class SparseVector:
    """Run-length/index sparse buffer — the v2 wire's first-class sparse
    type (gradient-compression PR, docs/compression.md).

    A flat COO vector: ``indices`` (ascending integer positions into a
    dense ``size``-element vector) and parallel ``values`` (any numeric
    dtype — f32 top-k survivors or int8 quantization codes). Positions not
    listed hold ``fill`` (0 by default — exactly what a dropped top-k
    coordinate means).

    On the v2 wire, indices and values ride as TWO aligned raw buffers
    (zero-copy decode, like ndarrays); on the v1 wire a SparseVector
    densifies to a plain ndarray tag so legacy peers decode it without
    knowing the type exists (``to_dense()`` semantics — the existing
    wire_format capability detection picks which encoding a peer gets).
    Decode validates index bounds: a tampered frame whose indices point
    outside ``[0, size)`` is rejected, never scattered out of bounds.
    """

    __slots__ = ("indices", "values", "size", "fill")

    def __init__(
        self,
        indices: Any,
        values: Any,
        size: int,
        fill: float = 0.0,
    ) -> None:
        indices = np.asarray(indices)
        values = np.asarray(values)
        if indices.ndim != 1 or values.ndim != 1:
            raise ValueError("SparseVector indices/values must be 1-D")
        if indices.dtype.kind not in "iu":
            raise ValueError(
                f"SparseVector indices must be integers, got {indices.dtype}"
            )
        _check_binary_dtype(values.dtype)
        if len(indices) != len(values):
            raise ValueError(
                f"SparseVector length mismatch: {len(indices)} indices vs "
                f"{len(values)} values"
            )
        size = int(size)
        if size < 0:
            raise ValueError("SparseVector size must be >= 0")
        if indices.size and (
            int(indices.min()) < 0 or int(indices.max()) >= size
        ):
            raise ValueError(
                "SparseVector index out of bounds for size "
                f"{size}: [{int(indices.min())}, {int(indices.max())}]"
            )
        self.indices = indices
        self.values = values
        self.size = size
        self.fill = fill

    @property
    def nnz(self) -> int:
        return int(self.indices.size)

    @property
    def density(self) -> float:
        return self.nnz / self.size if self.size else 0.0

    def wire_nbytes(self) -> int:
        """Exact v2 buffer bytes (indices + values, no alignment/header)."""
        return int(self.indices.nbytes) + int(self.values.nbytes)

    def to_dense(self) -> np.ndarray:
        """Materialize the dense vector (the v1-peer fallback encoding)."""
        out = np.full(self.size, self.fill, dtype=self.values.dtype)
        out[self.indices] = self.values
        return out

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, SparseVector)
            and self.size == other.size
            and self.fill == other.fill
            and self.values.dtype == other.values.dtype
            and np.array_equal(self.indices, other.indices)
            and np.array_equal(self.values, other.values)
        )

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"SparseVector(nnz={self.nnz}, size={self.size}, "
            f"dtype={self.values.dtype})"
        )


# ------------------------------------------------------------- v1 (json)
def _encode_v1(obj: Any) -> Any:
    import jax

    if isinstance(obj, SparseVector):
        # dense materialization for legacy peers: a v1 consumer decodes a
        # plain ndarray with fill at the dropped positions — semantically
        # the decompressed vector (see compress_flat's layout contract)
        arr = obj.to_dense()
        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        return {
            "__v6t__": "ndarray",
            "data": base64.b64encode(buf.getvalue()).decode("ascii"),
        }
    if isinstance(obj, np.generic):
        # preserve the scalar TYPE (np.float32(1.5) must not come back as a
        # 0-d ndarray — satellite fix); np.float64/np.int_ subclasses of
        # python numbers never reach this default hook (json handles them)
        return {
            "__v6t__": "npscalar",
            "dtype": obj.dtype.str,
            "data": base64.b64encode(obj.tobytes()).decode("ascii"),
        }
    if isinstance(obj, np.ndarray) or (
        hasattr(jax, "Array") and isinstance(obj, jax.Array)
    ):
        arr = np.asarray(obj)
        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        return {
            "__v6t__": "ndarray",
            "data": base64.b64encode(buf.getvalue()).decode("ascii"),
        }
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return {
            "__v6t__": "bytes",
            "data": base64.b64encode(bytes(obj)).decode("ascii"),
        }
    try:
        import pandas as pd

        if isinstance(obj, pd.DataFrame):
            return {"__v6t__": "dataframe", "data": obj.to_json(orient="split")}
        if isinstance(obj, pd.Series):
            return {"__v6t__": "series", "data": obj.to_json(orient="split")}
    except ImportError:  # pragma: no cover
        pass
    raise TypeError(f"not JSON-serializable: {type(obj)}")


def _decode_v1(d: dict[str, Any]) -> Any:
    tag = d.get("__v6t__")
    if tag is None:
        return d
    if tag == "ndarray":
        buf = io.BytesIO(base64.b64decode(d["data"]))
        return np.load(buf, allow_pickle=False)
    if tag == "npscalar":
        raw = base64.b64decode(d["data"])
        return np.frombuffer(raw, dtype=np.dtype(d["dtype"]))[0]
    if tag == "bytes":
        return base64.b64decode(d["data"])
    if tag == "dataframe":
        import pandas as pd

        return pd.read_json(io.StringIO(d["data"]), orient="split")
    if tag == "series":
        import pandas as pd

        return pd.read_json(io.StringIO(d["data"]), orient="split", typ="series")
    raise ValueError(f"unknown payload tag {tag!r}")


# ------------------------------------------------------------- v2 (binary)
def _check_binary_dtype(dtype: np.dtype) -> None:
    if dtype.hasobject or dtype.kind == "V":
        raise TypeError(
            f"dtype {dtype} cannot ride the binary wire (object/void); "
            "convert to a plain numeric/bytes representation first"
        )


def _encode_v2(obj: Any, buffers: list[Any]) -> Any:
    """Payload -> JSON-able header structure; raw buffers appended to
    ``buffers`` as memoryviews (no copies here)."""
    import jax

    if obj is None or isinstance(obj, (bool, int, float, str)):
        # np.float64 subclasses float, so (exactly like v1's json.dumps) it
        # rides as a plain float; narrower np scalars fall through to the
        # npscalar tag below and keep their dtype
        return obj
    if isinstance(obj, SparseVector):
        # first-class sparse node: indices and values as two aligned raw
        # buffers — zero-copy decode, no densification on the wire
        idx = np.ascontiguousarray(obj.indices)
        vals = np.ascontiguousarray(obj.values)
        buffers.append(memoryview(idx).cast("B") if idx.size else b"")
        buffers.append(memoryview(vals).cast("B") if vals.size else b"")
        return {
            "__v6t__": "sparse",
            "index_buffer": len(buffers) - 2,
            "value_buffer": len(buffers) - 1,
            "index_dtype": idx.dtype.str,
            "value_dtype": vals.dtype.str,
            "size": int(obj.size),
            "fill": float(obj.fill),
        }
    if isinstance(obj, np.generic):
        return {
            "__v6t__": "npscalar",
            "dtype": obj.dtype.str,
            "data": base64.b64encode(obj.tobytes()).decode("ascii"),
        }
    if isinstance(obj, np.ndarray) or (
        hasattr(jax, "Array") and isinstance(obj, jax.Array)
    ):
        arr = np.asarray(obj)
        _check_binary_dtype(arr.dtype)
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
        # cast("B") rejects zero-size views; an empty array has no bytes
        buffers.append(memoryview(arr).cast("B") if arr.size else b"")
        return {
            "__v6t__": "ndarray",
            "buffer": len(buffers) - 1,
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
            "order": "C",
        }
    if isinstance(obj, (bytes, bytearray, memoryview)):
        if isinstance(obj, bytes):
            buf: Any = memoryview(obj)
        else:
            mv = memoryview(obj)
            if mv.nbytes == 0:
                buf = b""  # cast("B") rejects zero-size views
            elif mv.c_contiguous:
                buf = mv.cast("B")
            else:
                # sliced/strided view (v1 accepted it via bytes()): one
                # unavoidable copy
                buf = memoryview(mv.tobytes())
        buffers.append(buf)
        return {"__v6t__": "bytes", "buffer": len(buffers) - 1}
    if isinstance(obj, dict):
        return {
            _json_key(k): _encode_v2(v, buffers) for k, v in obj.items()
        }
    if isinstance(obj, (list, tuple)):
        return [_encode_v2(v, buffers) for v in obj]
    try:
        import pandas as pd

        if isinstance(obj, pd.DataFrame):
            return {"__v6t__": "dataframe", "data": obj.to_json(orient="split")}
        if isinstance(obj, pd.Series):
            return {"__v6t__": "series", "data": obj.to_json(orient="split")}
    except ImportError:  # pragma: no cover
        pass
    raise TypeError(f"not wire-serializable: {type(obj)}")


def _json_key(k: Any) -> str:
    """Dict-key coercion with json.dumps semantics, so both wire formats
    agree: True->'true', None->'null', numbers via repr, str verbatim —
    anything else is a TypeError exactly like v1's json.dumps."""
    if isinstance(k, str):
        return k
    if k is True:
        return "true"
    if k is False:
        return "false"
    if k is None:
        return "null"
    if isinstance(k, (int, float)):
        return repr(k) if isinstance(k, float) else str(k)
    raise TypeError(
        f"keys must be str, int, float, bool or None, not {type(k)}"
    )


def _align(offset: int) -> int:
    return (offset + _ALIGN - 1) & ~(_ALIGN - 1)


def _serialize_v2(payload: Any) -> bytes:
    buffers: list[Any] = []
    structure = _encode_v2(payload, buffers)
    lengths = [b.nbytes if isinstance(b, memoryview) else len(b)
               for b in buffers]
    header = json.dumps(
        {"payload": structure, "buffers": lengths},
        separators=(",", ":"),
    ).encode("utf-8")
    parts: list[Any] = [MAGIC_V2, _HEADER_LEN.pack(len(header)), header]
    pos = len(MAGIC_V2) + _HEADER_LEN.size + len(header)
    for buf, n in zip(buffers, lengths):
        aligned = _align(pos)
        if aligned != pos:
            parts.append(b"\x00" * (aligned - pos))
        parts.append(buf)
        pos = aligned + n
    # ONE copy total: join gathers the memoryviews into the output frame.
    return b"".join(parts)


def _decode_v2(node: Any, views: list[memoryview], writable: bool) -> Any:
    if isinstance(node, list):
        return [_decode_v2(v, views, writable) for v in node]
    if not isinstance(node, dict):
        return node
    tag = node.get("__v6t__")
    if tag is None:
        return {k: _decode_v2(v, views, writable) for k, v in node.items()}
    if tag == "ndarray":
        dtype = np.dtype(node["dtype"])
        _check_binary_dtype(dtype)
        mv = views[node["buffer"]]
        arr = np.frombuffer(mv, dtype=dtype).reshape(node["shape"])
        # zero-copy view into the frame, read-only by construction;
        # writable=True materializes one copy (v1 np.load semantics)
        return arr.copy() if writable else arr
    if tag == "sparse":
        idx_dtype = np.dtype(node["index_dtype"])
        if idx_dtype.kind not in "iu":
            raise ValueError(
                f"malformed v2 frame: sparse index dtype {idx_dtype} "
                "is not an integer type"
            )
        val_dtype = np.dtype(node["value_dtype"])
        _check_binary_dtype(val_dtype)
        idx = np.frombuffer(views[node["index_buffer"]], dtype=idx_dtype)
        vals = np.frombuffer(views[node["value_buffer"]], dtype=val_dtype)
        if writable:
            idx, vals = idx.copy(), vals.copy()
        try:
            # the ctor enforces the bounds contract: tampered indices
            # pointing outside [0, size) must die HERE, at decode — never
            # reach a consumer's scatter
            return SparseVector(
                idx, vals, int(node["size"]), fill=node.get("fill", 0.0)
            )
        except ValueError as e:
            raise ValueError(f"malformed v2 frame: {e}") from e
    if tag == "npscalar":
        raw = base64.b64decode(node["data"])
        return np.frombuffer(raw, dtype=np.dtype(node["dtype"]))[0]
    if tag == "bytes":
        return bytes(views[node["buffer"]])
    if tag == "dataframe":
        import pandas as pd

        return pd.read_json(io.StringIO(node["data"]), orient="split")
    if tag == "series":
        import pandas as pd

        return pd.read_json(io.StringIO(node["data"]), orient="split",
                            typ="series")
    raise ValueError(f"unknown payload tag {tag!r}")


def _read_v2_header(raw: bytes) -> tuple[dict[str, Any], int]:
    """Parse a v2 frame's header; returns (header dict, buffer-region
    offset). The single definition of the frame prefix layout — shared by
    `deserialize` and `peek_structure` so they can never diverge."""
    prefix = len(MAGIC_V2) + _HEADER_LEN.size
    if len(raw) < prefix:
        raise ValueError("malformed v2 frame: truncated before header")
    (hlen,) = _HEADER_LEN.unpack(raw[len(MAGIC_V2):prefix])
    if len(raw) < prefix + hlen:
        raise ValueError("malformed v2 frame: truncated header")
    try:
        header = json.loads(raw[prefix:prefix + hlen])
        header["payload"], header["buffers"]  # required keys
    except (ValueError, KeyError, TypeError) as e:
        raise ValueError(f"malformed v2 frame header: {e}") from e
    return header, prefix + hlen


def _deserialize_v2(blob: bytes, writable: bool) -> Any:
    header, pos = _read_v2_header(blob)
    mv = memoryview(blob)
    views: list[memoryview] = []
    for n in header["buffers"]:
        off = _align(pos)
        if mv.nbytes < off + n:
            raise ValueError("malformed v2 frame: truncated buffer region")
        views.append(mv[off:off + n])
        pos = off + n
    return _decode_v2(header["payload"], views, writable)


# ---------------------------------------------------------------- public API
def _normalize_blob(blob: bytes | bytearray | memoryview | str) -> bytes:
    if isinstance(blob, str):
        return blob.encode("utf-8")
    if isinstance(blob, (bytearray, memoryview)):
        return bytes(blob)
    return blob


def serialize(payload: Any, format: str | None = None) -> bytes:
    """Payload -> wire bytes. ``format``: "v1"/"json", "v2"/"binary", or
    None to follow ``V6T_WIRE_FORMAT`` (default v2)."""
    fmt = default_format() if format is None else normalize_format(format)
    t0 = time.perf_counter()
    if fmt == "v2":
        blob = _serialize_v2(payload)
    else:
        blob = json.dumps(payload, default=_encode_v1).encode("utf-8")
    WIRE_STATS.record_encode(len(blob), time.perf_counter() - t0)
    return blob


def deserialize(
    blob: bytes | bytearray | memoryview | str, writable: bool = False
) -> Any:
    """Wire bytes -> payload; the format is auto-detected (v2 magic, else
    v1 JSON), so old blobs and old peers keep decoding.

    ``writable=False`` (default) decodes v2 arrays as zero-copy read-only
    views into the blob — the fast path for relays and read-only consumers.
    ``writable=True`` materializes one copy per array (v1 ``np.load``
    semantics); every boundary that hands arrays to third-party algorithm
    code (wrap.py INPUT_FILE, the sandbox OUTPUT_FILE harvest, the node
    daemon's input decode, client result fetches) passes it so in-place
    ``weights += delta`` keeps working exactly as under v1.
    """
    t0 = time.perf_counter()
    raw = _normalize_blob(blob)
    if raw[: len(MAGIC_V2)] == MAGIC_V2:
        out = _deserialize_v2(raw, writable)
    else:
        out = json.loads(raw.decode("utf-8"), object_hook=_decode_v1)
    WIRE_STATS.record_decode(len(raw), time.perf_counter() - t0)
    return out


def peek_structure(blob: bytes | bytearray | memoryview | str) -> Any:
    """The JSON-level structure of a wire blob WITHOUT materializing any
    array buffers: v2 -> the frame's header structure (tagged leaves stay
    as placeholder dicts), v1 -> plain ``json.loads`` with no object hook
    (base64 array strings stay strings). For relays that only need a
    metadata field (e.g. the proxy reading ``input_["method"]``) — decoding
    a 10 MiB weight payload to read one string is the old bug this avoids.
    Not recorded in WIRE_STATS (nothing payload-sized is touched)."""
    raw = _normalize_blob(blob)
    if raw[: len(MAGIC_V2)] == MAGIC_V2:
        return _read_v2_header(raw)[0]["payload"]
    return json.loads(raw.decode("utf-8"))


def wire_nbytes(payload: Any) -> int | None:
    """Cheap on-wire size estimate of ``payload`` in the v2 format — array
    and bytes leaves by exact ``nbytes`` WITHOUT touching (or device->host
    transferring) their data, structure by JSON length, DataFrames by
    in-memory column footprint. None when the payload holds something the
    wire cannot carry (host-mode in-process results may be arbitrary
    objects). Used by the run-lifecycle wire accounting so straggler
    analysis can tell compute-bound from transfer-bound stations.

    Sparse/quantized buffers are sized by what actually rides the wire:
    a `SparseVector` counts its index + value buffers (NOT the dense
    ``size * itemsize`` it stands for), and int8 quantization codes count
    one byte per element via their real ``nbytes`` — so
    ``Run.input/result_wire_bytes`` and ``metrics.wire_totals`` stay
    truthful under compression.
    """
    try:
        total = 0

        def walk(obj: Any) -> Any:
            nonlocal total
            if obj is None or isinstance(obj, (bool, int, float, str)):
                return obj
            if isinstance(obj, SparseVector):
                # two aligned buffers + the sparse header node — never the
                # dense footprint this vector REPLACES on the wire
                total += _align(int(obj.indices.nbytes))
                total += _align(int(obj.values.nbytes))
                total += 128  # header node (tag, dtypes, size, buffer ids)
                return 0
            if isinstance(obj, np.generic):
                total += int(obj.dtype.itemsize) + 32
                return 0
            if isinstance(obj, (bytes, bytearray, memoryview)):
                total += _align(len(obj))
                return 0
            if isinstance(obj, dict):
                return {str(k): walk(v) for k, v in obj.items()}
            if isinstance(obj, (list, tuple)):
                return [walk(v) for v in obj]
            nbytes = getattr(obj, "nbytes", None)
            shape = getattr(obj, "shape", None)
            if nbytes is not None and shape is not None:
                # ndarray / jax.Array (possibly device-resident): size from
                # metadata only — never np.asarray here
                total += _align(int(nbytes)) + 64
                return 0
            try:
                import pandas as pd

                if isinstance(obj, (pd.DataFrame, pd.Series)):
                    total += int(obj.memory_usage(deep=False).sum()) \
                        if hasattr(obj, "memory_usage") else 0
                    return 0
            except ImportError:  # pragma: no cover
                pass
            raise TypeError(type(obj))

        skeleton = walk(payload)
        total += len(json.dumps(skeleton, separators=(",", ":"),
                                default=str))
        total += len(MAGIC_V2) + _HEADER_LEN.size
        return int(total)
    except (TypeError, ValueError):
        return None
