"""Task payload (de)serialization.

Parity: the reference serializes task input/results as JSON written to the
container's INPUT_FILE/OUTPUT_FILE (SURVEY.md §2 item 18). JSON stays the
interchange default; numpy/jax arrays and pandas objects get a tagged
encoding so federated payloads (model weights, statistics tables) round-trip
without pickle (the reference moved away from pickle for the same
security reason).
"""
from __future__ import annotations

import base64
import io
import json
from typing import Any

import numpy as np


def _encode(obj: Any) -> Any:
    import jax

    if isinstance(obj, (np.ndarray, np.generic)) or (
        hasattr(jax, "Array") and isinstance(obj, jax.Array)
    ):
        arr = np.asarray(obj)
        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        return {
            "__v6t__": "ndarray",
            "data": base64.b64encode(buf.getvalue()).decode("ascii"),
        }
    try:
        import pandas as pd

        if isinstance(obj, pd.DataFrame):
            return {"__v6t__": "dataframe", "data": obj.to_json(orient="split")}
        if isinstance(obj, pd.Series):
            return {"__v6t__": "series", "data": obj.to_json(orient="split")}
    except ImportError:  # pragma: no cover
        pass
    raise TypeError(f"not JSON-serializable: {type(obj)}")


def _decode(d: dict[str, Any]) -> Any:
    tag = d.get("__v6t__")
    if tag is None:
        return d
    if tag == "ndarray":
        buf = io.BytesIO(base64.b64decode(d["data"]))
        return np.load(buf, allow_pickle=False)
    if tag == "dataframe":
        import pandas as pd

        return pd.read_json(io.StringIO(d["data"]), orient="split")
    if tag == "series":
        import pandas as pd

        return pd.read_json(io.StringIO(d["data"]), orient="split", typ="series")
    raise ValueError(f"unknown payload tag {tag!r}")


def serialize(payload: Any) -> bytes:
    return json.dumps(payload, default=_encode).encode("utf-8")


def deserialize(blob: bytes | str) -> Any:
    if isinstance(blob, bytes):
        blob = blob.decode("utf-8")
    return json.loads(blob, object_hook=_decode)
