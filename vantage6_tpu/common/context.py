"""Instance contexts + configuration manager.

Parity: vantage6-common ``AppContext``/``NodeContext``/``ServerContext`` and
``ConfigurationManager`` (SURVEY.md §2 item 22) — an *instance* (one named
server / node / store deployment) owns a YAML config file in a well-known
directory plus per-instance log/data dirs; contexts locate, validate and
expose these.

Directory layout (XDG-style instead of appdirs)::

    $XDG_CONFIG_HOME/vantage6_tpu/<kind>/<name>.yaml   config
    $XDG_DATA_HOME/vantage6_tpu/<kind>/<name>/         data dir
    $XDG_STATE_HOME/vantage6_tpu/<kind>/<name>/log/    logs
"""
from __future__ import annotations

import copy
import os
from pathlib import Path
from typing import Any, Callable

import yaml

from vantage6_tpu.common.log import setup_logging


class ConfigurationError(Exception):
    pass


def _xdg(var: str, default: str) -> Path:
    return Path(os.environ.get(var, os.path.expanduser(default)))


def config_root(system_folders: bool = False) -> Path:
    if system_folders:
        return Path("/etc/vantage6_tpu")
    return _xdg("XDG_CONFIG_HOME", "~/.config") / "vantage6_tpu"


def data_root(system_folders: bool = False) -> Path:
    if system_folders:
        return Path("/var/lib/vantage6_tpu")
    return _xdg("XDG_DATA_HOME", "~/.local/share") / "vantage6_tpu"


def state_root(system_folders: bool = False) -> Path:
    if system_folders:
        return Path("/var/log/vantage6_tpu")
    return _xdg("XDG_STATE_HOME", "~/.local/state") / "vantage6_tpu"


class Configuration(dict):
    """A validated config mapping with attribute access."""

    def __getattr__(self, key: str) -> Any:
        try:
            return self[key]
        except KeyError as e:
            raise AttributeError(key) from e


# Per-kind required keys + per-key validators (a lightweight stand-in for the
# reference's `schema` package validation).
Validator = Callable[[Any], bool]
SCHEMAS: dict[str, dict[str, tuple[bool, Validator]]] = {
    "node": {
        "api_url": (True, lambda v: isinstance(v, str) and v != ""),
        "api_key": (True, lambda v: isinstance(v, str) and v != ""),
        "databases": (False, lambda v: isinstance(v, list)),
        "encryption": (False, lambda v: isinstance(v, dict)),
        "policies": (False, lambda v: isinstance(v, dict)),
        "logging": (False, lambda v: isinstance(v, dict)),
    },
    "server": {
        "port": (False, lambda v: isinstance(v, int)),
        "uri": (False, lambda v: isinstance(v, str)),
        "jwt_secret": (False, lambda v: isinstance(v, str)),
        "logging": (False, lambda v: isinstance(v, dict)),
    },
    "store": {
        "port": (False, lambda v: isinstance(v, int)),
        "uri": (False, lambda v: isinstance(v, str)),
        "logging": (False, lambda v: isinstance(v, dict)),
    },
    "federation": {},  # validated by core.config.FederationConfig instead
}


class ConfigurationManager:
    """Loads + validates one instance's YAML config."""

    def __init__(self, kind: str):
        if kind not in SCHEMAS:
            raise ConfigurationError(
                f"unknown config kind {kind!r}; expected {sorted(SCHEMAS)}"
            )
        self.kind = kind

    def load(self, path: str | Path) -> Configuration:
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        if not isinstance(raw, dict):
            raise ConfigurationError(f"{path}: config must be a mapping")
        return self.validate(raw, source=str(path))

    def validate(
        self, raw: dict[str, Any], source: str = "<dict>"
    ) -> Configuration:
        schema = SCHEMAS[self.kind]
        for key, (required, check) in schema.items():
            if key not in raw:
                if required:
                    raise ConfigurationError(
                        f"{source}: missing required key {key!r}"
                    )
                continue
            if not check(raw[key]):
                raise ConfigurationError(f"{source}: invalid value for {key!r}")
        # Deep copy so interpolation never mutates the caller's dict — a
        # saved config must keep its `${VAR}` placeholders, not the resolved
        # secrets.
        cfg = Configuration(copy.deepcopy(raw))
        _interp_env_deep(cfg)
        return cfg

    def save(self, cfg: dict[str, Any], path: str | Path) -> None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            yaml.safe_dump(dict(cfg), f, sort_keys=False)


def _interp_env_deep(obj: Any) -> None:
    """In-place `${VAR}` interpolation in all string values."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            if isinstance(v, str):
                obj[k] = os.path.expandvars(v)
            else:
                _interp_env_deep(v)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            if isinstance(v, str):
                obj[i] = os.path.expandvars(v)
            else:
                _interp_env_deep(v)


class AppContext:
    """Base context: name + kind -> config, data dir, log dir, logger."""

    kind = "federation"

    def __init__(
        self,
        name: str,
        config_path: str | Path | None = None,
        system_folders: bool = False,
    ):
        self.name = name
        self.system_folders = system_folders
        self.config_path = Path(
            config_path
            if config_path is not None
            else self.default_config_path(name, system_folders)
        )
        if not self.config_path.exists():
            raise ConfigurationError(
                f"no {self.kind} configuration {name!r} at {self.config_path}"
            )
        self.config = ConfigurationManager(self.kind).load(self.config_path)
        self.log = setup_logging(
            f"{self.kind}/{name}",
            level=(self.config.get("logging", {}) or {}).get("level", "INFO"),
            log_dir=self.log_dir,
        )

    # ------------------------------------------------------------------ paths
    @classmethod
    def default_config_path(cls, name: str, system_folders: bool = False) -> Path:
        return config_root(system_folders) / cls.kind / f"{name}.yaml"

    @classmethod
    def available_configurations(cls, system_folders: bool = False) -> list[str]:
        d = config_root(system_folders) / cls.kind
        return sorted(p.stem for p in d.glob("*.yaml")) if d.exists() else []

    @classmethod
    def config_exists(cls, name: str, system_folders: bool = False) -> bool:
        return cls.default_config_path(name, system_folders).exists()

    @classmethod
    def create(
        cls,
        name: str,
        config: dict[str, Any],
        system_folders: bool = False,
        **kw: Any,
    ) -> "AppContext":
        """Write a new instance config and return its context."""
        path = cls.default_config_path(name, system_folders)
        if path.exists():
            raise ConfigurationError(f"{cls.kind} config {name!r} exists")
        manager = ConfigurationManager(cls.kind)
        manager.validate(config, source=f"create({name!r})")
        manager.save(config, path)
        return cls(name, system_folders=system_folders, **kw)

    @property
    def data_dir(self) -> Path:
        p = data_root(self.system_folders) / self.kind / self.name
        p.mkdir(parents=True, exist_ok=True)
        return p

    @property
    def log_dir(self) -> Path:
        p = state_root(self.system_folders) / self.kind / self.name / "log"
        p.mkdir(parents=True, exist_ok=True)
        return p


class NodeContext(AppContext):
    kind = "node"

    @property
    def databases(self) -> list[dict[str, Any]]:
        return self.config.get("databases", []) or []

    @property
    def api_url(self) -> str:
        return self.config["api_url"]

    @property
    def api_key(self) -> str:
        return self.config["api_key"]

    @property
    def private_key_path(self) -> Path:
        enc = self.config.get("encryption", {}) or {}
        return Path(enc.get("private_key", self.data_dir / "private_key.pem"))


class ServerContext(AppContext):
    kind = "server"

    DEFAULT_PORT = 7601

    @property
    def port(self) -> int:
        return int(self.config.get("port", self.DEFAULT_PORT))

    @property
    def uri(self) -> str:
        """Database URI; default is a sqlite file in the instance data dir."""
        return self.config.get("uri", f"sqlite:///{self.data_dir}/server.db")


class StoreContext(AppContext):
    kind = "store"

    DEFAULT_PORT = 7602

    @property
    def port(self) -> int:
        return int(self.config.get("port", self.DEFAULT_PORT))

    @property
    def uri(self) -> str:
        return self.config.get("uri", f"sqlite:///{self.data_dir}/store.db")
