"""2-layer CNN — the flagship FedAvg model (BASELINE.md workload 3).

The reference has no models at all (math lives in external algorithm
containers, SURVEY.md §1); this is the TPU-native counterpart of the CNN an
algorithm repo would ship for FedAvg-MNIST. bfloat16 activations keep the
convs on the MXU; params stay float32 for stable aggregation across stations.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class CNN(nn.Module):
    """conv(32) -> pool -> conv(64) -> pool -> dense(128) -> dense(classes)."""

    num_classes: int = 10
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.compute_dtype)
        x = nn.Conv(32, (3, 3), dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)


def init_cnn(key: jax.Array, input_shape=(1, 28, 28, 1), num_classes=10):
    model = CNN(num_classes=num_classes)
    params = model.init(key, jnp.zeros(input_shape, jnp.float32))["params"]
    return model, params


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=1) == labels).astype(jnp.float32))
