"""Logistic / linear models for tabular federated analysis.

Counterpart of the reference's v6-logistic-regression-py workload
(BASELINE.md workload 2) — there, each organization runs sklearn-ish local
steps and the central task averages coefficients; here the model is a jax
pytree usable in both host-mode partials and the device-mode FedAvg engine.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, jax.Array]


def init_logistic(key: jax.Array, n_features: int, n_classes: int = 2) -> Params:
    """Binary (n_classes=2 -> single logit) or multinomial logistic params."""
    out = 1 if n_classes == 2 else n_classes
    return {
        "w": jax.random.normal(key, (n_features, out)) * 0.01,
        "b": jnp.zeros((out,)),
    }


def logits(params: Params, x: jax.Array) -> jax.Array:
    return x @ params["w"] + params["b"]


def binary_loss(params: Params, x: jax.Array, y: jax.Array,
                l2: float = 0.0) -> jax.Array:
    """Mean negative log-likelihood, y in {0,1}, optional L2."""
    z = logits(params, x)[:, 0]
    nll = jnp.mean(jnp.logaddexp(0.0, z) - y * z)
    return nll + l2 * jnp.sum(params["w"] ** 2)


def multinomial_loss(params: Params, x: jax.Array, y: jax.Array,
                     l2: float = 0.0) -> jax.Array:
    logp = jax.nn.log_softmax(logits(params, x))
    nll = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
    return nll + l2 * jnp.sum(params["w"] ** 2)


def predict_proba(params: Params, x: jax.Array) -> jax.Array:
    z = logits(params, x)
    if z.shape[1] == 1:
        p = jax.nn.sigmoid(z[:, 0])
        return jnp.stack([1 - p, p], axis=1)
    return jax.nn.softmax(z)


def binary_accuracy(params: Params, x: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.mean(((logits(params, x)[:, 0] > 0) == (y > 0.5)).astype(
        jnp.float32))
