"""The node daemon — one data station's agent.

Parity: vantage6-node `Node` (SURVEY.md §2 item 10, call stack §3.3):
authenticate with the api_key → set up encryption + proxy + runner →
go online → sync missed work → listen for tasks → execute → report.
The reference listens on a SocketIO socket; here the daemon drains the
server's room-scoped event cursor (push via websockets arrives with the
same payloads — the cursor IS the reconnect path in both designs).
"""
from __future__ import annotations

import queue
import random
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Any, Callable

from vantage6_tpu.common.encryption import CryptorBase, DummyCryptor, RSACryptor
from vantage6_tpu.common.rest import RestError, RestSession
from vantage6_tpu.common.enums import TaskStatus
from vantage6_tpu.common.log import setup_logging
from vantage6_tpu.common.serialization import deserialize
from vantage6_tpu.node.gates import VPNManager
from vantage6_tpu.node.proxy import NodeProxy
from vantage6_tpu.node.runner import (
    PolicyViolation,
    RunSpec,
    TaskRunner,
    UnknownAlgorithm,
)
from vantage6_tpu.runtime.tracing import TRACER, parse_traceparent

log = setup_logging("vantage6_tpu/node")


def backoff_delay(
    base: float,
    failures: int,
    cap: float = 10.0,
    rng: Callable[[], float] = random.random,
) -> float:
    """Capped exponential backoff with jitter for the event-poll retry.

    Failure n sleeps uniform(0.5, 1.0) × min(cap, base · 2^(n-1)). The
    jitter is the point: 32 daemons that all lost the same restarting
    server must retry DECORRELATED, not hammer it again in lockstep at a
    fixed multiple of their shared poll_interval.
    """
    delay = min(cap, base * (2 ** max(0, failures - 1)))
    return delay * (0.5 + 0.5 * rng())


class _PendingReport:
    """One queued run PATCH awaiting its batch flush."""

    __slots__ = ("run_id", "fields", "done", "error")

    def __init__(self, run_id: int, fields: dict[str, Any]):
        self.run_id = run_id
        self.fields = fields
        self.done = threading.Event()
        self.error: Exception | None = None


class _BatchReporter:
    """Coalesces concurrent run status/result PATCHes into one
    ``PATCH /api/run/batch`` request.

    Worker threads call `submit_and_wait` — synchronous per caller (the
    ACTIVE-before-barrier and report-before-return orderings are
    preserved), but the TRANSPORT batches whatever is queued at flush
    time: when several of the daemon's workers finish near-simultaneously
    their reports ride one request. A lone report degrades to a batch of
    one. Per-item server outcomes (409 terminal, 403, ...) are re-raised
    in the submitting thread as RestError, so every existing caller-side
    handler (the 409 "already terminal" path) works unchanged. If the
    server lacks the batch endpoint (404/405: un-upgraded server), the
    items are replayed as per-run PATCHes and the daemon pins itself to
    the per-run path.
    """

    def __init__(self, daemon: "NodeDaemon"):
        self._daemon = daemon
        self._q: "queue.Queue[_PendingReport]" = queue.Queue()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def submit_and_wait(self, run_id: int, fields: dict[str, Any]) -> None:
        item = _PendingReport(run_id, fields)
        self._ensure_thread()
        self._q.put(item)
        if not item.done.wait(timeout=120.0):
            raise RestError(504, f"batched report for run {run_id} timed out")
        if item.error is not None:
            raise item.error

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="v6t-report"
                )
                self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            self._flush(self._drain(first))
        # stop requested: flush whatever is still queued so a final
        # COMPLETED report is never abandoned mid-shutdown
        try:
            while True:
                self._flush(self._drain(self._q.get_nowait()))
        except queue.Empty:
            pass

    def _drain(self, first: _PendingReport) -> list[_PendingReport]:
        batch = [first]
        while len(batch) < 250:
            try:
                batch.append(self._q.get_nowait())
            except queue.Empty:
                break
        return batch

    def _flush(self, batch: list[_PendingReport]) -> None:
        d = self._daemon
        if d._batch_ok is False:
            for item in batch:
                self._flush_single(item)
            return
        try:
            resp = d.request(
                "PATCH",
                "run/batch",
                {"runs": [{"id": it.run_id, **it.fields} for it in batch]},
            )
        except RestError as e:
            if e.status in (404, 405):
                d._batch_ok = False  # un-upgraded server: per-run forever
                for item in batch:
                    self._flush_single(item)
                return
            self._finish_all(batch, e)
            return
        except Exception as e:
            self._finish_all(batch, e)
            return
        by_id = {r.get("id"): r for r in resp.get("data", [])}
        for item in batch:
            r = by_id.get(item.run_id)
            if r is None:
                item.error = RestError(
                    500, f"batch response missing run {item.run_id}"
                )
            elif r.get("status_code", 200) >= 400:
                item.error = RestError(r["status_code"], r.get("msg", ""))
            item.done.set()

    def _flush_single(self, item: _PendingReport) -> None:
        try:
            self._daemon.request("PATCH", f"run/{item.run_id}", item.fields)
        except Exception as e:
            item.error = e
        item.done.set()

    @staticmethod
    def _finish_all(batch: list[_PendingReport], err: Exception) -> None:
        for item in batch:
            item.error = err
            item.done.set()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


class NodeDaemon:
    def __init__(
        self,
        api_url: str,
        api_key: str,
        algorithms: dict[str, str] | None = None,
        databases: list[dict[str, Any]] | None = None,
        policies: dict[str, Any] | None = None,
        private_key: str | Path | None = None,
        mode: str = "sandbox",
        poll_interval: float = 0.25,
        sync_interval: float = 15.0,
        ping_interval: float | None = None,
        fleet_push_interval: float | None = None,
        name: str = "",
        max_concurrent_runs: int = 4,
        station_secret: str | bytes | None = None,
        vpn: dict[str, Any] | None = None,
        device_engine: dict[str, Any] | None = None,
        transport: str = "batched",
        event_wait: float = 2.0,
    ):
        # control-plane transport policy:
        # - transport="batched" (default): claim sweeps, per-run dispatch
        #   fetches and status reports ride the multi-run endpoints
        #   (POST /run/claim-batch, PATCH /run/batch), falling back to the
        #   per-run endpoints automatically against an un-upgraded server;
        #   "per-run" pins the legacy per-run path (mixed-version testing).
        # - event_wait>0: event polls long-poll (?wait=S) so a dispatched
        #   run wakes this daemon on event PROPAGATION, with the
        #   poll_interval sweep demoted to the anti-entropy fallback;
        #   0 pins the legacy fixed-interval polling.
        if transport not in ("batched", "per-run"):
            raise ValueError(
                f"transport must be 'batched' or 'per-run', got {transport!r}"
            )
        # Device-engine membership FIRST: jax.distributed must be joined
        # before anything initializes the jax backend. With a coordinator
        # configured this daemon becomes one process of the federation's
        # global device mesh (DCN scale-out, core.distributed); an empty
        # dict enables the engine on the local devices only. This is how
        # the control plane meets the TPU data plane: a server-submitted
        # engine="device" task executes as ONE SPMD program spanning every
        # member daemon's devices.
        self.device_engine_cfg = device_engine
        if device_engine is not None:
            from vantage6_tpu.core import distributed as _dist

            _dist.initialize(
                coordinator_address=device_engine.get("coordinator"),
                num_processes=device_engine.get("num_processes"),
                process_id=device_engine.get("process_id"),
                local_device_ids=device_engine.get("local_device_ids"),
                auto=bool(device_engine.get("auto", False)),
            )
        # replica-aware transport: `api_url` may be a comma-separated list
        # of server replica URLs (N stateless replicas over one shared
        # store — docs/control_plane.md). The daemon talks to ONE at a
        # time (api_urls[0] initially) and rotates to the next on a
        # connection-level failure; any replica serves any request, so a
        # rotation is invisible above the transport.
        self.api_urls = [
            u.strip().rstrip("/") for u in api_url.split(",") if u.strip()
        ]
        if not self.api_urls:
            raise ValueError("api_url must name at least one server URL")
        self._url_index = 0
        self.api_url = self.api_urls[0]
        self.api_key = api_key
        self.poll_interval = poll_interval
        self.sync_interval = sync_interval
        # ping-window bookkeeping (the server watchdog's daemon_lapsed
        # rule watches node.last_seen_at): the sync worker POSTs a ping at
        # least every ping_interval so a live daemon never lapses, and the
        # counters below tell a dump whether THIS side was failing to ping
        # or the server was failing to hear
        self.ping_interval = (
            min(sync_interval, 10.0) if ping_interval is None
            else max(0.1, float(ping_interval))
        )
        self.last_ping_at: float | None = None
        self.ping_failures = 0
        # double-dispatch ledger (see _execute_run's activation CAS)
        self.activations_won = 0
        self.activations_lost = 0
        self.transport = transport
        self.event_wait = max(0.0, float(event_wait))
        # None = capability unknown; False = server lacks the batch
        # endpoints / long-poll (detected once, then pinned)
        self._batch_ok: bool | None = (
            None if transport == "batched" else False
        )
        self._long_poll: bool | None = None
        self._poll_failures = 0
        # consecutive full replica-URL rotations that found NO reachable
        # server (reset on any success): drives the capped jittered
        # backoff between sweeps, so N daemons that lost the whole
        # control plane re-probe decorrelated instead of in lockstep
        self._rotation_streak = 0
        self._reporter = _BatchReporter(self)
        # run_id -> claim-batch entry (run dict + embedded task +
        # container token): what a batched claim prefetched so _execute
        # skips its per-run GET run / GET task / POST token round-trips
        self._prefetched: dict[int, dict[str, Any]] = {}  # guarded-by: _claim_lock
        self._access_token: str | None = None
        self._refresh_token: str | None = None
        self._rest = RestSession(
            self.api_url,
            token_getter=lambda: self._access_token,
            refresh=self._refresh,
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._cursor = 0
        self._killed: set[int] = set()
        # Runs execute in workers, NOT the listen thread: a central run
        # blocks on its own subtasks, which may land on THIS node — the
        # reference gets the same concurrency from parallel containers.
        self._pool = ThreadPoolExecutor(
            max_workers=max_concurrent_runs, thread_name_prefix="v6t-run"
        )
        self._claimed: set[int] = set()  # guarded-by: _claim_lock
        self._claim_lock = threading.Lock()
        # one sweep at a time: the sync worker and a post-restart resync
        # must not interleave their claim-check -> PATCH windows
        self._sync_lock = threading.Lock()
        # device-engine runs execute on a DEDICATED single worker in
        # ascending task-id order: collective SPMD programs must enter in
        # the same globally agreed order on every member daemon, or two
        # concurrent device tasks grabbed in opposite orders deadlock the
        # mesh (each member waiting inside a different program)
        self._device_queue: "queue.PriorityQueue[tuple[int, int]]" = (
            queue.PriorityQueue()
        )
        self._device_thread: threading.Thread | None = None
        self._sync_thread: threading.Thread | None = None

        # authenticate (reference: Node.__init__ authenticates first)
        data = self._post_raw(
            "token/node", {"api_key": api_key}, auth=False
        )
        self._access_token = data["access_token"]
        self._refresh_token = data["refresh_token"]
        self.info = data["node"]
        self.id: int = self.info["id"]
        self.organization_id: int = self.info["organization"]["id"]
        self.collaboration_id: int = self.info["collaboration"]["id"]
        self.name = name or self.info["name"]

        # fleet telemetry push (common/fleet.py): this daemon ships its
        # compact snapshot + flight-note deltas through the same
        # replica-rotating request path as everything else, on the sync
        # worker's cadence. Capability-pinned inside the pusher: against
        # a pre-fleet server the first 404 turns pushing into a no-op.
        from vantage6_tpu.common.fleet import FleetPusher

        self.fleet = FleetPusher(
            source=f"daemon:{self.name}",
            service="daemon",
            request=self.request,
            interval=fleet_push_interval,
        )

        collab = self.request("GET", f"collaboration/{self.collaboration_id}")
        self.encrypted: bool = bool(collab.get("encrypted"))

        # encryption: the node holds its organization's private key
        if self.encrypted:
            if private_key is None:
                raise ValueError(
                    "collaboration is encrypted: the node needs a "
                    "private_key path"
                )
            self.cryptor: CryptorBase = RSACryptor(private_key)
            self._register_public_key()
        else:
            self.cryptor = DummyCryptor()

        self.runner = TaskRunner(
            algorithms=algorithms,
            databases=databases,
            policies=policies,
            mode=mode,
            station_secret=station_secret,
            device_engine=device_engine is not None,
        )
        # VPN parity (reference item 13): no WireGuard exists here — the
        # manager's surviving job is registering algorithm-declared ports as
        # server Port entities so iterative/MPC algorithms can discover peers
        self.vpn = VPNManager(**(vpn or {}))
        if self.vpn.enabled:
            self.vpn.setup()  # logs the platform stance, returns False
        self.proxy = NodeProxy(
            server_url=self.api_url,
            cryptor=self.cryptor,
            collaboration_id=self.collaboration_id,
            encrypted=self.encrypted,
        )
        self._proxy_server = None

    @classmethod
    def from_context(cls, ctx: Any, **overrides: Any) -> "NodeDaemon":
        """Build from a NodeContext (YAML instance config)."""
        cfg = ctx.config
        return cls(
            api_url=cfg["api_url"],
            api_key=cfg["api_key"],
            algorithms=cfg.get("algorithms", {}) or {},
            databases=cfg.get("databases", []) or [],
            policies=cfg.get("policies", {}) or {},
            private_key=(
                str(ctx.private_key_path)
                if (cfg.get("encryption", {}) or {}).get("enabled")
                else None
            ),
            mode=(cfg.get("runner", {}) or {}).get("mode", "sandbox"),
            name=ctx.name,
            station_secret=cfg.get("station_secret") or None,
            vpn=cfg.get("vpn") or None,
            device_engine=cfg.get("device_engine"),
            transport=cfg.get("transport", "batched"),
            event_wait=cfg.get("event_wait", 2.0),
            ping_interval=cfg.get("ping_interval"),
            fleet_push_interval=cfg.get("fleet_push_interval"),
            **overrides,
        )

    # ------------------------------------------------------------------ http
    def _post_raw(self, endpoint: str, body: Any, auth: bool = True) -> Any:
        session = self._rest if auth else RestSession(self.api_url)
        return session.request("POST", endpoint, body)

    def request(
        self,
        method: str,
        endpoint: str,
        json_body: Any = None,
        params: dict[str, Any] | None = None,
        timeout: float | None = None,
    ) -> Any:
        """One control-plane request, replica-aware: a CONNECTION-level
        failure (socket refused/reset/timed out — the server process is
        gone) rotates to the next replica URL and retries, once per
        configured replica. HTTP-level errors (RestError) pass through
        untouched: the server answered, the replica is fine.

        A FULL failed rotation (every replica refused) means the whole
        control plane is gone, not one process — the daemon backs off
        with capped jitter (same `backoff_delay` as the event poll,
        streak persisted across calls) and makes one more sweep before
        raising, so a fleet that lost all replicas at once re-probes
        decorrelated."""
        if len(self.api_urls) == 1:
            # single-URL daemons keep the historical fail-fast contract;
            # the event poll's own backoff paces the retries
            return self._rest.request(
                method, endpoint, json_body, params, timeout=timeout
            )
        last_exc: Exception | None = None
        for sweep in range(2):
            for _ in range(len(self.api_urls)):
                try:
                    result = self._rest.request(
                        method, endpoint, json_body, params, timeout=timeout
                    )
                except RestError:
                    raise
                except OSError as e:
                    last_exc = e
                    self._rotate_replica(e)
                    continue
                if self._rotation_streak:
                    log.info(
                        "control plane reachable again after %d failed "
                        "rotation(s)", self._rotation_streak,
                    )
                    self._rotation_streak = 0
                return result
            assert last_exc is not None
            self._rotation_streak += 1
            delay = backoff_delay(
                max(self.poll_interval, 0.05), self._rotation_streak,
                cap=5.0,
            )
            from vantage6_tpu.common.flight import FLIGHT
            from vantage6_tpu.common.telemetry import REGISTRY

            REGISTRY.counter("v6t_daemon_rotation_total").inc()
            FLIGHT.note(
                "replica_rotation_failed", attempt=self._rotation_streak,
                replicas=len(self.api_urls), retry_in_s=round(delay, 3),
                error=str(last_exc),
            )
            # one warning per streak (the _poll_once convention): entry
            # at WARNING, the rest at DEBUG, recovery at INFO above
            if self._rotation_streak == 1:
                log.warning(
                    "all %d replica URLs unreachable; backing off %.2fs "
                    "before re-sweep (further rotations logged at DEBUG): "
                    "%s", len(self.api_urls), delay, last_exc,
                )
            else:
                log.debug(
                    "full rotation %d failed (retry in %.2fs): %s",
                    self._rotation_streak, delay, last_exc,
                )
            if sweep == 0:
                self._stop.wait(delay)
        assert last_exc is not None
        raise last_exc

    def _rotate_replica(self, cause: Exception) -> None:
        """Point the transport at the next replica (all replicas are
        stateless over one store, so any of them serves any request).
        The in-flight proxy keeps its original URL until restart."""
        self._url_index = (self._url_index + 1) % len(self.api_urls)
        self.api_url = self.api_urls[self._url_index]
        self._rest.base_url = self.api_url
        log.warning(
            "server connection failed (%s); rotating to replica %s",
            cause, self.api_url,
        )

    # --------------------------------------------------- batched transport
    def _claim_batch(
        self,
        run_ids: list[int] | None = None,
        reset_orphans: bool = False,
        max_runs: int = 250,
    ) -> list[dict[str, Any]] | None:
        """One ``POST /api/run/claim-batch``; None when the server lacks
        the endpoint (the daemon pins itself to the per-run path)."""
        if self._batch_ok is False:
            return None
        body: dict[str, Any] = {"max": max_runs}
        if run_ids is not None:
            # explicit dispatch: the caller already claimed these ids
            body["run_ids"] = run_ids
        else:
            with self._claim_lock:
                body["exclude_run_ids"] = sorted(self._claimed)
        if reset_orphans:
            body["reset_orphans"] = True
        t_wall, t_perf = time.time(), time.perf_counter()
        try:
            resp = self.request("POST", "run/claim-batch", body)
        except RestError as e:
            if e.status in (404, 405):
                log.info("server lacks claim-batch; using per-run dispatch")
                self._batch_ok = False
                return None
            raise
        self._batch_ok = True
        entries = resp.get("data", [])
        # claim attribution for SWEEP-prefetched runs: the batch round-trip
        # IS their claim window — stash it so _execute can record a
        # daemon.claim span even though it never fetches (sweep-claimed
        # runs — offline daemon, lost event — are precisely the
        # slow-dispatch cases the trace exists to explain)
        claim_s = time.perf_counter() - t_perf
        for entry in entries:
            entry["_claim_wall0"] = t_wall
            entry["_claim_s"] = claim_s
        return entries

    def _report(self, run_id: int, **fields: Any) -> None:
        """Report run status/result — batched (coalescing reporter) when
        the server supports it, per-run PATCH otherwise."""
        if self.transport == "batched" and self._batch_ok is not False:
            self._reporter.submit_and_wait(run_id, fields)
        else:
            self.request("PATCH", f"run/{run_id}", fields)

    def _iter_pages(self, endpoint: str, params: dict[str, Any] | None = None):
        """Yield every item of a paginated listing (full page drain, 250 a
        page) — the ONE pagination loop the read-only listing sweeps share.
        The orphan pass in `_sync_missed_runs_locked` keeps its own loop: it
        MUTATES the filtered set mid-drain and needs page-reset control."""
        page = 1
        while True:
            body = self.request(
                "GET", endpoint,
                params={**(params or {}), "per_page": 250, "page": page},
            )
            data = body.get("data", [])
            yield from data
            total = body.get("pagination", {}).get("total", 0)
            if page * 250 >= total or not data:
                return
            page += 1

    def _refresh(self) -> bool:
        if self._refresh_token:
            try:
                data = RestSession(self.api_url).request(
                    "POST", "token/refresh",
                    {"refresh_token": self._refresh_token},
                )
                self._access_token = data["access_token"]
                self._refresh_token = data.get(
                    "refresh_token", self._refresh_token
                )
                return True
            except RestError:
                pass
        # refresh rejected: the server may have RESTARTED with a fresh JWT
        # secret (no configured jwt_secret). The api_key is the node's
        # durable credential — re-authenticate from scratch so a server
        # bounce never bricks a running daemon.
        try:
            data = self._post_raw(
                "token/node", {"api_key": self.api_key}, auth=False
            )
            # inside the try: a token response missing a key must fail-soft
            # to False (the documented contract), not raise KeyError out of
            # the request path; a response without refresh_token keeps the
            # old one rather than clearing it
            self._access_token = data["access_token"]
            self._refresh_token = data.get(
                "refresh_token", self._refresh_token
            )
        except Exception as e:
            log.warning("node re-authentication failed: %s", e)
            return False
        log.info("re-authenticated with api_key (refresh token rejected — "
                 "server restart?)")
        return True

    def _register_public_key(self) -> None:
        org = self.request("GET", f"organization/{self.organization_id}")
        pub = self.cryptor.public_key_str  # type: ignore[union-attr]
        if org.get("public_key") != pub:
            self.request(
                "PATCH",
                f"organization/{self.organization_id}",
                {"public_key": pub},
            )

    # ------------------------------------------------------------- lifecycle
    def start(self, background: bool = True) -> "NodeDaemon":
        # crash forensics: label this process's flight recorder and arm
        # dump-on-fatal + kill -USR2 (docs/observability.md). Idempotent —
        # a test process hosting several daemons installs the hooks once.
        from vantage6_tpu.common.flight import install as flight_install

        flight_install(service=f"daemon:{self.name}")
        self._proxy_server = self.proxy.serve()
        self.request("PATCH", f"node/{self.id}", {"status": "online"})
        self._cursor = self.request("GET", "event", params={"since": 0})[
            "cursor"
        ]
        self._sync_missed_runs()
        self._reconcile_sessions()
        self._sync_thread = threading.Thread(
            target=self._sync_worker, daemon=True, name="v6t-sync"
        )
        self._sync_thread.start()
        if self.runner.device_engine:
            self._device_thread = threading.Thread(
                target=self._device_worker, daemon=True,
                name="v6t-device-engine",
            )
            self._device_thread.start()
        if background:
            self._thread = threading.Thread(target=self._listen, daemon=True)
            self._thread.start()
            return self
        self._listen()
        return self

    def crash(self) -> None:
        """Simulate a hard process death (V6T_FAULTS `crash`): every
        worker stops but the node is NEVER patched offline — the server
        only learns through its `daemon_lapsed` watchdog rule, exactly
        like a real SIGKILL mid-round. Used by the fault-injection
        harness; see docs/OPERATOR_GUIDE.md "autopilot"."""
        self._stop.set()
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._reporter.stop()
        if self._proxy_server:
            self._proxy_server.stop()
            self._proxy_server = None

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        if self._device_thread:
            self._device_thread.join(timeout=10)
        if self._sync_thread:
            self._sync_thread.join(timeout=10)
        self._pool.shutdown(wait=True, cancel_futures=True)
        # after the pool: workers inside submit_and_wait need the reporter
        # alive until their final report flushed
        self._reporter.stop()
        try:
            self.request("PATCH", f"node/{self.id}", {"status": "offline"})
        except Exception:
            pass
        if self._proxy_server:
            self._proxy_server.stop()

    # ---------------------------------------------------------------- listen
    def _listen(self) -> None:
        """Prefer websocket push (SocketIO parity); the REST cursor remains
        the fallback AND the gap-filler after any socket drop."""
        from vantage6_tpu.common.faults import FAULTS

        discover_at = 0.0
        ws_url: str | None = None
        while not self._stop.is_set():
            if FAULTS.daemon_crash():
                log.error(
                    "injected daemon crash (V6T_FAULTS): dying without "
                    "the offline handshake"
                )
                self.crash()
                return
            now = time.monotonic()
            if now >= discover_at:
                ws_url = self._discover_ws()
                # no bridge on the server is the steady state for polling
                # deployments — don't double request load re-asking every
                # cycle; after a drop the next re-discovery is soon enough
                discover_at = now + (10.0 if ws_url is None else 1.0)
            if ws_url:
                self._listen_ws(ws_url)  # returns on disconnect or stop
                if self._stop.is_set():
                    return
                discover_at = 0.0  # re-discover after a drop
            # event fetch: long-poll when the server supports it (the
            # request itself blocks until an event lands, so no sleep);
            # the fixed poll_interval survives only as the legacy-server
            # cadence and the post-failure pacing
            waited = self._poll_once()
            if not waited:
                self._stop.wait(self.poll_interval)

    def _discover_ws(self) -> str | None:
        try:
            return self.request("GET", "health").get("websocket_url")
        except Exception:
            return None

    def _poll_once(self) -> bool:
        """One event fetch. Returns True when no further sleep is needed
        (the server long-polled for us, or the failure path already slept
        its backoff)."""
        # name filter: _handle only acts on these three, and without the
        # filter every status-update in the collaboration room would wake
        # every long-polling daemon (N× request amplification per event)
        params: dict[str, Any] = {
            "since": self._cursor,
            "names": "task-created,kill-task,session-deleted",
        }
        use_wait = self.event_wait > 0 and self._long_poll is not False
        if use_wait:
            params["wait"] = self.event_wait
        try:
            batch = self.request(
                "GET", "event", params=params,
                # a long poll must not hang forever on a dead server: give
                # the server its window plus generous transit margin
                timeout=(self.event_wait + 30.0) if use_wait else None,
            )
        except Exception as e:
            # capped exponential backoff + jitter: N daemons that lost the
            # same restarting server must NOT retry in lockstep
            self._poll_failures += 1
            delay = backoff_delay(
                max(self.poll_interval, 0.05), self._poll_failures
            )
            # ONE warning per failure streak, not one per retry: a server
            # restart used to spam a warning every backoff step across
            # every daemon. The streak's shape stays fully recorded — a
            # telemetry counter per failure and a flight-recorder note per
            # attempt (the dump shows each retry) — while the console gets
            # one line on entry and one on recovery.
            from vantage6_tpu.common.flight import FLIGHT
            from vantage6_tpu.common.telemetry import REGISTRY

            REGISTRY.counter("v6t_daemon_backoff_total").inc()
            FLIGHT.note(
                "event_poll_error", attempt=self._poll_failures,
                retry_in_s=round(delay, 3), error=str(e),
            )
            if self._poll_failures == 1:
                log.warning(
                    "event poll failed, entering backoff (retry in "
                    "%.2fs; further retries logged at DEBUG): %s", delay, e,
                )
            else:
                log.debug(
                    "event poll failed (attempt %d, retry in %.2fs): %s",
                    self._poll_failures, delay, e,
                )
            self._stop.wait(delay)
            return True
        if self._poll_failures:
            log.info(
                "event poll recovered after %d failed attempt(s)",
                self._poll_failures,
            )
        self._poll_failures = 0
        self._long_poll = bool(batch.get("long_poll"))
        if batch.get("truncated"):
            # the replay buffer overflowed past our cursor: events were
            # LOST, not delayed. Same exposure as a cursor regression —
            # resync everything an event could have carried.
            log.info(
                "event buffer overflowed past cursor %s; resyncing "
                "runs/kills/sessions", self._cursor,
            )
            self._cursor = batch["cursor"]
            self._heal()
        if batch["cursor"] < self._cursor:
            # the hub's sequence counter runs BEHIND our watermark: the
            # server restarted (in-memory hub, fresh counter). Keeping the
            # old watermark would filter out every future event forever —
            # adopt the new sequence space and heal (see _heal).
            log.info(
                "event cursor regressed %s -> %s (server restart); "
                "resyncing runs/kills/sessions", self._cursor,
                batch["cursor"],
            )
            self._cursor = batch["cursor"]
            # a restarted server also lost its capability answer
            self._long_poll = None
            self._batch_ok = (
                None if self.transport == "batched" else False
            )
            self._heal()
        else:
            self._cursor = max(self._cursor, batch["cursor"])
        for event in batch["data"]:
            self._handle(event)
        return use_wait and bool(batch.get("long_poll"))

    def _heal(self) -> None:
        """Resync everything an event could have carried: queued runs,
        kills (a missed kill-task would let a killed run execute to
        completion), and deleted sessions (a missed session-deleted leaves
        extracted dataframes on disk) — runs have the periodic sweep as
        backstop, kills and sessions only have this."""
        for heal in (self._sync_missed_runs, self._sync_kills,
                     self._reconcile_sessions):
            try:
                heal()
            except Exception as e:
                log.warning("event-gap %s failed: %s", heal.__name__, e)

    def _listen_ws(self, ws_url: str) -> None:
        import json as _json

        try:
            # inside the try: a missing websockets package must degrade to
            # polling, not kill the listen thread
            from websockets.sync.client import connect

            with connect(ws_url) as ws:
                ws.send(
                    _json.dumps(
                        {"token": self._access_token, "since": self._cursor}
                    )
                )
                hello = _json.loads(ws.recv(timeout=10))
                if not hello.get("connected"):
                    log.warning("ws auth rejected: %s", hello)
                    return
                log.info("event push connected (%s)", ws_url)
                while not self._stop.is_set():
                    try:
                        msg = _json.loads(ws.recv(timeout=self.poll_interval))
                    except TimeoutError:
                        continue
                    event = msg.get("event")
                    if event:
                        self._cursor = max(self._cursor, event["seq"])
                        self._handle(event)
        except Exception as e:
            log.warning("event push dropped (%s); falling back to polling", e)

    def _handle(self, event: dict[str, Any]) -> None:
        name, data = event["name"], event["data"]
        if name == "task-created" and data.get("run_id"):
            if data.get("organization_id") == self.organization_id:
                self._submit(data["run_id"])
        elif name == "kill-task":
            self._killed.add(data.get("run_id"))
        elif name == "session-deleted" and data.get("session_id"):
            # drop the LOCAL dataframe store for the deleted workspace
            self.runner.drop_session(data["session_id"])

    def _submit(self, run_id: int, entry: dict[str, Any] | None = None) -> None:
        with self._claim_lock:
            if run_id in self._claimed:
                return
            self._claimed.add(run_id)
            if entry is not None:
                self._prefetched[run_id] = entry
        self._pool.submit(self._execute_logged, run_id)

    def _unclaim(self, run_id: int) -> None:
        """Give a run back to the sweep after a failure that never reached
        a terminal status patch — a claimed-but-dead run would otherwise be
        orphaned for this daemon's whole life."""
        with self._claim_lock:
            self._claimed.discard(run_id)
            self._prefetched.pop(run_id, None)

    def _execute_logged(self, run_id: int, dispatched: bool = False) -> None:
        try:
            self._execute(run_id, dispatched=dispatched)
        except Exception:
            log.error("run %s worker crashed:\n%s", run_id,
                      traceback.format_exc(limit=8))
            # whatever state the run is in, this thread is done with it; if
            # the crash left it non-terminal, the anti-entropy sweep (or a
            # restart) must be able to pick it up again
            self._unclaim(run_id)

    def _device_worker(self) -> None:
        """Drain device-engine runs one at a time, lowest task id first.

        The local PriorityQueue only orders runs already delivered to THIS
        daemon; the globally agreed order every mesh member must follow is
        the server-assigned task id. So before entering a popped run, ask
        the server whether an EARLIER device run for this node is still
        pending (its event may simply not have arrived yet) — if so, run
        that one first and keep the popped run queued.
        """
        attempted: set[int] = set()
        # a task's engine is immutable: resolve each task id once, not on
        # every ordering scan (the scan runs per device-run dispatch)
        engine_cache: dict[int, str] = {}
        while not self._stop.is_set():
            try:
                task_id, run_id = self._device_queue.get(timeout=0.25)
            except queue.Empty:
                continue
            lower = self._lower_pending_device_run(
                task_id, attempted, engine_cache
            )
            if lower is not None:
                self._device_queue.put((task_id, run_id))
                l_task_id, l_run_id = lower
                with self._claim_lock:
                    self._claimed.add(l_run_id)
                attempted.add(l_run_id)
                self._execute_logged(l_run_id, dispatched=True)
                continue
            attempted.add(run_id)
            self._execute_logged(run_id, dispatched=True)

    def _lower_pending_device_run(
        self,
        task_id: int,
        attempted: set[int],
        engine_cache: dict[int, str],
    ) -> tuple[int, int] | None:
        """The server's word on ordering: the lowest-task-id PENDING device
        run assigned to this node that precedes ``task_id`` (excluding runs
        this worker already attempted — a run that failed before reaching a
        terminal status must not wedge the queue). Drains every page: the
        decisive run hiding on page 2 of a deep backlog would re-open the
        opposite-order deadlock this check exists to prevent."""
        import jax

        if jax.process_count() <= 1:
            # single-process mesh: no peer daemon to agree with, so local
            # queue order suffices — skip the server scan entirely
            return None
        candidates: list[tuple[int, int]] = []
        try:
            for run in self._iter_pages(
                "run", {"status": TaskStatus.PENDING.value}
            ):
                tid = (run.get("task") or {}).get("id")
                if tid is None or tid >= task_id or run["id"] in attempted:
                    continue
                candidates.append((tid, run["id"]))
        except Exception:
            return None  # can't consult the server: local order only
        for tid, rid in sorted(candidates):
            engine = engine_cache.get(tid)
            if engine is None:
                try:
                    engine = self.request(
                        "GET", f"task/{tid}"
                    ).get("engine") or "process"
                except Exception:
                    continue
                engine_cache[tid] = engine
            if engine == "device":
                return (tid, rid)
        return None

    def _await_device_peers(self, task: dict[str, Any], run_id: int) -> None:
        """Control-plane barrier before entering a collective SPMD program.

        Entering the program while ANY member daemon will never arrive
        (its run failed to decrypt, was killed, its node refused or is
        offline) blocks this thread inside the collectives until the comm
        backend's own timeout fires. This barrier waits until every peer
        run is ACTIVE (its daemon patched ACTIVE immediately before its own
        barrier) and aborts cleanly if a peer reaches a failed state or the
        wait times out. Single-process meshes skip it: their programs span
        no other daemon.
        """
        import jax

        if jax.process_count() <= 1:
            return
        timeout = float(
            (self.device_engine_cfg or {}).get("barrier_timeout", 120.0)
        )
        failed_states = {s.value for s in TaskStatus.failed_statuses()}
        waiting_states = {
            TaskStatus.PENDING.value,
            TaskStatus.INITIALIZING.value,
        }
        deadline = time.monotonic() + timeout
        while not self._stop.is_set():
            runs = self._all_task_runs(task["id"])
            peers = [r for r in runs if r["id"] != run_id]
            if not peers:
                # fail closed: on a multi-process mesh a device task always
                # has peer runs; seeing none means the server hid them
                # (scoped listing) — entering the collective alone would
                # block in the comm backend
                raise RuntimeError(
                    "no peer runs visible for a multi-process device task "
                    "— refusing to enter the collective program alone"
                )
            bad = [r for r in peers if r["status"] in failed_states]
            if bad:
                raise RuntimeError(
                    "aborting before collective entry: peer run(s) "
                    f"{[(r['id'], r['status']) for r in bad]} will never "
                    "join the SPMD program"
                )
            if run_id in self._killed:
                raise RuntimeError("run killed while awaiting peers")
            if all(r["status"] not in waiting_states for r in peers):
                return
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"device-engine barrier timed out after {timeout:.0f}s: "
                    f"peer runs still "
                    f"{[(r['id'], r['status']) for r in peers if r['status'] in waiting_states]}"
                    " — not entering the collective program without them"
                )
            # status transitions are human-scale; N daemons hammering the
            # server at sub-second cadence buys no freshness
            self._stop.wait(1.0)
        raise RuntimeError("daemon stopping; device run abandoned")

    def _all_task_runs(self, task_id: int) -> list[dict[str, Any]]:
        """EVERY run of a task (full page drain — a >250-org collaboration
        must not hide still-pending peers behind page 1)."""
        return list(self._iter_pages(f"task/{task_id}/run"))

    def _sync_missed_runs(self) -> None:
        """Reference: sync_task_queue_with_server — reclaim every run this
        node owes an execution. Runs at start AND periodically
        (``_sync_worker``); the claim set makes it idempotent and safe
        mid-life:

        - PENDING runs (queued while offline, or whose event was lost) are
          simply (re-)submitted — `_submit` dedupes via the claim set;
        - INITIALIZING/ACTIVE runs NOT in the claim set are orphans —
          left by a previous daemon life, or finished work whose terminal
          report was lost — and are reset to pending server-side, then
          re-executed. Anything this daemon is currently executing IS in
          the claim set and is never touched; that guard (not "the claim
          set is empty at start") is what makes mid-life reclaim sound.

        Serialized by ``_sync_lock``: the periodic sweep and a
        post-restart resync must not interleave claim-check -> PATCH.

        Against a batch-capable server the WHOLE sweep — orphan reset plus
        pending claim, with run/task/token prefetched — is one
        ``claim-batch`` request per 250 runs instead of the page-walking
        per-run reset loop below (which remains the mixed-version path).
        """
        with self._sync_lock:
            if self.transport == "batched" and self._batch_ok is not False:
                try:
                    if self._claim_batch_sweep():
                        return
                except Exception as e:
                    log.warning(
                        "batched claim sweep failed (%s); falling back to "
                        "the per-run sweep", e,
                    )
            self._sync_missed_runs_locked()

    def _claim_batch_sweep(self) -> bool:
        """Sweep via claim-batch; False when the server lacks the endpoint
        (the caller then runs the legacy per-run sweep)."""
        while True:
            entries = self._claim_batch(reset_orphans=True)
            if entries is None:
                return False
            for entry in entries:
                self._submit(entry["id"], entry)
            if len(entries) < 250:
                return True
            # a full page: newly claimed ids join the exclude list, so the
            # next request returns the NEXT slice of the backlog

    def _sync_kills(self) -> None:
        """Re-learn kills this node may have missed (post-restart heal):
        the kill-task EVENT is the only push channel, so after a cursor
        reset the killed set is rebuilt from the server's run statuses.
        Drains EVERY page like the other listings here — the listing is
        id-ascending, so with >250 historical kills the RECENT ones (the
        dangerous ones: their runs may still be executing locally) would
        hide behind page 1."""
        for run in self._iter_pages(
            "run", {"status": TaskStatus.KILLED.value}
        ):
            self._killed.add(run["id"])

    def _sync_missed_runs_locked(self) -> None:
        # Orphan statuses FIRST: were PENDING processed first, a run it
        # just submitted could go ACTIVE in a worker thread and then be
        # "reclaimed" (reset to pending mid-execution) by the pass that
        # follows. The claimed-set guard below closes the rest of that
        # window.
        for status in (TaskStatus.INITIALIZING, TaskStatus.ACTIVE,
                       TaskStatus.PENDING):
            mutating = status is not TaskStatus.PENDING
            page = 1
            while True:
                # the orphan pass MUTATES the filtered set (each PATCH
                # removes a run from this status), so after any progress it
                # re-fetches page 1 — incrementing the page would skip
                # everything the shrinkage slid onto page 1. A page of
                # only claimed (still-executing) runs advances the page
                # instead: reclaimable orphans behind it must not starve.
                body = self.request(
                    "GET",
                    "run",
                    params={
                        "status": status.value,
                        "per_page": 250,
                        "page": page,
                    },
                )
                progressed = skipped = 0
                for run in body["data"]:
                    if mutating:
                        with self._claim_lock:
                            if run["id"] in self._claimed:
                                skipped += 1  # executing in THIS daemon
                                continue
                        try:
                            self.request(
                                "PATCH",
                                f"run/{run['id']}",
                                {
                                    "status": TaskStatus.PENDING.value,
                                    "log": "orphaned mid-run (daemon "
                                           "restart or lost report); "
                                           "re-queued by sync",
                                },
                            )
                        except Exception as e:
                            # e.g. 409: finished/killed between list + patch
                            log.info(
                                "orphan run %s not re-queued: %s",
                                run["id"], e,
                            )
                            continue
                        progressed += 1
                    self._submit(run["id"])
                if not body["data"]:
                    break
                if mutating:
                    if progressed > 0:
                        page = 1       # set shrank: start over
                    elif skipped > 0:
                        page += 1      # page was all claimed: look deeper
                    else:
                        break          # only PATCH failures left: no spin
                    continue
                total = body.get("pagination", {}).get("total", 0)
                if page * 250 >= total:
                    break
                page += 1

    def _sync_worker(self) -> None:
        """Periodic run sweep (anti-entropy). Events remain the fast path;
        this closes the gaps events cannot guarantee against — a hub replay
        buffer overflow between polls, a dropped socket frame, a run whose
        first execution attempt failed before any status patch, or a run
        whose TERMINAL patch was lost (finished work stuck ACTIVE at the
        server). Orphan reclaim is safe mid-life because anything this
        daemon currently executes is in the claim set and skipped."""
        next_sweep = time.monotonic() + self.sync_interval
        next_ping = time.monotonic()  # first ping immediately
        next_push = time.monotonic() + self.fleet.interval
        while True:
            now = time.monotonic()
            # wake exactly at the next due event — pings, sweeps and
            # fleet pushes each keep their OWN cadence instead of
            # quantizing to a shared tick (a shared tick silently
            # stretched the 15 s sweep to 20)
            wait = max(0.0, min(next_ping, next_sweep, next_push) - now)
            if self._stop.wait(wait):
                return
            now = time.monotonic()
            if now >= next_push:
                next_push = now + self.fleet.interval
                # fail-soft by contract (counter + flight note inside);
                # a pre-fleet server pins this into a no-op
                self.fleet.maybe_push()
            if now >= next_ping:
                next_ping = now + self.ping_interval
                try:
                    self.ping()
                except Exception as e:
                    # a missed ping window flips the server's
                    # daemon_lapsed alert — record the miss on THIS side
                    # too so a dump shows which end was failing
                    self.ping_failures += 1
                    from vantage6_tpu.common.flight import FLIGHT

                    FLIGHT.note(
                        "ping_failed", failures=self.ping_failures,
                        error=str(e),
                    )
                    if self.ping_failures == 1:
                        log.warning("server ping failed: %s", e)
            if now >= next_sweep:
                # fixed cadence (+= not now+): a slow sweep must not
                # push every later sweep back; if we fell more than one
                # period behind, re-anchor instead of bursting
                next_sweep += self.sync_interval
                if next_sweep <= now:
                    next_sweep = now + self.sync_interval
                try:
                    self._sync_missed_runs()
                except Exception as e:
                    log.warning("anti-entropy run sweep failed: %s", e)

    def _reconcile_sessions(self) -> None:
        """Drop local session stores whose server session no longer exists.

        The SESSION_DELETED event only reaches connected nodes; a node
        offline at deletion time would otherwise keep extracted (possibly
        sensitive) dataframes on disk forever. A 404 probe per locally
        stored session closes that gap at every (re)start.
        """
        from vantage6_tpu.common.rest import RestError

        for d in self.runner.work_dir.glob("session_*"):
            try:
                sid = int(d.name.split("_", 1)[1])
            except ValueError:
                continue
            try:
                self.request("GET", f"session/{sid}")
            except RestError as e:
                if e.status == 404:
                    log.info(
                        "session %s deleted while offline; dropping store",
                        sid,
                    )
                    self.runner.drop_session(sid)
            except Exception as e:
                log.warning("session %s reconcile probe failed: %s", sid, e)

    # --------------------------------------------------------------- execute
    def _execute(self, run_id: int, dispatched: bool = False) -> None:
        with self._claim_lock:
            pre = self._prefetched.pop(run_id, None)
        prefetched_token: str | None = None
        # claim attribution: when THIS call pays the fetch round-trip(s)
        # (event dispatch / per-run path — a sweep-prefetched entry already
        # paid inside claim-batch), measure it and record a retroactive
        # daemon.claim span once the task's trace context is known
        claim_wall0, claim_perf0 = time.time(), time.perf_counter()
        fetched_here = pre is None
        if pre is None and self.transport == "batched" \
                and self._batch_ok is not False:
            # event-dispatch fast path: run + task + container token in ONE
            # request instead of GET run / GET task / POST token/container
            try:
                entries = self._claim_batch(run_ids=[run_id], max_runs=1)
            except Exception as e:
                log.error("cannot fetch run %s: %s", run_id, e)
                self._unclaim(run_id)  # still pending server-side: retryable
                return
            if entries is not None:
                if not entries:
                    # not pending anymore (or gone): same outcome as the
                    # per-run status check below
                    return
                pre = entries[0]
        if pre is not None:
            run = pre
            task = pre["task"]
            prefetched_token = pre.get("container_token")
        else:
            try:
                run = self.request("GET", f"run/{run_id}")
            except Exception as e:
                log.error("cannot fetch run %s: %s", run_id, e)
                self._unclaim(run_id)  # still pending server-side: retryable
                return
            if run["status"] != TaskStatus.PENDING.value:
                return
            task = self.request("GET", f"task/{run['task']['id']}")
        if run["status"] != TaskStatus.PENDING.value or run_id in self._killed:
            return
        if (
            task.get("engine") == "device"
            and self.runner.device_engine
            and not dispatched
        ):
            # re-route to the dedicated ordered device worker (see __init__);
            # an UNconfigured node falls through so the runner records the
            # PolicyViolation as NOT_ALLOWED. The prefetched claim goes back
            # so the device worker's later _execute reuses it.
            if pre is not None:
                with self._claim_lock:
                    self._prefetched[run_id] = pre
            self._device_queue.put((task["id"], run_id))
            return
        # one federated task = ONE trace: the server persisted the creating
        # request's context on the task; every span below attaches to it.
        # Untraced tasks (old server, tracing off) resolve to None and the
        # spans are no-ops — require_parent keeps polling noise out.
        tctx = parse_traceparent(task.get("traceparent"))
        service = f"daemon:{self.name}"
        trace_attrs = {
            "run_id": run_id, "task_id": task.get("id"),
            "node_id": self.id, "organization_id": self.organization_id,
        }
        if tctx is not None:
            if fetched_here:
                wall0: float | None = claim_wall0
                claim_s = time.perf_counter() - claim_perf0
            else:  # sweep-prefetched: use the batch round-trip's window
                wall0 = pre.get("_claim_wall0")
                claim_s = pre.get("_claim_s", 0.0)
            if wall0 is not None:
                TRACER.record_span(
                    "daemon.claim", wall0, claim_s,
                    parent=tctx, kind="claim", service=service,
                    attrs=trace_attrs,
                )
        with TRACER.span(
            "daemon.exec", kind="daemon", parent=tctx, service=service,
            attrs=trace_attrs, require_parent=True,
        ):
            self._execute_run(run_id, run, task, prefetched_token)

    def _execute_run(
        self,
        run_id: int,
        run: dict[str, Any],
        task: dict[str, Any],
        prefetched_token: str | None,
    ) -> None:
        def patch(**kw: Any) -> None:
            try:
                self._report(run_id, **kw)
            except RuntimeError as e:
                # 409 = the server already moved the run to a terminal state
                # (killed mid-execution); the server's word is final
                if "409" in str(e):
                    log.info("run %s already terminal at server: %s", run_id, e)
                else:
                    raise
        try:
            payload = deserialize(
                self.cryptor.decrypt_str_to_bytes(run["input"] or ""),
                writable=True,  # args flow into algorithm code (may mutate)
            )
        except Exception:
            patch(
                status=TaskStatus.FAILED.value,
                log="cannot decrypt/deserialize input "
                + traceback.format_exc(limit=2),
                finished_at=time.time(),
            )
            return
        if task.get("engine") == "device":
            # every DETERMINISTIC refusal must happen BEFORE this daemon
            # goes ACTIVE: peers' barriers read ACTIVE as "will enter the
            # collective program", and a post-ACTIVE refusal would leave
            # them blocked inside the collectives (see preflight_device)
            try:
                self.runner.preflight_device(
                    task["image"],
                    str(task.get("init_user", {}).get("id", "")),
                )
            except PolicyViolation as e:
                patch(
                    status=TaskStatus.NOT_ALLOWED.value,
                    log=str(e),
                    finished_at=time.time(),
                )
                return
            except UnknownAlgorithm as e:
                patch(
                    status=TaskStatus.NO_IMAGE.value,
                    log=str(e),
                    finished_at=time.time(),
                )
                return
        # inside the daemon.exec span: this record (and everything the run
        # logs from here on this thread) carries the task's trace_id, the
        # join key a flight-recorder dump correlates logs to spans with
        log.info(
            "run %s: executing %s/%s for task %s", run_id,
            task.get("image"), task.get("method"), task.get("id"),
        )
        # activation is the dispatch serialization point: the server takes
        # it as a compare-and-swap (PENDING -> ACTIVE, one winner). A 409
        # here means another claimant — this daemon's own duplicate
        # dispatch, or the same run claimed through a DIFFERENT server
        # replica — already activated it, and executing anyway would
        # double-run the algorithm. Unlike the terminal-state 409s that
        # `patch` swallows mid-run, a lost activation ABORTS the run.
        try:
            self._report(
                run_id, status=TaskStatus.ACTIVE.value,
                started_at=time.time(),
            )
        except RuntimeError as e:
            if "409" in str(e):
                log.info(
                    "run %s activation lost (already active/terminal at "
                    "server): %s — dropping", run_id, e,
                )
                self.activations_lost += 1
                return
            raise
        # past the CAS: this daemon is THE executor of this run. The two
        # counters are the bench's double-dispatch ledger — across all
        # daemons, activations_won must equal the number of runs created.
        self.activations_won += 1
        if self.vpn.enabled:
            # register the algorithm's declared ports (module EXPOSED_PORTS;
            # reference: EXPOSE labels) as server Port entities before the
            # run starts, so peer partials can look them up mid-round
            try:
                for p in self.runner.algorithm_ports(task["image"]):
                    self.request(
                        "POST",
                        "port",
                        {"run_id": run_id, "port": p, "label": "vpn"},
                    )
            except Exception as e:
                log.warning("port registration failed for run %s: %s",
                            run_id, e)
        try:
            # everything after ACTIVE must record its failure, or the run
            # sticks ACTIVE forever while the researcher polls
            token = prefetched_token or self.request(
                "POST",
                "token/container",
                {"task_id": task["id"], "image": task["image"]},
            )["container_token"]
            session = task.get("session") or {}
            spec = RunSpec(
                run_id=run_id,
                task_id=task["id"],
                image=task["image"],
                engine=task.get("engine") or "process",
                method=payload.get("method", task["method"]),
                input_payload=payload,
                databases=task.get("databases") or [],
                session_id=session.get("id"),
                store_as=task.get("store_as"),
                token=token,
                server_url=(
                    self._proxy_server.url if self._proxy_server else ""
                ),
                metadata={
                    "node_id": self.id,
                    "organization": str(self.organization_id),
                    "collaboration": str(self.collaboration_id),
                    "init_user": str(task.get("init_user", {}).get("id", "")),
                },
            )
            if spec.engine == "device" and self.runner.device_engine:
                self._await_device_peers(task, run_id)
            # kind="exec" is what the straggler view groups by station
            with TRACER.span(
                "runner.exec", kind="exec",
                service=f"daemon:{self.name}",
                attrs={
                    "run_id": run_id,
                    "organization_id": self.organization_id,
                    "node_id": self.id,
                    "engine": spec.engine,
                },
                require_parent=True,
            ):
                result = self.runner.run(spec)
        except PolicyViolation as e:
            patch(
                status=TaskStatus.NOT_ALLOWED.value,
                log=str(e),
                finished_at=time.time(),
            )
            return
        except UnknownAlgorithm as e:
            patch(
                status=TaskStatus.NO_IMAGE.value,
                log=str(e),
                finished_at=time.time(),
            )
            return
        except Exception:
            patch(
                status=TaskStatus.CRASHED.value,
                log=traceback.format_exc(limit=8),
                finished_at=time.time(),
            )
            return
        if run_id in self._killed:
            # killed while executing: the server already holds KILLED; do
            # not deliver results the user cancelled
            log.info("run %s was killed mid-execution; dropping result", run_id)
            return
        # result goes back encrypted toward the INITIATING organization —
        # still inside the record-failure envelope: a missing/invalid init-org
        # public key or a serialization error must not leave the run ACTIVE
        # forever with the result silently lost
        from vantage6_tpu.common.serialization import serialize

        try:
            init_org = task.get("init_org", {}).get("id")
            pubkey = ""
            if self.encrypted and init_org is not None:
                org = self.request("GET", f"organization/{init_org}")
                pubkey = org.get("public_key") or ""
            # the node's wire_format policy covers the UPLOADED result too
            # (not just the container ABI): a node pinned to v1 for old
            # researcher clients must not push v2 binary result blobs
            wire_format = self.runner.policies.get("wire_format")
            blob = self.cryptor.encrypt_bytes_to_str(
                serialize(result, format=wire_format),
                pubkey,
                format=wire_format,
            )
            # result upload as its own hop: serialize+encrypt above stay in
            # daemon.exec; this span is PURELY the report round-trip (which
            # may coalesce into a PATCH run/batch — the wait is the cost)
            with TRACER.span(
                "daemon.report", kind="report",
                service=f"daemon:{self.name}",
                attrs={"run_id": run_id, "result_bytes": len(blob)},
                require_parent=True,
            ):
                patch(
                    status=TaskStatus.COMPLETED.value,
                    result=blob,
                    finished_at=time.time(),
                )
        except Exception:
            patch(
                status=TaskStatus.FAILED.value,
                log="result delivery failed: "
                + traceback.format_exc(limit=4),
                finished_at=time.time(),
            )
            return
        if spec.store_as and isinstance(result, dict) and result.get("stored"):
            # session bookkeeping only (the dataframe stayed local); a
            # failed report must not fail the COMPLETED run
            try:
                self.request(
                    "PATCH",
                    f"session/{spec.session_id}/dataframe/{spec.store_as}",
                    {"ready": True, "columns": result.get("columns") or []},
                )
            except Exception as e:
                log.warning(
                    "session dataframe report failed for run %s: %s",
                    run_id, e,
                )

    # --------------------------------------------------------------- health
    def ping(self) -> None:
        self.request("POST", "ping")
        self.last_ping_at = time.time()
        self.ping_failures = 0

    def alerts(self) -> dict[str, Any]:
        """The server watchdog's alert state (GET /api/alerts) — the
        daemon-side client of the ops plane, for operators shelling into
        a station and for tests asserting the federation's health."""
        return self.request("GET", "alerts")
