"""Data-station agent (parity: vantage6-node, SURVEY.md §2 items 10-15)."""
from vantage6_tpu.node.daemon import NodeDaemon  # noqa: F401
from vantage6_tpu.node.runner import TaskRunner  # noqa: F401
