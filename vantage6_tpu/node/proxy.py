"""The node's proxy server — algorithm containers' window to the world.

Parity: vantage6-node `proxy_server.py` (SURVEY.md §2 item 12). Algorithm
containers never reach the control plane directly: they talk to this little
server on the node-local network, which (a) relays requests with the
container's JWT, (b) encrypts subtask inputs per destination organization's
public key, and (c) decrypts incoming results with the node's (org's)
private key — so containers never touch key material.
"""
from __future__ import annotations

import base64
from typing import Any

from vantage6_tpu.common.encryption import CryptorBase
from vantage6_tpu.common.rest import pooled_request
from vantage6_tpu.common.log import setup_logging
from vantage6_tpu.server.web import App, AppServer, HTTPError, Request

log = setup_logging("vantage6_tpu/node.proxy")


class NodeProxy:
    """Builds the proxy App for one node."""

    def __init__(
        self,
        server_url: str,
        cryptor: CryptorBase,
        collaboration_id: int,
        encrypted: bool,
    ):
        self.server_url = server_url.rstrip("/")
        self.cryptor = cryptor
        self.collaboration_id = collaboration_id
        self.encrypted = encrypted
        self._org_pubkeys: dict[int, str] = {}
        self.app = App("v6t-node-proxy")
        self._register()

    # ------------------------------------------------------------- helpers
    def _forward(
        self,
        req: Request,
        method: str,
        endpoint: str,
        json_body: Any = None,
    ) -> Any:
        token = req.bearer_token
        if not token:
            raise HTTPError(401, "container token required")
        # shared keep-alive pool: every relayed call rides a warm socket;
        # the timeout outlasts the server's 25 s long-poll cap so a
        # forwarded event wait completes but a dead server can't wedge a
        # relay thread forever
        resp = pooled_request(
            method,
            f"{self.server_url}/api/{endpoint.lstrip('/')}",
            json_body=json_body,
            params={k: v[0] for k, v in req.query.items()},
            headers={"Authorization": f"Bearer {token}"},
            timeout=60.0,
        )
        body = resp.json() if resp.content else {}
        if resp.status_code >= 400:
            raise HTTPError(resp.status_code, body.get("msg", "upstream error"))
        return body

    def _pubkey(self, req: Request, org_id: int) -> str:
        if org_id not in self._org_pubkeys:
            org = self._forward(req, "GET", f"organization/{org_id}")
            key = org.get("public_key") or ""
            if not key:
                raise HTTPError(
                    400,
                    f"organization {org_id} has no public key; cannot "
                    "encrypt the subtask input",
                )
            self._org_pubkeys[org_id] = key
        return self._org_pubkeys[org_id]

    def _decrypt_result(self, blob: str | None) -> str | None:
        """Encrypted-toward-our-org blob -> base64(plaintext serialized).

        ``decrypt_bytes`` auto-detects the wire framing, so v1 '$'-joined
        strings and base64'd v2 binary frames both decrypt."""
        if not blob:
            return blob
        try:
            plain = self.cryptor.decrypt_bytes(blob)
        except Exception:
            # result was encrypted toward a different org (not our task
            # tree) — pass the ciphertext through rather than failing
            return blob
        return base64.b64encode(plain).decode("ascii")

    # -------------------------------------------------------------- routes
    def _register(self) -> None:
        app = self.app

        @app.route("/api/task", methods=("POST",))
        def create_task(req: Request):
            body = req.json or {}
            orgs = body.get("organizations") or []
            if not orgs:
                raise HTTPError(400, "organizations required")
            try:
                input_plain = base64.b64decode(body.get("input", ""))
            except Exception:
                raise HTTPError(400, "input must be base64") from None
            # single-pass broadcast: the payload is AES-encrypted ONCE and
            # only the key seal differs per destination organization — an
            # N-org subtask fan-out no longer pays N full encrypt passes
            pubkeys = [
                self._pubkey(req, int(o)) if self.encrypted else ""
                for o in orgs
            ]
            wires = self.cryptor.encrypt_bytes_to_str_broadcast(
                input_plain, pubkeys
            )
            org_specs = [
                {"id": int(o), "input": w} for o, w in zip(orgs, wires)
            ]
            method = ""
            try:
                # wire-format-aware metadata peek: reads the structure
                # header only, never materializes the (possibly many-MB)
                # array buffers just to learn one string
                from vantage6_tpu.common.serialization import peek_structure

                decoded = peek_structure(input_plain)
                if isinstance(decoded, dict):
                    m = decoded.get("method", "")
                    if isinstance(m, str):
                        method = m
            except Exception:
                pass
            upstream = {
                "name": body.get("name", "subtask"),
                "image": body.get("image", ""),
                "method": method,
                "collaboration_id": self.collaboration_id,
                "organizations": org_specs,
                "databases": body.get("databases") or [],
            }
            # the server derives the true image from the container token's
            # parent task; containers cannot spoof it (resources._create_task)
            if not upstream["image"]:
                task_id = self._token_task_id(req)
                parent = self._forward(req, "GET", f"task/{task_id}")
                upstream["image"] = parent["image"]
            return self._forward(req, "POST", "task", upstream), 201

        @app.route("/api/task/<int:id>", methods=("GET",))
        def get_task(req: Request, id: int):
            return self._forward(req, "GET", f"task/{id}")

        @app.route("/api/task/<int:id>/run", methods=("GET",))
        def get_task_runs(req: Request, id: int):
            body = self._forward(req, "GET", f"task/{id}/run")
            for run in body.get("data", []):
                run["result"] = self._decrypt_result(run.get("result"))
                run.pop("input", None)  # containers never see others' inputs
            return body

        @app.route("/api/run", methods=("GET",))
        def get_runs(req: Request):
            body = self._forward(req, "GET", "run")
            for run in body.get("data", []):
                run["result"] = self._decrypt_result(run.get("result"))
                run.pop("input", None)
            return body

        @app.route("/api/organization", methods=("GET",))
        def organizations(req: Request):
            return self._forward(req, "GET", "organization")

        # untimed: relayed long polls block for the upstream wait window
        @app.route("/api/event", methods=("GET",), untimed=True)
        def events(req: Request):
            # event long-poll relay: a central algorithm's
            # wait_for_results blocks HERE (query params — since/wait —
            # pass through) and wakes on its subtasks' status events
            return self._forward(req, "GET", "event")

        @app.route("/api/health", methods=("GET",))
        def health(req: Request):
            return {"status": "ok", "proxy": True}

        @app.route("/api/metrics", methods=("GET",))
        def metrics(req: Request):
            """NODE-process telemetry (Prometheus text): the daemon's
            wire/REST/tracing counters live in this process, not the
            server's — operators scrape each node here. Trace context
            relays transparently: the container's `traceparent` header
            joins the proxy's server span, and `pooled_request` forwards
            the continuation upstream on every relayed call."""
            from vantage6_tpu.common.telemetry import (
                PROMETHEUS_CONTENT_TYPE,
                REGISTRY,
            )
            from vantage6_tpu.server.web import Response

            return Response(
                REGISTRY.render_prometheus(),
                headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
            )

    def _token_task_id(self, req: Request) -> int:
        """Best-effort read of the container token's task id (unverified
        here — the server re-verifies; the proxy just needs routing info)."""
        import json as _json

        token = req.bearer_token or ""
        try:
            payload = token.split(".")[1]
            payload += "=" * (-len(payload) % 4)
            claims = _json.loads(base64.urlsafe_b64decode(payload))
            return int(claims["sub"]["task_id"])
        except Exception:
            raise HTTPError(401, "malformed container token") from None

    # ---------------------------------------------------------------- serve
    def serve(self, host: str = "127.0.0.1", port: int = 0) -> AppServer:
        server = AppServer(self.app, host, port)
        server.start_background()
        log.info("node proxy on %s", server.url)
        return server
