"""Network gates: VPN, outbound whitelist, SSH tunnels.

Parity: vantage6-node's optional networking containers (SURVEY.md §2 items
13-15) — WireGuard VPN for cross-station algorithm traffic, a squid proxy
whitelisting outbound HTTP, and SSH tunnels to internal services. On a TPU
pod none of these transports exist (cross-station traffic is ICI; stations
are sub-meshes, not firewalled hospitals), so these managers keep the
reference's *configuration and policy surface* — parse/validate config,
answer reachability questions, register ports — while the transport itself
is the mesh. Each manager states its stance via `supported`/`reason`.
"""
from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from typing import Any
from urllib.parse import urlparse

from vantage6_tpu.common.log import setup_logging

log = setup_logging("vantage6_tpu/node.gates")


@dataclass
class VPNManager:
    """Reference: WireGuard client container + server-registered ports.

    Here "VPN connectivity" between algorithm runs maps to device-mesh
    neighbor exchange; the manager still tracks per-run exposed ports (the
    server's `Port` entity) so iterative/MPC algorithms can discover peers.
    """

    enabled: bool = False
    subnet: str = "10.76.0.0/16"
    supported: bool = False
    reason: str = (
        "cross-station traffic rides the device mesh (ICI), not WireGuard; "
        "port registration is kept for peer discovery parity"
    )

    def setup(self) -> bool:
        if self.enabled:
            log.warning("vpn requested: %s", self.reason)
        return False

    def exposed_ports(self, algorithm_env: dict[str, Any]) -> list[int]:
        """Ports an algorithm declares (reference: image EXPOSE labels)."""
        raw = str(algorithm_env.get("ports", "") or "")
        return [int(p) for p in raw.split(",") if p.strip().isdigit()]


@dataclass
class OutboundWhitelist:
    """Reference: squid proxy restricting algorithm egress (item 14).

    The policy *decision* survives: `allows(url)` is consulted by
    `algorithm.data_loading.load_data` for every remote database URI, on
    both execution paths — inline (TaskRunner.egress, built from node
    policies.egress) and sandboxed (the V6T_EGRESS env var re-builds the
    whitelist inside the child; see algorithm.wrap._env_gates).
    """

    enabled: bool = False
    domains: list[str] = field(default_factory=list)
    ips: list[str] = field(default_factory=list)
    ports: list[int] = field(default_factory=list)

    def allows(self, url: str) -> bool:
        if not self.enabled:
            return True
        parsed = urlparse(url if "//" in url else f"//{url}")
        host = parsed.hostname or ""
        port = parsed.port
        host_ok = any(
            fnmatch.fnmatch(host, pat) for pat in (self.domains + self.ips)
        )
        port_ok = port is None or not self.ports or port in self.ports
        return host_ok and port_ok


@dataclass
class SSHTunnelManager:
    """Reference: ssh tunnels from node to whitelisted internal hosts
    (item 15). Tracked as *named endpoints* databases may address via
    ``options.ssh_tunnel`` — `data_loading` resolves the name to the
    endpoint's station-local ``local_uri`` (TaskRunner.ssh_tunnels inline;
    V6T_SSH_TUNNELS over the sandbox ABI). Actual ssh transport is out of
    scope on-pod (data is mounted/loaded directly)."""

    tunnels: dict[str, dict[str, Any]] = field(default_factory=dict)
    supported: bool = False
    reason: str = "station data is mounted locally; no remote DB hop exists"

    @classmethod
    def from_config(cls, cfg: list[dict[str, Any]] | None) -> "SSHTunnelManager":
        mgr = cls()
        for t in cfg or []:
            name = t.get("hostname") or t.get("name")
            if not name:
                raise ValueError("ssh tunnel config needs a hostname/name")
            mgr.tunnels[name] = dict(t)
        if mgr.tunnels:
            log.warning("ssh tunnels configured: %s", mgr.reason)
        return mgr

    def endpoint(self, name: str) -> dict[str, Any]:
        if name not in self.tunnels:
            raise KeyError(
                f"no tunnel {name!r} (configured: {sorted(self.tunnels)})"
            )
        return self.tunnels[name]
