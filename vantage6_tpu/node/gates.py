"""Network gates: VPN, outbound whitelist, SSH tunnels.

Parity: vantage6-node's optional networking containers (SURVEY.md §2 items
13-15) — WireGuard VPN for cross-station algorithm traffic, a squid proxy
whitelisting outbound HTTP, and SSH tunnels to internal services. On a TPU
pod none of these transports exist (cross-station traffic is ICI; stations
are sub-meshes, not firewalled hospitals), so these managers keep the
reference's *configuration and policy surface* — parse/validate config,
answer reachability questions, register ports — while the transport itself
is the mesh. Each manager states its stance via `supported`/`reason`.
"""
from __future__ import annotations

import fnmatch
import ipaddress
from dataclasses import dataclass, field
from typing import Any
from urllib.parse import urlparse

from vantage6_tpu.common.log import setup_logging

log = setup_logging("vantage6_tpu/node.gates")


@dataclass
class VPNManager:
    """Reference: WireGuard client container + server-registered ports.

    Here "VPN connectivity" between algorithm runs maps to device-mesh
    neighbor exchange; the manager still tracks per-run exposed ports (the
    server's `Port` entity) so iterative/MPC algorithms can discover peers.
    """

    enabled: bool = False
    subnet: str = "10.76.0.0/16"
    supported: bool = False
    reason: str = (
        "cross-station traffic rides the device mesh (ICI), not WireGuard; "
        "port registration is kept for peer discovery parity"
    )

    def __post_init__(self) -> None:
        # the subnet is config other tools consume (the reference hands it
        # to wireguard) — a typo must fail at daemon start, not at first
        # use. strict=False: WireGuard-style interface addresses
        # (10.76.0.1/16) have host bits set and are fine. A DISABLED vpn's
        # subnet is never consumed, so stale garbage there only warns —
        # it must not brick a daemon whose feature is off.
        try:
            ipaddress.ip_network(self.subnet, strict=False)
        except ValueError as e:
            if self.enabled:
                raise ValueError(f"vpn subnet {self.subnet!r}: {e}") from None
            log.warning("ignoring invalid subnet on disabled vpn: %s", e)

    def setup(self) -> bool:
        if self.enabled:
            log.warning("vpn requested: %s", self.reason)
        return False

    def exposed_ports(self, algorithm_env: dict[str, Any]) -> list[int]:
        """Ports an algorithm declares (reference: image EXPOSE labels).
        Out-of-range numbers are dropped with a warning — the server's
        Port entity validates 1..65535 and one bad entry must not sink
        the whole registration."""
        raw = str(algorithm_env.get("ports", "") or "")
        ports = []
        for p in raw.split(","):
            if not p.strip().isdigit():
                continue
            n = int(p)
            if 1 <= n <= 65535:
                ports.append(n)
            else:
                log.warning("ignoring out-of-range exposed port %s", n)
        return ports


@dataclass
class OutboundWhitelist:
    """Reference: squid proxy restricting algorithm egress (item 14).

    The policy *decision* survives: `allows(url)` is consulted by
    `algorithm.data_loading.load_data` for every remote database URI, on
    both execution paths — inline (TaskRunner.egress, built from node
    policies.egress) and sandboxed (the V6T_EGRESS env var re-builds the
    whitelist inside the child; see algorithm.wrap._env_gates).
    """

    enabled: bool = False
    domains: list[str] = field(default_factory=list)
    ips: list[str] = field(default_factory=list)
    ports: list[int] = field(default_factory=list)

    def _ip_allowed(self, addr: "ipaddress.IPv4Address | ipaddress.IPv6Address") -> bool:
        """`ips` entries are exact addresses OR CIDR networks — the same
        semantics as squid's `dst` acls (the reference whitelists
        ip/subnet entries distinctly from dstdomain globs)."""
        # [::ffff:10.0.0.1] IS 10.0.0.1: an IPv4 CIDR entry must treat
        # both spellings identically (version-mismatched containment is
        # silently False otherwise)
        mapped = getattr(addr, "ipv4_mapped", None)
        if mapped is not None:
            addr = mapped
        for entry in self.ips:
            try:
                if addr in ipaddress.ip_network(entry, strict=False):
                    return True
            except ValueError:
                # not CIDR/address syntax: fall back to glob on the string
                if fnmatch.fnmatch(str(addr), entry):
                    return True
        return False

    def allows(self, url: str) -> bool:
        if not self.enabled:
            return True
        try:
            parsed = urlparse(url if "//" in url else f"//{url}")
            host = parsed.hostname or ""
            port = parsed.port
        except ValueError:
            # malformed URL (unclosed IPv6 bracket, ":99999", ":abc"): the
            # GATE must answer, and fail-closed beats a ValueError escaping
            # into the algorithm run as a confusing non-policy crash
            return False
        try:
            addr = ipaddress.ip_address(host)
        except ValueError:
            addr = None
        if addr is not None:
            # a literal-IP URL must match an ip/CIDR entry; domain globs
            # deliberately do NOT apply (squid: dstdomain never matches
            # raw IPs — matching would let 10.* style globs leak)
            host_ok = self._ip_allowed(addr)
        else:
            host_ok = any(fnmatch.fnmatch(host, pat) for pat in self.domains)
        port_ok = port is None or not self.ports or port in self.ports
        return host_ok and port_ok


@dataclass
class SSHTunnelManager:
    """Reference: ssh tunnels from node to whitelisted internal hosts
    (item 15). Tracked as *named endpoints* databases may address via
    ``options.ssh_tunnel`` — `data_loading` resolves the name to the
    endpoint's station-local ``local_uri`` (TaskRunner.ssh_tunnels inline;
    V6T_SSH_TUNNELS over the sandbox ABI). Actual ssh transport is out of
    scope on-pod (data is mounted/loaded directly)."""

    tunnels: dict[str, dict[str, Any]] = field(default_factory=dict)
    supported: bool = False
    reason: str = "station data is mounted locally; no remote DB hop exists"

    @classmethod
    def from_config(cls, cfg: list[dict[str, Any]] | None) -> "SSHTunnelManager":
        mgr = cls()
        for t in cfg or []:
            name = t.get("hostname") or t.get("name")
            if not name:
                raise ValueError("ssh tunnel config needs a hostname/name")
            cls._validate_shape(name, t)
            mgr.tunnels[name] = dict(t)
        if mgr.tunnels:
            log.warning("ssh tunnels configured: %s", mgr.reason)
        return mgr

    @staticmethod
    def _validate_shape(name: str, t: dict[str, Any]) -> None:
        """Reject malformed reference-shaped config at daemon start.

        The reference's tunnel entry nests ``ssh: {host, port, identity:
        {username, key}}`` and ``tunnel: {bind: {ip, port}, dest: {ip,
        port}}``; both blocks are optional here (the transport is N/A
        on-pod) but when present they must be well-formed — a silently
        mis-typed port would otherwise surface only as a confusing
        data-loading failure deep inside an algorithm run."""
        ssh = t.get("ssh")
        if ssh is not None:
            if not isinstance(ssh, dict) or not ssh.get("host"):
                raise ValueError(f"ssh tunnel {name!r}: ssh block needs host")
            port = ssh.get("port", 22)
            if not isinstance(port, int) or not 1 <= port <= 65535:
                raise ValueError(f"ssh tunnel {name!r}: bad ssh port {port!r}")
        tunnel = t.get("tunnel")
        if tunnel is not None:
            for leg in ("bind", "dest"):
                block = (tunnel or {}).get(leg)
                if not isinstance(block, dict):
                    raise ValueError(
                        f"ssh tunnel {name!r}: tunnel needs a {leg} block"
                    )
                p = block.get("port")
                if not isinstance(p, int) or not 1 <= p <= 65535:
                    raise ValueError(
                        f"ssh tunnel {name!r}: bad {leg} port {p!r}"
                    )

    def endpoint(self, name: str) -> dict[str, Any]:
        if name not in self.tunnels:
            raise KeyError(
                f"no tunnel {name!r} (configured: {sorted(self.tunnels)})"
            )
        return self.tunnels[name]
