"""TaskRunner — the DockerManager/DockerTaskManager equivalent.

Parity: SURVEY.md §2 item 11. The reference pulls the algorithm image,
verifies it against node policy, creates a container with data mounts + env
ABI, and harvests the exit code + OUTPUT_FILE. Here an "image" names a
registered Python algorithm module (see common.artifact); execution is
either **inline** (imported module, same process — the on-pod fast path) or
**sandboxed** (a subprocess speaking the identical env-file ABI that a real
container would — `wrap_algorithm` on the other side), chosen per node
config. Policy gates (allowed algorithms, basics) match the reference's.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
import subprocess
import sys
import tempfile
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from vantage6_tpu.common.artifact import parse_ref
from vantage6_tpu.common.log import setup_logging
from vantage6_tpu.common.serialization import deserialize, serialize
from vantage6_tpu.node.gates import OutboundWhitelist, SSHTunnelManager

log = setup_logging("vantage6_tpu/node.runner")


import threading

# One lock per PROCESS: the global device mesh is a process-wide singleton,
# and two concurrent collective programs would interleave their rendezvous
# and deadlock — device-engine runs execute strictly one at a time.
_DEVICE_ENGINE_LOCK = threading.Lock()


class PolicyViolation(Exception):
    """Algorithm/image refused by node policy (reference: NOT_ALLOWED)."""


class UnknownAlgorithm(Exception):
    """Image not registered at this node (reference: NO_DOCKER_IMAGE)."""


@dataclass
class RunSpec:
    """Everything the runner needs for one run."""

    run_id: int
    task_id: int
    image: str
    method: str
    input_payload: dict[str, Any]  # decrypted {"method","args","kwargs"}
    databases: list[dict[str, Any]] = field(default_factory=list)
    token: str = ""  # container token for subtask creation
    server_url: str = ""  # proxy URL the algorithm should talk to
    metadata: dict[str, Any] = field(default_factory=dict)
    # sessions (reference v4.7+): this run executes inside a session
    # workspace; store_as persists the returned dataframe locally
    session_id: int | None = None
    store_as: str | None = None
    # "process" (sandbox/inline per node config) or "device": the run is one
    # collective SPMD program over the federation's global device mesh
    engine: str = "process"


class TaskRunner:
    def __init__(
        self,
        algorithms: dict[str, str] | None = None,
        databases: list[dict[str, Any]] | None = None,
        policies: dict[str, Any] | None = None,
        mode: str = "sandbox",
        work_dir: str | Path | None = None,
        station_secret: str | bytes | None = None,
        identity_key_path: str | None = None,
        org_identities: dict[int, str] | None = None,
        device_engine: bool = False,
    ):
        """``algorithms`` maps image name -> importable module path.

        ``databases`` is the node-config list ({label, type, uri}).
        ``mode``: "sandbox" (subprocess ABI, default — container parity) or
        "inline" (same process — fast, used by tests and trusted setups).
        ``station_secret`` (hex str or bytes) is this station's local secret
        for DH mask agreement (common.secureagg_dh); it is handed only to
        the algorithm's own run environment, never uploaded.
        ``identity_key_path`` / ``org_identities`` (node config) provision
        the org RSA identity key and the trusted identity-pubkey roster for
        secure-aggregation advert signing/verification (wrap.py ABI).
        """
        self.algorithms = dict(algorithms or {})
        self.databases = {d["label"]: d for d in (databases or [])}
        self.policies = dict(policies or {})
        if isinstance(station_secret, str):
            station_secret = bytes.fromhex(station_secret)
        self.station_secret = station_secret
        self.identity_key_path = identity_key_path
        self.org_identities = dict(org_identities or {}) or None
        # network gates (reference items 14/15): egress whitelist consulted
        # on every remote data-loading URI; ssh tunnel endpoints resolved for
        # databases that address them by name
        self.egress = OutboundWhitelist(**(self.policies.get("egress") or {}))
        self.ssh_tunnels = SSHTunnelManager.from_config(
            self.policies.get("ssh_tunnels")
        )
        if mode not in ("sandbox", "inline"):
            raise ValueError(f"unknown runner mode {mode!r}")
        self.mode = mode
        # a typo'd wire_format policy must fail NODE STARTUP, not turn
        # every later run into a CRASHED serialize() error
        wire_format = self.policies.get("wire_format")
        if wire_format is not None:
            from vantage6_tpu.common.serialization import normalize_format

            self.policies["wire_format"] = normalize_format(str(wire_format))
        # device_engine: this node's daemon owns (a slice of) the federation
        # device mesh — it joined jax.distributed at start — and accepts
        # engine="device" tasks. Off by default: a device task arriving at an
        # unconfigured node is refused, not silently run on the wrong mesh.
        self.device_engine = bool(device_engine)
        self._marker_cache: dict[str, bool] = {}
        self.work_dir = Path(work_dir or tempfile.mkdtemp(prefix="v6t_node_"))
        self.work_dir.mkdir(parents=True, exist_ok=True)

    # ---------------------------------------------------------------- policy
    def check_policy(self, image: str, init_user: str | None = None) -> None:
        """Reference DockerManager policy gate: allowed algorithms and
        (optionally) allowed initiating users."""
        ref = parse_ref(image)  # raises on malformed refs
        allowed = self.policies.get("allowed_algorithms")
        if allowed and not any(
            fnmatch.fnmatch(image, pat) or fnmatch.fnmatch(ref.without_digest, pat)
            for pat in allowed
        ):
            raise PolicyViolation(f"algorithm {image!r} not in allow-list")
        users = self.policies.get("allowed_users")
        if users:
            # configs write ids as ints, the wire carries strings — compare
            # normalized so [1] and ["1"] behave identically
            allowed_users = {str(u) for u in users}
            if init_user is None or str(init_user) not in allowed_users:
                raise PolicyViolation(
                    f"user {init_user!r} may not run tasks on this node"
                )

    def resolve(self, image: str) -> str:
        module = self.algorithms.get(image) or self.algorithms.get(
            parse_ref(image).without_digest
        )
        if module is None:
            raise UnknownAlgorithm(f"no algorithm registered for {image!r}")
        return module

    def has_device_marker(self, module: str) -> bool:
        """Whether ``module`` declares ``DEVICE_ENGINE = True`` — WITHOUT
        importing it (importing would execute its top-level code in the
        daemon process, the very bypass the marker check exists to refuse).
        Already-imported modules are probed live; otherwise the source is
        parsed statically, memoized per module name (the run path checks the
        marker both before ACTIVE and inside run(); one disk read + AST
        parse covers the daemon's lifetime). (find_spec imports parent
        PACKAGES — acceptable: the marker gate is about the algorithm
        module's own code.)
        """
        import ast
        import importlib.util

        mod = sys.modules.get(module)
        if mod is not None:
            return bool(getattr(mod, "DEVICE_ENGINE", False))
        if module in self._marker_cache:
            return self._marker_cache[module]
        marked = False
        try:
            spec = importlib.util.find_spec(module)
        except (ImportError, ValueError):
            spec = None
        if spec is not None and spec.origin and spec.origin.endswith(".py"):
            try:
                tree = ast.parse(Path(spec.origin).read_text())
            except (OSError, SyntaxError):
                tree = None
            for node in tree.body if tree else []:
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets = [node.target]
                if any(
                    isinstance(t, ast.Name) and t.id == "DEVICE_ENGINE"
                    for t in targets
                ):
                    marked = bool(getattr(node.value, "value", False))
        self._marker_cache[module] = marked
        return marked

    def preflight_device(self, image: str, init_user: str | None = None) -> None:
        """All DETERMINISTIC refusals for an engine="device" run, checkable
        before the daemon goes ACTIVE. Peers treat ACTIVE as "this node WILL
        enter the collective program" — any refusal discovered after ACTIVE
        leaves them blocked inside the collectives until the comm backend
        times out, so everything that can fail locally must fail here first.
        Raises PolicyViolation / UnknownAlgorithm.
        """
        self.check_policy(image, init_user)
        module = self.resolve(image)
        if not self.device_engine:
            raise PolicyViolation(
                "this node is not configured as a device-engine mesh "
                "member (node config: device_engine)"
            )
        if not self.has_device_marker(module):
            raise PolicyViolation(
                f"algorithm {image!r} is not a device-engine module "
                "(no DEVICE_ENGINE marker): refusing to run it inline in "
                "the daemon process"
            )

    def algorithm_ports(self, image: str) -> list[int]:
        """Ports the algorithm declares for cross-station traffic — module
        attribute ``EXPOSED_PORTS`` (reference: docker image EXPOSE labels
        read by the VPN manager). Empty when undeclared/unresolvable."""
        import importlib

        try:
            mod = importlib.import_module(self.resolve(image))
        except (UnknownAlgorithm, ImportError):
            return []
        return [int(p) for p in getattr(mod, "EXPOSED_PORTS", []) or []]

    # ------------------------------------------------------------- sessions
    def session_dir(self, session_id: int) -> Path:
        """This node's LOCAL store for one session's dataframes (reference
        v4.7 'sessions': dataframes persist at the station between tasks
        and never travel)."""
        d = self.work_dir / f"session_{int(session_id)}"
        d.mkdir(parents=True, exist_ok=True)
        return d

    def session_file(self, session_id: int, handle: str) -> Path:
        safe = "".join(c for c in handle if c.isalnum() or c in "-_")
        if safe != handle or not safe:
            raise PolicyViolation(f"invalid session dataframe handle {handle!r}")
        return self.session_dir(session_id) / f"{safe}.pkl"

    def drop_session(self, session_id: int) -> None:
        """Delete the whole local store (server session deleted)."""
        import shutil

        d = self.work_dir / f"session_{int(session_id)}"
        if d.exists():
            shutil.rmtree(d, ignore_errors=True)

    def _store_session_result(self, spec: RunSpec, result: Any) -> Any:
        """Persist a store_as run's dataframe locally; upload METADATA only."""
        import pandas as pd

        df = result
        if isinstance(df, dict) and "dataframe" in df:
            df = df["dataframe"]
        if not isinstance(df, pd.DataFrame):
            raise RuntimeError(
                f"task stores dataframe {spec.store_as!r} but the algorithm "
                f"returned {type(result).__name__}, not a DataFrame"
            )
        path = self.session_file(spec.session_id, spec.store_as)
        df.to_pickle(path)
        return {
            "stored": spec.store_as,
            "session_id": spec.session_id,
            "rows": int(len(df)),
            "columns": [
                {"name": str(c), "dtype": str(t)}
                for c, t in df.dtypes.items()
            ],
        }

    # ----------------------------------------------------------------- run
    def run(self, spec: RunSpec) -> Any:
        """Execute one run; returns the (plaintext) result object.

        Raises PolicyViolation/UnknownAlgorithm for gate failures and
        RuntimeError (with the log tail) when the algorithm itself crashes.
        """
        self.check_policy(spec.image, spec.metadata.get("init_user"))
        module = self.resolve(spec.image)
        if spec.store_as and spec.session_id is None:
            raise RuntimeError("store_as requires a session_id")
        if spec.engine == "device":
            # device-engine run: the SPMD program must execute IN the daemon
            # process (the subprocess sandbox cannot reach the devices the
            # daemon's jax.distributed membership owns), one task at a time
            # (collective programs cannot interleave on one mesh). The same
            # refusals run in preflight_device (before the daemon patches
            # ACTIVE); re-checked here so direct runner callers can't bypass.
            self.preflight_device(spec.image, spec.metadata.get("init_user"))
            with _DEVICE_ENGINE_LOCK:
                result = self._run_inline(module, spec)
        elif self.mode == "inline":
            result = self._run_inline(module, spec)
        else:
            result = self._run_sandbox(module, spec)
        if spec.store_as:
            return self._store_session_result(spec, result)
        return result

    # ------------------------------------------------------------ inline
    def _run_inline(self, module: str, spec: RunSpec) -> Any:
        import importlib

        from vantage6_tpu.algorithm.context import (
            AlgorithmEnvironment,
            RunMetadata,
            algorithm_environment,
        )
        from vantage6_tpu.algorithm.data_loading import load_data
        from vantage6_tpu.client.rest import RestAlgorithmClient
        from vantage6_tpu.core.config import DatabaseConfig

        mod = importlib.import_module(module)
        fn = getattr(mod, spec.method, None)
        if fn is None:
            raise UnknownAlgorithm(
                f"method {spec.method!r} not found in {module}"
            )
        frames = [
            load_data(
                DatabaseConfig(**self._db_config(d, spec.session_id)),
                whitelist=self.egress,
                ssh_tunnels=self.ssh_tunnels,
            )
            for d in (spec.databases or [{"label": "default"}])
        ]
        client = (
            RestAlgorithmClient(spec.server_url, token=spec.token)
            if spec.server_url
            else None
        )
        env = AlgorithmEnvironment(
            dataframes=frames,
            client=client,
            metadata=RunMetadata(
                task_id=spec.task_id,
                run_id=spec.run_id,
                node_id=spec.metadata.get("node_id"),
                organization=spec.metadata.get("organization", ""),
                collaboration=spec.metadata.get("collaboration", ""),
            ),
            station_secret=self.station_secret,
            identity=(
                self._load_identity if self.identity_key_path else None
            ),
            org_identities=self.org_identities,
        )
        args = spec.input_payload.get("args", []) or []
        kwargs = spec.input_payload.get("kwargs", {}) or {}
        with algorithm_environment(env):
            return fn(*args, **kwargs)

    def _load_identity(self):
        """Lazy org-identity cryptor (zero-arg factory for the run env)."""
        from vantage6_tpu.common.encryption import RSACryptor

        return RSACryptor(self.identity_key_path)

    # ----------------------------------------------------------- sandbox
    def _run_sandbox(self, module: str, spec: RunSpec) -> Any:
        """Subprocess speaking the container ABI (reference: docker run)."""
        run_dir = self.work_dir / f"run_{spec.run_id}"
        run_dir.mkdir(parents=True, exist_ok=True)
        input_file = run_dir / "input"
        output_file = run_dir / "output"
        token_file = run_dir / "token"
        # INPUT_FILE rides the v2 binary wire by default (raw aligned array
        # buffers, no base64 — docs/wire_format.md); node policy
        # `wire_format: v1` pins the legacy JSON ABI for old algorithm
        # containers. wrap_algorithm auto-detects on read either way.
        wire_format = self.policies.get("wire_format")
        input_file.write_bytes(serialize(spec.input_payload, format=wire_format))
        token_file.write_text(spec.token)

        # the child must be able to import vantage6_tpu regardless of the
        # node's cwd or whether the package is pip-installed: pin the
        # directory that contains this very package onto its PYTHONPATH
        import vantage6_tpu

        pkg_root = str(Path(vantage6_tpu.__file__).resolve().parent.parent)
        env = {
            **os.environ,
            "PYTHONPATH": os.pathsep.join(
                p for p in (pkg_root, os.environ.get("PYTHONPATH")) if p
            ),
            "INPUT_FILE": str(input_file),
            "OUTPUT_FILE": str(output_file),
            "TOKEN_FILE": str(token_file),
            "TASK_ID": str(spec.task_id),
            "RUN_ID": str(spec.run_id),
            "TEMPORARY_FOLDER": str(run_dir),
        }
        if wire_format:
            # the child's OUTPUT_FILE serialize follows the same node policy
            env["V6T_WIRE_FORMAT"] = str(wire_format)
        # trace context crosses the ABI: the subprocess executes under a
        # span joined on this (wrap_algorithm reads it), so the child's
        # subtask fan-out stays in the task's trace (docs/observability.md)
        from vantage6_tpu.runtime.tracing import TRACER

        traceparent = TRACER.current_traceparent()
        if traceparent:
            env["V6T_TRACEPARENT"] = traceparent
        if not self.policies.get("accelerator", False):
            # sandboxed algorithms default to CPU, like the reference's
            # containers: faster startup and no contention for (or hangs on)
            # the host's accelerator; opt in via policies: {accelerator: true}
            env["JAX_PLATFORMS"] = "cpu"
            env["PALLAS_AXON_POOL_IPS"] = ""
        if spec.server_url:
            env["V6T_SERVER_URL"] = spec.server_url
        if self.station_secret:
            env["V6T_STATION_SECRET"] = self.station_secret.hex()
        if self.identity_key_path:
            env["V6T_IDENTITY_KEY"] = str(self.identity_key_path)
        if self.org_identities:
            env["V6T_ORG_IDENTITIES"] = json.dumps(
                {str(k): v for k, v in self.org_identities.items()}
            )
        # network gates cross the ABI as JSON so the sandboxed loader
        # enforces the same egress policy the inline path does
        if self.egress.enabled:
            env["V6T_EGRESS"] = json.dumps(dataclasses.asdict(self.egress))
        if self.ssh_tunnels.tunnels:
            env["V6T_SSH_TUNNELS"] = json.dumps(
                list(self.ssh_tunnels.tunnels.values())
            )
        requested = spec.databases or [{"label": "default"}]
        env["USER_REQUESTED_DATABASE_LABELS"] = ",".join(
            d.get("label", "default") for d in requested
        )
        for d in requested:
            label = d.get("label", "default")
            cfg = self._db_config(d, spec.session_id)
            env[f"DATABASE_{label.upper()}_URI"] = str(cfg.get("uri", ""))
            env[f"DATABASE_{label.upper()}_TYPE"] = str(cfg.get("type", "csv"))
            env[f"DATABASE_{label.upper()}_OPTIONS"] = json.dumps(
                cfg.get("options", {}) or {}
            )
        for k, v in spec.metadata.items():
            if k in ("node_id",):
                env["NODE_ID"] = str(v)
            elif k == "organization":
                env["ORGANIZATION_NAME"] = str(v)
            elif k == "collaboration":
                env["COLLABORATION_NAME"] = str(v)

        # child of the daemon's runner.exec span: separates subprocess
        # spawn+ABI overhead from the run's total (inline mode has none,
        # which is exactly what this makes visible in the per-hop table)
        from vantage6_tpu.runtime.tracing import TRACER

        with TRACER.span(
            "runner.sandbox", kind="sandbox",
            attrs={"run_id": spec.run_id, "image": spec.image},
            require_parent=True,
        ):
            proc = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "from vantage6_tpu.algorithm.wrap import wrap_algorithm; "
                    f"wrap_algorithm({module!r})",
                ],
                env=env,
                capture_output=True,
                text=True,
                timeout=self.policies.get("task_timeout", 600),
            )
        (run_dir / "log").write_text(proc.stdout + proc.stderr)
        if proc.returncode != 0:
            raise RuntimeError(
                f"algorithm exited {proc.returncode}:\n"
                + (proc.stderr or proc.stdout)[-2000:]
            )
        if not output_file.exists():
            raise RuntimeError("algorithm wrote no OUTPUT_FILE")
        # writable: harvested results are handed onward to caller code
        # that may mutate them (v1 semantics)
        return deserialize(output_file.read_bytes(), writable=True)

    # ----------------------------------------------------------------- util
    def _db_config(
        self, requested: dict[str, Any], session_id: int | None = None
    ) -> dict[str, Any]:
        label = requested.get("label", "default")
        if requested.get("type") == "session":
            # session dataframe reference: resolve to this node's LOCAL
            # session store (materialized by an earlier store_as task)
            handle = requested.get("dataframe") or label
            if session_id is None:
                raise KeyError(
                    f"database {label!r} references session dataframe "
                    f"{handle!r} but the task carries no session"
                )
            path = self.session_file(session_id, handle)
            if not path.exists():
                raise KeyError(
                    f"session {session_id} has no materialized dataframe "
                    f"{handle!r} at this node (did its extraction task run?)"
                )
            return {
                "label": label,
                "type": "session",
                "uri": str(path),
                "options": {},
            }
        cfg = self.databases.get(label)
        if cfg is None:
            raise KeyError(
                f"node has no database labeled {label!r} "
                f"(configured: {sorted(self.databases)})"
            )
        return {
            "label": label,
            "type": cfg.get("type", "csv"),
            "uri": cfg.get("uri", ""),
            "options": cfg.get("options", {}) or {},
        }
