"""Native runtime kernels (C++ via ctypes) with bit-identical numpy fallback.

The reference ships no native code (SURVEY.md §2.2); this package is the
rebuild's native layer for the *cross-host* secure-aggregation path: ChaCha20
pairwise mask generation, fixed-point quantization and wrapping modular sums
at memory bandwidth instead of interpreter speed. The on-pod path never
comes here (XLA collectives); nodes use this before uploading results to a
remote control plane.

`lib()` compiles `secureagg.cpp` on first use with g++ (cached next to the
package); every entry point transparently falls back to numpy when no
compiler is available, and the two implementations are bit-identical (tested
against each other and the RFC 8439 vector).
"""
from __future__ import annotations

import ctypes
import hashlib
import hmac
import os
import subprocess
import tempfile
from functools import lru_cache
from pathlib import Path

import numpy as np

from vantage6_tpu.common.log import setup_logging

log = setup_logging("vantage6_tpu/native")

_SRC = Path(__file__).parent / "secureagg.cpp"


@lru_cache(maxsize=1)
def lib() -> ctypes.CDLL | None:
    """Compile-on-first-use; None => use the numpy fallback."""
    if os.environ.get("V6T_DISABLE_NATIVE"):
        return None
    # per-user cache dir, 0700: a world-writable shared path (/tmp) would let
    # another local user plant a .so that we'd load into the node process
    default_cache = Path(
        os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache")
    ) / "v6t_native"
    cache_dir = Path(os.environ.get("V6T_NATIVE_CACHE", default_cache))
    cache_dir.mkdir(parents=True, exist_ok=True)
    os.chmod(cache_dir, 0o700)
    so_path = cache_dir / "libv6t_secureagg.so"
    if not so_path.exists() or so_path.stat().st_mtime < _SRC.stat().st_mtime:
        # build to a unique temp name, then atomically publish: concurrent
        # daemons must never CDLL a half-linked file
        fd, tmp_so = tempfile.mkstemp(suffix=".so", dir=cache_dir)
        os.close(fd)
        try:
            subprocess.run(
                [
                    "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                    str(_SRC), "-o", tmp_so,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp_so, so_path)
        except (subprocess.SubprocessError, FileNotFoundError) as e:
            Path(tmp_so).unlink(missing_ok=True)
            log.warning("native build failed (%s); using numpy fallback", e)
            return None
    try:
        dll = ctypes.CDLL(str(so_path))
    except OSError as e:  # pragma: no cover
        log.warning("cannot load %s (%s); using numpy fallback", so_path, e)
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    dll.v6t_chacha20_stream.argtypes = [u8p, u8p, u32p, ctypes.c_size_t]
    dll.v6t_pairwise_mask_i32.argtypes = [
        u8p, ctypes.c_uint32, ctypes.c_uint32, i32p, ctypes.c_size_t,
    ]
    dll.v6t_quantize_f32.argtypes = [f32p, i32p, ctypes.c_size_t, ctypes.c_float]
    dll.v6t_dequantize_i32.argtypes = [i32p, f32p, ctypes.c_size_t, ctypes.c_float]
    dll.v6t_sum_i32_wrap.argtypes = [i32p, i32p, ctypes.c_size_t, ctypes.c_size_t]
    return dll


def native_available() -> bool:
    return lib() is not None


# ------------------------------------------------------------ numpy fallback


def _chacha20_stream_np(key: bytes, nonce: bytes, n: int) -> np.ndarray:
    """RFC 8439 ChaCha20 keystream as n uint32 words (vectorized blocks)."""
    assert len(key) == 32 and len(nonce) == 12
    blocks = (n + 15) // 16
    state = np.empty((blocks, 16), np.uint32)
    state[:, 0:4] = np.array(
        [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], np.uint32
    )
    state[:, 4:12] = np.frombuffer(key, np.uint32)
    state[:, 12] = np.arange(blocks, dtype=np.uint32)
    state[:, 13:16] = np.frombuffer(nonce, np.uint32)
    w = state.copy()

    def rotl(x, r):
        return (x << np.uint32(r)) | (x >> np.uint32(32 - r))

    def quarter(a, b, c, d):
        w[:, a] += w[:, b]; w[:, d] ^= w[:, a]; w[:, d] = rotl(w[:, d], 16)
        w[:, c] += w[:, d]; w[:, b] ^= w[:, c]; w[:, b] = rotl(w[:, b], 12)
        w[:, a] += w[:, b]; w[:, d] ^= w[:, a]; w[:, d] = rotl(w[:, d], 8)
        w[:, c] += w[:, d]; w[:, b] ^= w[:, c]; w[:, b] = rotl(w[:, b], 7)

    with np.errstate(over="ignore"):
        for _ in range(10):
            quarter(0, 4, 8, 12)
            quarter(1, 5, 9, 13)
            quarter(2, 6, 10, 14)
            quarter(3, 7, 11, 15)
            quarter(0, 5, 10, 15)
            quarter(1, 6, 11, 12)
            quarter(2, 7, 8, 13)
            quarter(3, 4, 9, 14)
        w += state
    return w.reshape(-1)[:n]


def pair_nonce(i: int, j: int) -> bytes:
    """The 96-bit nonce for pair (i, j): words [i, j, 0] little-endian —
    the shared contract of the C++ kernel, the numpy fallback, and the DH
    path (common.secureagg_dh) which reuses the keystream with per-pair
    keys."""
    return (
        int(i).to_bytes(4, "little")
        + int(j).to_bytes(4, "little")
        + b"\x00\x00\x00\x00"
    )


_pair_nonce = pair_nonce


# -------------------------------------------------------------- public API


def chacha20_stream(key: bytes, nonce: bytes, n: int) -> np.ndarray:
    """n uint32 keystream words."""
    if len(key) != 32 or len(nonce) != 12:
        raise ValueError(
            f"key must be 32 bytes and nonce 12 (got {len(key)}/{len(nonce)})"
        )
    dll = lib()
    if dll is None:
        return _chacha20_stream_np(key, nonce, n)
    out = np.empty(n, np.uint32)
    dll.v6t_chacha20_stream(
        np.frombuffer(bytearray(key), np.uint8).ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint8)
        ),
        np.frombuffer(bytearray(nonce), np.uint8).ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint8)
        ),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        n,
    )
    return out


def quantize(x: np.ndarray, scale: float) -> np.ndarray:
    """float32 -> fixed-point int32 (np.rint semantics on both paths).

    Raises when a value itself exceeds the int32 range at this scale —
    silent wrap-around here would corrupt the aggregate undetectably.
    Callers must ALSO budget for the sum: pick
    ``scale <= 2**31 / (n_parties * max|value|)``.
    """
    x = np.ascontiguousarray(x, np.float32)
    # the guard must use the SAME float32 product the kernels compute:
    # f32 multiplication is magnitude-monotonic, so checking the peak in f32
    # bounds every element; any f32 < 2^31 is <= 2147483520 and casts safely
    peak = np.float32(np.max(np.abs(x))) if x.size else np.float32(0)
    # NOT (prod < limit), so NaN/inf inputs are rejected too — NaN would
    # sail through a `prod >= limit` check and corrupt the aggregate
    prod = np.float32(peak) * np.float32(scale)
    if not prod < np.float32(2.0**31):
        raise ValueError(
            f"quantization overflow/invalid: max |value| {float(peak):g} * "
            f"scale {scale:g} not inside int32 range (NaN/inf values are "
            "rejected here too)"
        )
    dll = lib()
    if dll is None:
        return np.rint(x * scale).astype(np.int32)
    out = np.empty(x.size, np.int32)
    dll.v6t_quantize_f32(
        x.reshape(-1).ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        x.size,
        scale,
    )
    return out.reshape(x.shape)


def dequantize(q: np.ndarray, scale: float) -> np.ndarray:
    q = np.ascontiguousarray(q, np.int32)
    dll = lib()
    if dll is None:
        # float32 cast-then-divide, matching the C++ kernel bit-for-bit
        # (float64 division would differ for |q| > 2^24)
        return q.astype(np.float32) / np.float32(scale)
    out = np.empty(q.size, np.float32)
    dll.v6t_dequantize_i32(
        q.reshape(-1).ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        q.size,
        scale,
    )
    return out.reshape(q.shape)


def derive_mask_key(seed: bytes, tag: bytes | str | int) -> bytes:
    """Per-aggregation 32-byte subkey: HMAC-SHA256(seed, context || tag).

    The pairwise mask nonce is only (i, j) — it carries no round/task
    identity — so REUSING one key across two aggregations produces
    byte-identical masks, and the relaying server (exactly the party the
    threat model defends against) could difference a station's two uploads
    to cancel them and recover the quantized plaintext delta. Every
    aggregation must therefore run under a fresh subkey; all parties derive
    it from the provisioned long-term seed plus a shared per-aggregation
    ``tag`` (task id, round number, …) that need not be secret.
    """
    if isinstance(tag, int):
        tag = str(tag)
    if isinstance(tag, str):
        tag = tag.encode()
    return hmac.new(seed, b"v6t-secureagg-mask-v1:" + tag,
                    hashlib.sha256).digest()


def add_pairwise_masks(
    seed: bytes,
    station: int,
    n_stations: int,
    quantized: np.ndarray,
    tag: bytes | str | int = b"",
) -> np.ndarray:
    """Return `quantized` plus this station's pairwise masks (mod 2^32).

    For each pair (i, j), i < j, station i adds +PRG, station j adds -PRG;
    summed over all stations the masks cancel exactly. The keystream key is
    ``derive_mask_key(seed, tag)`` — pass a distinct ``tag`` per aggregation
    (see that function for why reuse is a real unmasking attack).
    """
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    seed = derive_mask_key(seed, tag)
    q = np.ascontiguousarray(quantized, np.int32)
    dll = lib()
    if dll is not None:
        buf = q.reshape(-1).copy()
        dll.v6t_pairwise_mask_i32(
            np.frombuffer(bytearray(seed), np.uint8).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)
            ),
            int(station),
            int(n_stations),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            buf.size,
        )
        return buf.reshape(q.shape)
    acc = q.reshape(-1).astype(np.uint32)
    with np.errstate(over="ignore"):
        for other in range(n_stations):
            if other == station:
                continue
            i, j = min(station, other), max(station, other)
            stream = _chacha20_stream_np(seed, _pair_nonce(i, j), acc.size)
            acc = acc + stream if station == i else acc - stream
    return acc.astype(np.int32).reshape(q.shape)


def sum_wrapping(stacked: np.ndarray) -> np.ndarray:
    """Column sum of [S, n] int32 with mod-2^32 wrap-around."""
    x = np.ascontiguousarray(stacked, np.int32)
    if x.ndim == 1:
        x = x[None]
    s, n = x.shape[0], x[0].size
    dll = lib()
    if dll is None:
        with np.errstate(over="ignore"):
            return (
                x.reshape(s, -1)
                .astype(np.uint32)
                .sum(axis=0, dtype=np.uint32)
                .astype(np.int32)
                .reshape(x.shape[1:])
            )
    out = np.empty(n, np.int32)
    dll.v6t_sum_i32_wrap(
        x.reshape(-1).ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        s,
        n,
    )
    return out.reshape(x.shape[1:])


# ------------------------------------------------------- high-level helpers


def mask_update(
    seed: bytes,
    station: int,
    n_stations: int,
    values: np.ndarray,
    scale: float = 2.0**16,
    tag: bytes | str | int = b"",
) -> np.ndarray:
    """What a node uploads: quantized values + this station's masks.

    ``tag`` must be shared by all parties of ONE aggregation and differ
    between aggregations (see derive_mask_key)."""
    return add_pairwise_masks(
        seed, station, n_stations, quantize(values, scale), tag=tag
    )


def unmask_sum(masked: np.ndarray, scale: float = 2.0**16) -> np.ndarray:
    """What the aggregator computes: masks cancel in the wrapping sum."""
    return dequantize(sum_wrapping(masked), scale)
