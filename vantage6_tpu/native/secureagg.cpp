// Native secure-aggregation kernels for the cross-host path.
//
// The reference (vantage6) has no native code (SURVEY.md §2.2); its secure
// sums live in algorithm repos as Paillier bigint — seconds per vector. This
// library is the rebuild's native equivalent for payloads that LEAVE the pod
// (node -> server REST deployment): each station adds pairwise ChaCha20
// keystream masks (mod 2^32) to its quantized update before upload; the
// masks cancel exactly in the server-side modular sum. On-pod aggregation
// never comes here — it lowers to XLA collectives.
//
// Contract mirrored bit-for-bit by the numpy fallback in
// vantage6_tpu/native/__init__.py:
//   - ChaCha20 (RFC 8439 block function, 20 rounds, counter from 0)
//   - pair (i, j), i < j: 96-bit nonce = words [i, j, 0] (little-endian)
//   - station s adds +mask(i,j) if s == i else -mask(i,j), mod 2^32
//   - the `seed` these kernels receive is a PER-AGGREGATION subkey
//     (HMAC-SHA256 of the provisioned long-term seed and an aggregation
//     tag, derived host-side in derive_mask_key): the nonce carries no
//     round identity, so a key reused across two aggregations would emit
//     identical masks and let the relay difference two uploads to unmask
//     them. Callers must never feed the long-term seed here directly.
//
// Build: g++ -O3 -shared -fPIC (no external deps).

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

inline uint32_t rotl(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

inline void quarter(uint32_t& a, uint32_t& b, uint32_t& c, uint32_t& d) {
  a += b; d ^= a; d = rotl(d, 16);
  c += d; b ^= c; b = rotl(b, 12);
  a += b; d ^= a; d = rotl(d, 8);
  c += d; b ^= c; b = rotl(b, 7);
}

// One ChaCha20 block: 16 words of keystream.
void chacha20_block(const uint32_t key[8], uint32_t counter,
                    const uint32_t nonce[3], uint32_t out[16]) {
  static const uint32_t kConst[4] = {0x61707865u, 0x3320646eu, 0x79622d32u,
                                     0x6b206574u};
  uint32_t s[16];
  s[0] = kConst[0]; s[1] = kConst[1]; s[2] = kConst[2]; s[3] = kConst[3];
  std::memcpy(s + 4, key, 32);
  s[12] = counter;
  s[13] = nonce[0]; s[14] = nonce[1]; s[15] = nonce[2];
  uint32_t w[16];
  std::memcpy(w, s, sizeof(w));
  for (int r = 0; r < 10; ++r) {
    quarter(w[0], w[4], w[8], w[12]);
    quarter(w[1], w[5], w[9], w[13]);
    quarter(w[2], w[6], w[10], w[14]);
    quarter(w[3], w[7], w[11], w[15]);
    quarter(w[0], w[5], w[10], w[15]);
    quarter(w[1], w[6], w[11], w[12]);
    quarter(w[2], w[7], w[8], w[13]);
    quarter(w[3], w[4], w[9], w[14]);
  }
  for (int i = 0; i < 16; ++i) out[i] = w[i] + s[i];
}

}  // namespace

extern "C" {

// Fill `out[n]` with ChaCha20 keystream words. `key` is 32 bytes
// (little-endian words); nonce96 is 12 bytes.
void v6t_chacha20_stream(const uint8_t* key_bytes, const uint8_t* nonce_bytes,
                         uint32_t* out, size_t n) {
  uint32_t key[8], nonce[3];
  std::memcpy(key, key_bytes, 32);
  std::memcpy(nonce, nonce_bytes, 12);
  uint32_t block[16];
  uint32_t counter = 0;
  size_t i = 0;
  while (i < n) {
    chacha20_block(key, counter++, nonce, block);
    size_t take = (n - i) < 16 ? (n - i) : 16;
    std::memcpy(out + i, block, take * sizeof(uint32_t));
    i += take;
  }
}

// Add this station's pairwise masks to `buf[n]` in place (wrapping int32).
// seed: 32-byte shared federation seed. For every pair (i, j), i < j, the
// mask stream's 96-bit nonce is [i, j, 0]; station i adds +, station j adds -.
void v6t_pairwise_mask_i32(const uint8_t* seed, uint32_t station,
                           uint32_t n_stations, int32_t* buf, size_t n) {
  uint32_t key[8];
  std::memcpy(key, seed, 32);
  uint32_t* stream = new uint32_t[n];
  for (uint32_t other = 0; other < n_stations; ++other) {
    if (other == station) continue;
    uint32_t i = station < other ? station : other;
    uint32_t j = station < other ? other : station;
    uint32_t nonce[3] = {i, j, 0};
    uint32_t block[16];
    uint32_t counter = 0;
    size_t pos = 0;
    while (pos < n) {
      chacha20_block(key, counter++, nonce, block);
      size_t take = (n - pos) < 16 ? (n - pos) : 16;
      std::memcpy(stream + pos, block, take * sizeof(uint32_t));
      pos += take;
    }
    if (station == i) {
      for (size_t k = 0; k < n; ++k)
        buf[k] = (int32_t)((uint32_t)buf[k] + stream[k]);
    } else {
      for (size_t k = 0; k < n; ++k)
        buf[k] = (int32_t)((uint32_t)buf[k] - stream[k]);
    }
  }
  delete[] stream;
}

// Quantize float -> fixed-point int32 with round-half-away-from-zero
// (matches numpy's np.round... careful: np.round is half-to-even; we use
// rint to match np.rint exactly on both sides).
void v6t_quantize_f32(const float* in, int32_t* out, size_t n, float scale) {
  for (size_t k = 0; k < n; ++k) {
    out[k] = (int32_t)__builtin_rintf(in[k] * scale);
  }
}

void v6t_dequantize_i32(const int32_t* in, float* out, size_t n, float scale) {
  for (size_t k = 0; k < n; ++k) out[k] = (float)in[k] / scale;
}

// Wrapping column sum over S stacked int32 vectors: out[k] = sum_s x[s][k]
// (mod 2^32). This is the server-side aggregation of masked uploads.
void v6t_sum_i32_wrap(const int32_t* stacked, int32_t* out, size_t s,
                      size_t n) {
  for (size_t k = 0; k < n; ++k) out[k] = 0;
  for (size_t row = 0; row < s; ++row) {
    const int32_t* x = stacked + row * n;
    for (size_t k = 0; k < n; ++k)
      out[k] = (int32_t)((uint32_t)out[k] + (uint32_t)x[k]);
  }
}

}  // extern "C"
