"""Transfer guards: the TPU-world analogue of race/sanitizer checks.

SURVEY.md §5 maps the reference's (absent) race detection to "jax
transfer-guard / donation checks" here: the federated hot loop must be
device-resident — an implicit host→device transfer inside a round means
some array silently fell off the mesh (a performance bug at best, a
stale-host-copy correctness bug at worst). Wrap round loops in
``no_implicit_transfers()`` in tests/benchmarks to make that a hard error
instead of a silent HBM↔host round trip.
"""
from __future__ import annotations

import contextlib
from typing import Iterator

import jax


@contextlib.contextmanager
def no_implicit_transfers() -> Iterator[None]:
    """Raise on any IMPLICIT host<->device transfer inside the block.

    Explicit movement (`jax.device_put`, `np.asarray(x)`, `.block_until_ready`
    on results you then pull) stays allowed — the guard targets the silent
    transfers jit tracing inserts when an operand lives on the wrong side.
    """
    with jax.transfer_guard("disallow"):
        yield


@contextlib.contextmanager
def log_transfers() -> Iterator[None]:
    """Diagnostic mode: report implicit transfers without failing."""
    with jax.transfer_guard("log"):
        yield
