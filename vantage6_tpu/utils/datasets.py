"""Synthetic datasets + federated partitioners.

This image has no network and no MNIST on disk, so benchmarks and tests use a
structured synthetic generator: each class gets a fixed random template and
samples are template + noise. A small CNN genuinely has to learn the
templates, so accuracy curves behave like a real (if easy) image task —
enough for convergence tests and for throughput benchmarking, which is
shape-dependent, not content-dependent.

Partitioners mirror the federated reality the reference serves: horizontally
partitioned data across organizations, either iid or Dirichlet non-iid (the
standard FedAvg heterogeneity knob).
"""
from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path

import numpy as np


def synthetic_image_classes(
    n: int,
    *,
    n_classes: int = 10,
    shape: tuple[int, int, int] = (28, 28, 1),
    noise: float = 0.7,
    seed: int = 0,
    template_seed: int = 1234,
) -> tuple[np.ndarray, np.ndarray]:
    """MNIST-shaped synthetic data: class template + gaussian noise.

    ``template_seed`` fixes the class templates independently of ``seed`` so
    differently-seeded draws (train vs eval) come from the SAME task.
    """
    rng = np.random.default_rng(seed)
    templates = (
        np.random.default_rng(template_seed)
        .normal(size=(n_classes, *shape))
        .astype(np.float32)
    )
    labels = rng.integers(0, n_classes, size=n).astype(np.int32)
    x = templates[labels] + noise * rng.normal(size=(n, *shape)).astype(
        np.float32
    )
    return x, labels


def _read_idx(path: Path) -> np.ndarray:
    """IDX (LeCun MNIST format) reader — magic 0x0801 (labels) / 0x0803
    (images); transparently decompresses .gz."""
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:  # type: ignore[operator]
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path}: not an IDX file")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        if dtype_code != 0x08:  # ubyte, the only type MNIST uses
            raise ValueError(f"{path}: unsupported IDX dtype {dtype_code:#x}")
        data = np.frombuffer(f.read(), np.uint8)
    return data.reshape(dims)


def load_mnist(
    data_dir: str | Path | None = None, split: str = "train"
) -> tuple[np.ndarray, np.ndarray] | None:
    """Real MNIST from a local directory, if present; else None.

    Makes BASELINE.md's accuracy-parity criterion measurable the moment the
    files exist (no network in this image, so they must be provided). The
    directory — ``data_dir`` arg, else $V6T_MNIST_DIR, else ./data/mnist —
    may hold either:
      - ``mnist.npz`` with arrays x_train/y_train/x_test/y_test (keras
        layout), or
      - the classic IDX pair ``train-images-idx3-ubyte[.gz]`` +
        ``train-labels-idx1-ubyte[.gz]`` (and t10k-* for split="test").

    Returns (x [n,28,28,1] float32 in [0,1], y [n] int32), or None when
    nothing is found — callers fall back to the synthetic generator.
    """
    root = Path(
        data_dir
        or os.environ.get("V6T_MNIST_DIR", "")
        or Path("data") / "mnist"
    )
    npz = root / "mnist.npz"
    if npz.exists():
        with np.load(npz) as z:
            x = z[f"x_{split}"]
            y = z[f"y_{split}"]
    else:
        prefix = "train" if split == "train" else "t10k"
        images = labels = None
        for suffix in ("", ".gz"):
            ip = root / f"{prefix}-images-idx3-ubyte{suffix}"
            lp = root / f"{prefix}-labels-idx1-ubyte{suffix}"
            if ip.exists() and lp.exists():
                images, labels = ip, lp
                break
        if images is None:
            return None
        x = _read_idx(images)
        y = _read_idx(labels)
    x = np.asarray(x, np.float32) / 255.0
    if x.ndim == 3:
        x = x[..., None]
    return x, np.asarray(y, np.int32)


def image_classes(
    n: int,
    *,
    seed: int = 0,
    data_dir: str | Path | None = None,
    noise: float = 0.7,
) -> tuple[np.ndarray, np.ndarray]:
    """n MNIST-shaped examples: REAL MNIST when a local copy exists
    (sampled with `seed`), synthetic templates otherwise — the single entry
    point workloads/benchmarks use. ``noise`` is the synthetic task's
    difficulty knob (ignored on real data): raising it takes few-round
    accuracy below the ceiling so a parity gap has room to show
    (VERDICT r3 weak #2; bench.py sets the calibrated value)."""
    real = load_mnist(data_dir)
    if real is None:
        return synthetic_image_classes(n, seed=seed, noise=noise)
    x, y = real
    idx = np.random.default_rng(seed).choice(
        len(x), size=n, replace=n > len(x)
    )
    return x[idx], y[idx]


def synthetic_tabular(
    n: int,
    *,
    n_features: int = 16,
    seed: int = 0,
    noise: float = 0.5,
) -> tuple[np.ndarray, np.ndarray]:
    """Linearly separable-ish binary tabular data for logistic regression."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=n_features).astype(np.float32)
    x = rng.normal(size=(n, n_features)).astype(np.float32)
    logits = x @ w + noise * rng.normal(size=n).astype(np.float32)
    y = (logits > 0).astype(np.float32)
    return x, y


def partition_iid(
    x: np.ndarray, y: np.ndarray, n_stations: int, seed: int = 0
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Shuffle + equal split. Truncates the remainder so shards are
    homogeneous (SPMD static shapes; see partition_padded for ragged)."""
    rng = np.random.default_rng(seed)
    idx = rng.permutation(len(x))
    per = len(x) // n_stations
    return [
        (x[idx[i * per:(i + 1) * per]], y[idx[i * per:(i + 1) * per]])
        for i in range(n_stations)
    ]


def partition_dirichlet(
    x: np.ndarray,
    y: np.ndarray,
    n_stations: int,
    alpha: float = 0.5,
    seed: int = 0,
    n_classes: int | None = None,
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Non-iid label-skew split: per class, proportions ~ Dirichlet(alpha).

    Low alpha -> strong heterogeneity (each station dominated by few
    classes) — the standard FedAvg stress test. Shards are ragged; pad with
    `pad_shards` before stacking for device mode.
    """
    rng = np.random.default_rng(seed)
    classes = np.unique(y) if n_classes is None else np.arange(n_classes)
    station_idx: list[list[int]] = [[] for _ in range(n_stations)]
    for c in classes:
        c_idx = np.flatnonzero(y == c)
        rng.shuffle(c_idx)
        props = rng.dirichlet([alpha] * n_stations)
        cuts = (np.cumsum(props) * len(c_idx)).astype(int)[:-1]
        for s, part in enumerate(np.split(c_idx, cuts)):
            station_idx[s].extend(part.tolist())
    out = []
    for s in range(n_stations):
        idx = np.asarray(station_idx[s], dtype=int)
        rng.shuffle(idx)
        out.append((x[idx], y[idx]))
    return out


def pad_shards(
    shards: list[tuple[np.ndarray, np.ndarray]],
    pad_to: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Ragged shards -> stacked [S, n_max, ...] + labels + true counts.

    SPMD needs static shapes (SURVEY.md §7 hard part 3): short stations are
    zero-padded; `counts` carries true sizes for weighted aggregation and
    batch masking.
    """
    n_max = pad_to or max(len(sx) for sx, _ in shards)
    xs, ys, counts = [], [], []
    for sx, sy in shards:
        n = len(sx)
        if n > n_max:
            raise ValueError(f"shard of {n} exceeds pad_to={n_max}")
        pad_n = n_max - n
        xs.append(np.concatenate([sx, np.zeros((pad_n, *sx.shape[1:]),
                                               sx.dtype)]))
        ys.append(np.concatenate([sy, np.zeros((pad_n, *sy.shape[1:]),
                                               sy.dtype)]))
        counts.append(n)
    return np.stack(xs), np.stack(ys), np.asarray(counts, np.float32)
