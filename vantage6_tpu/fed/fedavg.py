"""FedAvg engine: a federated round as ONE compiled SPMD program.

This is the TPU-native rewrite of the reference's central/partial round
(SURVEY.md §3.2): where vantage6 pays SocketIO fan-out + N container
lifecycles + 2N HTTPS result hops + polling per round, here a round is a
single jitted program — per-station local SGD under `fed_map` (shard_map over
the station axis), aggregation as a weighted mean the GSPMD partitioner
lowers to an all-reduce over ICI. `run_rounds` additionally folds the round
loop into `lax.scan`, so an entire training run is one XLA computation with
zero host round-trips.

Semantics kept from the reference world:
- per-station example counts weight the aggregation (ragged shards are
  padded; sampling respects true counts);
- a participation mask drops stations (offline nodes / stragglers / failure
  injection) bit-accurately — FedAvg-with-dropout, the SPMD answer to the
  reference's asynchrony (SURVEY.md §7 hard part 1);
- a server optimizer generalizes plain averaging (optax.sgd(1.0) == FedAvg;
  adam == FedAdam etc., Reddi et al. 2021).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from vantage6_tpu.core.mesh import FederationMesh
from vantage6_tpu.fed.collectives import (
    all_gather_stations,
    fed_mean,
    fed_mean_scattered,
    flat_size,
    flatten_stacked,
    flatten_tree,
    padded_flat_size,
    per_round_masks,
    station_update_stats,
    unflatten_like,
    unflatten_stacked,
)
from vantage6_tpu.common.telemetry import REGISTRY
from vantage6_tpu.fed.compression import (
    CompressorSpec,
    compress_stacked,
    record_round_telemetry,
)
from vantage6_tpu.runtime.profiling import observed_jit

Pytree = Any
# loss_fn(params, batch_x, batch_y, example_weights) -> scalar mean loss
LossFn = Callable[[Pytree, jax.Array, jax.Array, jax.Array], jax.Array]


@dataclasses.dataclass(frozen=True)
class FedAvgSpec:
    loss_fn: LossFn
    local_steps: int = 1
    batch_size: int = 32
    local_lr: float = 0.1
    server_optimizer: optax.GradientTransformation | None = None  # default sgd(1)
    # Sharded server update (ZeRO-1 over the station axis): the pseudo-
    # gradient is reduce-scattered, server-optimizer moments and the optax
    # update live only on each slot's 1/D flat param shard, and params are
    # all-gathered once per round. Replicated and sharded modes are
    # numerically equivalent in f32 (tests/test_scattered_update.py parity).
    shard_server_update: bool = False
    # On-wire dtype of the delta reduce-scatter (e.g. jnp.bfloat16 halves
    # collective bytes). Master params, moments and post-scatter math stay
    # f32 — see docs/sharded_update.md for the accuracy caveats. Used by
    # the scattered exchange (shard_server_update=True) and, when a
    # compressor is set, as the pre-quantization cast (cast, THEN
    # quantize — docs/compression.md composition order).
    comm_dtype: Any = None
    # Gradient compression of the per-station delta uplink (CompressorSpec,
    # docs/compression.md): stochastic int8 and/or top-k with per-station
    # error-feedback accumulators carried in the optimizer state. The
    # aggregation consumes the DECOMPRESSED deltas, so this composes with
    # both the replicated and the scattered (ZeRO-1) server update.
    compressor: CompressorSpec | None = None
    # Learning-plane statistics (docs/observability.md "learning plane"):
    # per-station update L2 norms, cosine-to-pooled-delta, per-station EF
    # mass and the global update norm, computed INSIDE the jitted round at
    # the flat-pack seam (collectives.station_update_stats) and returned
    # as the 4th element of round()/run_rounds(). fp32-identical between
    # the replicated and scattered update paths. Off = stats come back as
    # an empty dict and the round pays nothing for them.
    learning_stats: bool = True
    # Unroll factor of the inner local-steps lax.scan (True = fully
    # unrolled, no while loop). Semantics and RNG streams are identical at
    # any value — a pure compilation-strategy knob. XLA:CPU runs
    # convolutions inside while-loop bodies ~6x slower than in straight-
    # line code (measured, docs/device_speed.md), so CPU callers of the
    # fused path want True; on TPU the scan form compiles faster and runs
    # at the same speed, so the default stays 1.
    local_unroll: int | bool = 1


@dataclasses.dataclass(frozen=True)
class AsyncRoundSpec:
    """FedBuff-style buffered-async round shape (Nguyen et al. 2022).

    The server dispatches ``quorum + over_select`` stations, aggregates
    the FIRST ``quorum`` results to arrive, and kills whatever is still
    running at quorum (or at ``deadline_s``, whichever comes first).
    Non-accepted stations accrue **staleness**: when a stale station's
    update finally lands in a later round, it participates discounted by
    ``staleness_discount ** staleness`` — the standard FedBuff weighting
    that keeps slow-but-honest contributors in the model without letting
    their stale gradients drag it backwards.

    The discount rides the existing participation-mask seam
    (:meth:`FedAvg.async_round` folds it into ``mask``), so the jitted
    round program is byte-identical to the synchronous one: compression
    error-feedback still waits on mask==0 stations, learning stats stay
    participation-aware, and no new traced signature is introduced.
    """

    quorum: int                      # K: accept the first K results
    over_select: int = 1             # m: dispatch K + m stations
    staleness_discount: float = 0.5  # weight multiplier per round of staleness
    deadline_s: float = 30.0         # hard per-round wall-clock cap

    def validate(self) -> None:
        if self.quorum < 1:
            raise ValueError("AsyncRoundSpec.quorum must be >= 1")
        if self.over_select < 0:
            raise ValueError("AsyncRoundSpec.over_select must be >= 0")
        if not (0.0 < self.staleness_discount <= 1.0):
            raise ValueError(
                "AsyncRoundSpec.staleness_discount must be in (0, 1]"
            )
        if self.deadline_s <= 0:
            raise ValueError("AsyncRoundSpec.deadline_s must be > 0")

    @property
    def n_select(self) -> int:
        return self.quorum + self.over_select

    def staleness_weights(self, staleness: Any) -> jax.Array:
        """Per-station multiplicative discount ``discount ** staleness``
        for a ``[S]`` staleness vector (rounds since the station last
        contributed an accepted update)."""
        return jnp.power(
            jnp.asarray(self.staleness_discount, jnp.float32),
            jnp.asarray(staleness, jnp.float32),
        )


class FedAvg:
    """Compiles and runs federated-averaging rounds on a FederationMesh."""

    def __init__(self, mesh: FederationMesh, spec: FedAvgSpec):
        self.mesh = mesh
        self.spec = spec
        if spec.compressor is not None:
            spec.compressor.validate()
        # an identity compressor (no top-k, no int8) is a no-op: skip the
        # flat-pack round-trip entirely rather than paying it for nothing
        self._compressing = (
            spec.compressor is not None and not spec.compressor.identity
        )
        self.server_opt = spec.server_optimizer or optax.sgd(1.0)
        # optional learning-plane sink (attach_history): when set, every
        # round()/run_rounds() host-records its stats into it
        self.history: Any = None
        # NOTE: no buffer donation here — callers legitimately reuse params
        # across round() calls (e.g. ablations from one init); the scan in
        # run_rounds already reuses buffers internally. All three
        # executables dispatch through the device observatory
        # (runtime.profiling): every lowering/compile is a device.compile
        # span + v6t_jit_* telemetry, and a shape-wobbling caller shows up
        # as a named retrace instead of silent slow rounds.
        self._round = observed_jit("fedavg.round", self._round_impl)
        # n_rounds is a SWEEP static: callers legitimately compile the
        # fused program at several K values (warmup K=1, production K=32,
        # a tail-flush K=7). The observatory counts those as
        # static_sweeps, not retraces — a K sweep must not trip
        # recompile_storm (docs/device_speed.md "K-selection").
        self._run = observed_jit(
            "fedavg.run_rounds", self._run_impl,
            static_argnames=("n_rounds", "unroll"),
            sweep_statics=("n_rounds", "unroll"),
        )
        # run_rounds IS the multi-round fast path: donating params,
        # opt_state and the key lets XLA update the scan carry in place
        # instead of double-buffering model + moments for the whole run.
        # Kept as a SEPARATE executable so run_rounds(donate=False) (and
        # AOT callers compiling self._run directly) never consume caller
        # buffers.
        self._run_donating = observed_jit(
            "fedavg.run_rounds_donating", self._run_impl,
            static_argnames=("n_rounds", "unroll"),
            sweep_statics=("n_rounds", "unroll"),
            donate_argnums=(0, 1, 6),  # params, opt_state, key
        )
        # fused buffered-async runner: staleness rides the scan carry so K
        # async rounds (accept masks + FedBuff discounting) are one
        # dispatch, composing with compression EF exactly like _run_impl.
        self._run_async = observed_jit(
            "fedavg.run_rounds_async", self._run_async_impl,
            static_argnames=("n_rounds",),
            sweep_statics=("n_rounds",),
        )
        self._run_async_donating = observed_jit(
            "fedavg.run_rounds_async_donating", self._run_async_impl,
            static_argnames=("n_rounds",),
            sweep_statics=("n_rounds",),
            donate_argnums=(0, 1, 6, 8),  # params, opt_state, key, staleness
        )

    # ------------------------------------------------------------ local step
    def _local_update(
        self,
        x: jax.Array,          # [n_pad, ...] this station's (padded) examples
        y: jax.Array,          # [n_pad, ...]
        count: jax.Array,      # [] true example count
        station_id: jax.Array, # [] index for per-station RNG
        params: Pytree,        # replicated global model
        round_key: jax.Array,  # replicated per-round RNG key
    ) -> tuple[Pytree, jax.Array]:
        """`local_steps` of minibatch SGD from the global params; returns
        (delta, mean loss). Runs per-station inside fed_map."""
        spec = self.spec
        key = jax.random.fold_in(round_key, station_id)
        # Sampling bound: padded rows are never drawn because idx < count.
        safe_count = jnp.maximum(count.astype(jnp.int32), 1)

        def sgd_step(p: Pytree, step_key: jax.Array):
            idx = jax.random.randint(
                step_key, (spec.batch_size,), 0, safe_count
            )
            bx = jnp.take(x, idx, axis=0)
            by = jnp.take(y, idx, axis=0)
            w = jnp.ones((spec.batch_size,), jnp.float32)
            loss, grads = jax.value_and_grad(spec.loss_fn)(p, bx, by, w)
            p = jax.tree.map(lambda a, g: a - spec.local_lr * g, p, grads)
            return p, loss

        step_keys = jax.random.split(key, spec.local_steps)
        if spec.local_unroll is True:
            # Python-unrolled: identical math over the identical key
            # stream, but NO scan/while op in the lowered program —
            # XLA:CPU executes the conv inside a scan body (even a fully
            # `unroll=`-ed one, which keeps a trip-count-1 while) ~6x
            # slower than the same conv in straight-line code (measured,
            # docs/device_speed.md "K-selection").
            new_params, step_losses = params, []
            for i in range(spec.local_steps):
                new_params, loss = sgd_step(new_params, step_keys[i])
                step_losses.append(loss)
            losses = jnp.stack(step_losses)
        else:
            new_params, losses = jax.lax.scan(
                sgd_step, params, step_keys, unroll=spec.local_unroll
            )
        delta = jax.tree.map(lambda n, o: n - o, new_params, params)
        return delta, jnp.mean(losses)

    # ----------------------------------------------------------------- round
    def _round_impl(
        self,
        params: Pytree,
        opt_state: Any,
        stacked_x: jax.Array,   # [S, n_pad, ...]
        stacked_y: jax.Array,   # [S, n_pad, ...]
        counts: jax.Array,      # [S]
        mask: jax.Array,        # [S] participation (1.0 = in this round)
        round_key: jax.Array,
    ):
        station_ids = jnp.arange(self.mesh.n_stations)
        deltas, losses = self.mesh.fed_map(
            self._local_update,
            stacked_x,
            stacked_y,
            counts,
            station_ids,
            replicated_args=(params, round_key),
        )
        weights = counts * mask
        # Gradient compression at the delta-exchange boundary: the
        # aggregation below consumes the DECOMPRESSED per-station deltas —
        # exactly what a real server reconstructs from each station's
        # compressed uplink — and the per-station error-feedback
        # accumulators ride the optimizer-state carry to the next round.
        ef = None
        flat = None
        if self._compressing:
            server_state = opt_state["server"]
            deltas, ef, flat = self._compress_deltas(
                deltas, opt_state["ef"], round_key, mask
            )
        else:
            server_state = opt_state
        # learning-plane stats at the flat-pack seam, BEFORE the server
        # update: computed on the (reconstructed, post-decompression)
        # deltas the aggregation actually consumes, by one shared formula
        # independent of the update mode — replicated and scattered rounds
        # report fp32-identical stats (bench parity assertion). When
        # compressing, the flat matrix from the compression pass is reused.
        stats: dict[str, Any] = {}
        if self.spec.learning_stats:
            if flat is None:
                flat = flatten_stacked(deltas)
            stats = station_update_stats(flat, weights=weights, ef=ef)
        if self.spec.shard_server_update:
            params, server_state = self._sharded_server_update(
                params, server_state, deltas, weights
            )
        else:
            mean_delta = fed_mean(deltas, weights=weights)
            # Server update on the pseudo-gradient (negative mean delta).
            pseudo_grad = jax.tree.map(lambda d: -d, mean_delta)
            updates, server_state = self.server_opt.update(
                pseudo_grad, server_state, params
            )
            params = optax.apply_updates(params, updates)
        round_loss = fed_mean(losses, weights=weights)
        new_state = (
            {"server": server_state, "ef": ef}
            if self._compressing
            else server_state
        )
        return params, new_state, round_loss, stats

    def _compress_deltas(
        self, deltas: Pytree, ef: jax.Array, round_key: jax.Array,
        mask: jax.Array,
    ) -> tuple[Pytree, jax.Array, jax.Array]:
        """Per-station compress -> decompress of the delta uplink (the
        flat-pack seam): error feedback re-injected before compressing,
        ``comm_dtype`` applied as the pre-quantization cast (cast, then
        quantize). Returns the reconstructed deltas + new EF [S, N] + the
        reconstructed flat [S, N] matrix (reused by the learning-stats
        pass so the round never flat-packs twice).
        Pure/traced — runs inside the round program; wire accounting
        happens host-side in round()/run_rounds().

        A masked-out station never ships anything, so its accumulator
        must WAIT, not update: under SPMD it computes a (fictional) delta
        like everyone else, but both that delta and the would-be shipped
        mass are discarded — its EF row carries over unchanged (the
        docs/compression.md "mass is never lost" contract;
        tests/test_compression.py::test_masked_station_ef_waits)."""
        template = jax.tree.map(lambda x: x[0], deltas)
        flat = flatten_stacked(deltas)
        # a key stream disjoint from _local_update's fold_in(key, station):
        # station ids are < n_stations, 2**31 - 1 never is
        keys = jax.random.split(
            jax.random.fold_in(round_key, 2**31 - 1), self.mesh.n_stations
        )
        _, hat, new_ef = compress_stacked(
            self.spec.compressor, flat, ef, keys,
            cast_dtype=self.spec.comm_dtype,
        )
        participating = (mask != 0).reshape(-1, 1)
        new_ef = jnp.where(participating, new_ef, ef)
        return unflatten_stacked(template, hat), new_ef, hat

    def _sharded_server_update(
        self, params: Pytree, opt_state: Any, deltas: Pytree,
        weights: jax.Array,
    ) -> tuple[Pytree, Any]:
        """Reduce-scatter -> shard-local optax update -> all-gather.

        The mean delta is never materialized in full: each slot receives
        only its 1/D shard of the flat pseudo-gradient (psum_scatter),
        applies the server optimizer against its 1/D flat param shard —
        moments in ``opt_state`` are flat [N_pad] vectors sharded the same
        way (ZeRO-1) — and ONE all-gather re-replicates the updated params
        for the next round's broadcast.
        """
        mesh = self.mesh
        grad_shard = jax.tree.map(
            lambda d: -d,
            fed_mean_scattered(
                mesh, deltas, weights=weights,
                comm_dtype=self.spec.comm_dtype,
            ),
        )
        flat_params = flatten_tree(params)
        n_pad = padded_flat_size(flat_params.size, mesh.station_axis_size)
        flat_params = jnp.pad(flat_params, (0, n_pad - flat_params.size))
        # Hold only this slot's shard live: the update below is elementwise,
        # so GSPMD keeps everything downstream 1/D-sharded too.
        flat_params = jax.lax.with_sharding_constraint(
            flat_params, mesh.station_sharding()
        )
        updates, opt_state = self.server_opt.update(
            grad_shard, opt_state, flat_params
        )
        new_flat = all_gather_stations(
            mesh, optax.apply_updates(flat_params, updates)
        )
        return unflatten_like(params, new_flat), opt_state

    # ------------------------------------------------------------ public API
    def init(self, params: Pytree) -> Any:
        """Server-optimizer state for ``params``.

        With ``shard_server_update`` the state is built over the FLAT padded
        f32 param vector (moments are [N_pad] arrays, placed sharded over
        the station axis) — checkpoints of the two modes are therefore NOT
        interchangeable. With a ``compressor``, the returned state is a
        ``{"server": <optimizer state>, "ef": [S, N]}`` dict carrying each
        station's zero-initialized error-feedback accumulator (sharded over
        the station axis) — again not checkpoint-compatible with the
        uncompressed modes.
        """
        if self.spec.shard_server_update:
            flat = flatten_tree(params)
            n_pad = padded_flat_size(flat.size, self.mesh.station_axis_size)
            flat = jnp.pad(flat, (0, n_pad - flat.size))
            state = self.server_opt.init(flat)
            state = jax.tree.map(
                lambda x: jax.device_put(x, self.mesh.station_sharding())
                if getattr(x, "ndim", 0) == 1 and x.shape == (n_pad,)
                else x,
                state,
            )
        else:
            state = self.server_opt.init(params)
        if self._compressing:
            ef = jnp.zeros(
                (self.mesh.n_stations, flat_size(params)), jnp.float32
            )
            return {"server": state, "ef": self.mesh.shard_stacked(ef)}
        return state

    def round(
        self,
        params: Pytree,
        opt_state: Any,
        stacked_x: jax.Array,
        stacked_y: jax.Array,
        counts: jax.Array,
        key: jax.Array,
        mask: jax.Array | None = None,
    ):
        """One federated round. Returns (params, opt_state, mean_loss,
        stats) — ``stats`` is the learning-plane dict from
        ``collectives.station_update_stats`` ({} when
        ``spec.learning_stats`` is off); feed it to a
        ``runtime.learning.RoundHistory`` to arm convergence tracking and
        the anomalous-station watchdog rules."""
        if mask is None:
            mask = jnp.ones_like(counts)
        self._record_wire(params)
        out = self._round(
            params, opt_state, stacked_x, stacked_y, counts, mask, key
        )
        self._record_history(out[2], out[3], rounds_per_dispatch=1)
        return out

    def async_round(
        self,
        params: Pytree,
        opt_state: Any,
        stacked_x: jax.Array,
        stacked_y: jax.Array,
        counts: jax.Array,
        key: jax.Array,
        accept_mask: jax.Array,
        staleness: jax.Array,
        spec: AsyncRoundSpec,
        mask: jax.Array | None = None,
    ):
        """One buffered-async round: only ``accept_mask`` stations (the
        first-K arrivals, from ``Federation.run_buffered`` or a
        simulator) contribute, each discounted by
        ``spec.staleness_discount ** staleness``.

        Implemented entirely at the participation-mask seam — the
        effective mask is ``mask * accept_mask * discount`` and feeds the
        SAME jitted round program as :meth:`round` (``weights = counts *
        mask`` inside ``_round_impl``), so nothing retraces and
        compression EF / learning stats compose unchanged. A fractional
        mask weights the aggregation; EF-wait and stats participation key
        on ``mask != 0``, which is exactly "the station shipped an
        update this round"."""
        spec.validate()
        effective = (
            jnp.asarray(accept_mask, jnp.float32)
            * spec.staleness_weights(staleness)
        )
        if mask is not None:
            effective = effective * jnp.asarray(mask, jnp.float32)
        return self.round(
            params, opt_state, stacked_x, stacked_y, counts, key,
            mask=effective,
        )

    def _record_wire(self, params: Pytree, n_rounds: int = 1) -> None:
        """Host-side wire accounting for the compressed delta uplink
        (``v6t_compress_*`` series) — metadata-only, never touches device
        data and never runs inside the traced round."""
        if self._compressing:
            record_round_telemetry(
                self.spec.compressor, flat_size(params),
                self.mesh.n_stations, rounds=n_rounds,
            )

    def compression_stats(self, params: Pytree) -> dict[str, Any] | None:
        """Static per-round wire accounting of the delta uplink: raw vs
        compressed bytes across all stations + the reduction ratio (the
        bench's acceptance numbers). None without an effective compressor.
        Metadata-only — safe to call around a compiled run."""
        if not self._compressing:
            return None
        n = flat_size(params)
        spec = self.spec.compressor
        s = self.mesh.n_stations
        return {
            "n_params": n,
            "raw_bytes_per_round": 4 * n * s,
            "wire_bytes_per_round": spec.wire_nbytes(n) * s,
            "reduction": round(spec.ratio(n), 2),
        }

    def run_rounds(
        self,
        params: Pytree,
        stacked_x: jax.Array,
        stacked_y: jax.Array,
        counts: jax.Array,
        key: jax.Array,
        n_rounds: int,
        mask: jax.Array | None = None,
        opt_state: Any = None,
        donate: bool = True,
        unroll: int | bool = 1,
    ):
        """`n_rounds` federated rounds as ONE compiled program (lax.scan) —
        the FUSED fast path (docs/device_speed.md): per-station training,
        aggregation, compression EF and learning stats all stay on device
        with zero host round-trips between rounds. ``mask`` may be ``[S]``
        (one roster for the whole dispatch) or ``[n_rounds, S]`` (a
        per-round roster riding the scan xs). Returns (params, opt_state,
        losses[n], stats) — ``stats`` holds the per-round learning-plane
        arrays stacked over the scan axis (``station_norm``/
        ``station_cos`` ``[n, S]``, ``update_norm`` ``[n]``; {} when
        ``spec.learning_stats`` is off).

        Pass the ``opt_state`` from a checkpoint to CONTINUE a run (resuming
        FedAdam etc. without resetting server-optimizer moments); omitted, a
        fresh optimizer state is initialized.

        DONATION: by default ``params``, ``opt_state`` and ``key`` buffers
        are donated — XLA updates the scan carry in place instead of
        double-buffering model + moments, but the caller's input arrays are
        CONSUMED and must not be touched again (use the returned values).
        Pass ``donate=False`` to keep the inputs alive (e.g. ablations
        re-running several configs from one init). ``round()`` never
        donates (tests/test_scattered_update.py pins both contracts).

        ``unroll`` is the round-loop unroll factor (True = fully unrolled,
        no while loop) — a pure compilation-strategy knob with identical
        semantics at any value. Combine with ``FedAvgSpec.local_unroll``
        on CPU, where XLA runs convolutions inside while-loop bodies ~6x
        slower than straight-line (docs/device_speed.md "K-selection");
        leave both at 1 on TPU, where the scan form compiles much faster
        at the same execution speed.
        """
        if mask is None:
            mask = jnp.ones_like(counts)
        if opt_state is None:
            opt_state = self.init(params)
        self._record_wire(params, n_rounds=n_rounds)
        self._record_fused(n_rounds)
        run = self._run_donating if donate else self._run
        out = run(
            params, opt_state, stacked_x, stacked_y, counts, mask, key,
            n_rounds=n_rounds, unroll=unroll,
        )
        self._record_history(out[2], out[3], rounds_per_dispatch=n_rounds)
        return out

    def run_rounds_async(
        self,
        params: Pytree,
        stacked_x: jax.Array,
        stacked_y: jax.Array,
        counts: jax.Array,
        key: jax.Array,
        n_rounds: int,
        accept_masks: jax.Array,
        spec: AsyncRoundSpec,
        staleness: jax.Array | None = None,
        mask: jax.Array | None = None,
        opt_state: Any = None,
        donate: bool = True,
    ):
        """``n_rounds`` buffered-async rounds as ONE fused program: the
        FedBuff staleness vector rides the scan carry, so K rounds of
        :meth:`async_round` semantics (accept-mask weighting discounted
        by ``spec.staleness_discount ** staleness``) run with zero host
        round-trips. ``accept_masks`` is ``[n_rounds, S]`` (each fused
        round's first-K arrivals, e.g. from a quorum simulator) or ``[S]``
        (same acceptance every round). Returns (params, opt_state,
        staleness[S], losses[n], stats) — the final staleness vector
        continues into the next fused dispatch, exactly like the host
        bookkeeping it replaces."""
        spec.validate()
        if mask is None:
            mask = jnp.ones_like(counts)
        if staleness is None:
            staleness = jnp.zeros_like(counts, dtype=jnp.float32)
        if opt_state is None:
            opt_state = self.init(params)
        self._record_wire(params, n_rounds=n_rounds)
        self._record_fused(n_rounds)
        run = self._run_async_donating if donate else self._run_async
        out = run(
            params, opt_state, stacked_x, stacked_y, counts, mask, key,
            accept_masks, jnp.asarray(staleness, jnp.float32),
            jnp.float32(spec.staleness_discount), n_rounds=n_rounds,
        )
        self._record_history(out[3], out[4], rounds_per_dispatch=n_rounds)
        return out

    def _record_fused(self, n_rounds: int) -> None:
        """Fused-program telemetry (host-side, metadata only): how many
        logical rounds each dispatch amortizes — the `v6t_fused_*` series
        docs/device_speed.md reads beside rounds_per_sec."""
        REGISTRY.counter("v6t_fused_dispatches_total").inc()
        REGISTRY.counter("v6t_fused_rounds_total").inc(n_rounds)
        REGISTRY.gauge("v6t_fused_rounds_per_dispatch").set(n_rounds)

    # --------------------------------------------------------- learning plane
    def attach_history(self, history: Any) -> Any:
        """Attach a ``runtime.learning.RoundHistory`` (or a registry key —
        resolved through the process ``LEARNING`` registry): every
        round()/run_rounds() call then host-records its stats into it
        (telemetry gauges, flight notes, a ``learning.round`` span on the
        ambient trace — the learning-plane observatory). Recording pulls
        the tiny [S] stat vectors to host, which BLOCKS on the round's
        completion — attach when observing, not when racing dispatches.
        Returns the history. Pass None to detach."""
        if history is not None and not hasattr(history, "record_engine"):
            from vantage6_tpu.runtime.learning import LEARNING

            history = LEARNING.history(history)
        self.history = history
        return history

    def _record_history(
        self, losses: Any, stats: Any, rounds_per_dispatch: int = 1
    ) -> None:
        history = getattr(self, "history", None)
        if history is None or not stats:
            return
        try:
            history.record_engine(
                losses, stats, rounds_per_dispatch=rounds_per_dispatch
            )
        except Exception:  # observability must never fail the round
            import logging

            logging.getLogger("vantage6_tpu/fedavg").debug(
                "round-history recording failed", exc_info=True
            )

    def _run_impl(
        self, params, opt_state, stacked_x, stacked_y, counts, mask, key,
        *, n_rounds: int, unroll: int | bool = 1
    ):
        # the participation mask rides the scan xs (one [S] row per
        # round), not the closure: a [S] mask broadcasts to every round,
        # a [K, S] matrix gives each fused round its own roster — same
        # executable either way (rank is static), zero host round-trips
        masks = per_round_masks(mask, n_rounds)

        def body(carry, xs):
            round_key, m = xs
            p, s = carry
            p, s, loss, stats = self._round_impl(
                p, s, stacked_x, stacked_y, counts, m, round_key
            )
            return (p, s), (loss, stats)

        keys = jax.random.split(key, n_rounds)
        if unroll is True:
            # Python-unrolled round loop — same contract as the
            # local_unroll fast path above: no while op survives in the
            # lowered program, which is what lets XLA:CPU keep its fast
            # conv path. Bit-identical to the scan form (same bodies over
            # the same xs, in order).
            carry, ys = (params, opt_state), []
            for i in range(n_rounds):
                carry, y = body(carry, (keys[i], masks[i]))
                ys.append(y)
            params, opt_state = carry
            losses = jnp.stack([loss for loss, _ in ys])
            stats = jax.tree.map(lambda *a: jnp.stack(a), *[s for _, s in ys])
        else:
            (params, opt_state), (losses, stats) = jax.lax.scan(
                body, (params, opt_state), (keys, masks), unroll=unroll
            )
        return params, opt_state, losses, stats

    def _run_async_impl(
        self, params, opt_state, stacked_x, stacked_y, counts, mask, key,
        accept_masks, staleness, discount, *, n_rounds: int
    ):
        """K buffered-async rounds as ONE program: FedBuff staleness
        (rounds since each station's last accepted update) rides the scan
        CARRY, so the per-round effective mask ``accept * discount**stale
        * mask`` — exactly :meth:`async_round`'s seam — is computed
        on-device between fused rounds with no host in the loop."""
        masks = per_round_masks(mask, n_rounds)
        accepts = per_round_masks(accept_masks, n_rounds)
        disc = jnp.asarray(discount, jnp.float32)

        def body(carry, xs):
            p, s, stale = carry
            round_key, m, accept = xs
            eff = accept * jnp.power(disc, stale) * m
            p, s, loss, stats = self._round_impl(
                p, s, stacked_x, stacked_y, counts, eff, round_key
            )
            # accepted stations reset; everyone else ages one round —
            # the same bookkeeping Federation.run_buffered does host-side
            stale = jnp.where(accept != 0, 0.0, stale + 1.0)
            return (p, s, stale), (loss, stats)

        keys = jax.random.split(key, n_rounds)
        init = (params, opt_state, jnp.asarray(staleness, jnp.float32))
        (params, opt_state, staleness), (losses, stats) = jax.lax.scan(
            body, init, (keys, masks, accepts)
        )
        return params, opt_state, staleness, losses, stats
