"""Gradient compression at the delta-exchange boundary (docs/compression.md).

PR 1 narrowed the scattered exchange to bf16 (2x) and PR 3 removed the
framing overhead (25%), but every FedAvg round still ships a DENSE
full-model delta per station. The communication-perspective survey
(PAPERS.md, arXiv 2405.20431) is explicit that the next order of magnitude
comes from quantization + sparsification. This module is that layer — one
composable :class:`CompressorSpec` applied to flat per-station deltas at
the seam the flat-pack helpers in ``fed.collectives`` already define:

- **stochastic int8 quantization**: per-chunk scale (``chunk`` elements
  share one f32 scale, so outliers only poison their own chunk) with
  UNBIASED stochastic rounding — ``E[dequantize(quantize(x))] == x``
  exactly, so quantization noise averages out across stations and rounds
  instead of accumulating as bias (pinned by
  tests/test_compression.py::test_int8_roundtrip_is_unbiased).
- **top-k sparsification**: keep the k = ``topk_ratio * n`` largest-
  magnitude entries; the survivors' positions ride as an index buffer
  (the v2 wire's first-class sparse type, `serialization.SparseVector`).
- **error feedback** (Stich et al. / Karimireddy et al.): each station
  keeps an accumulator of everything compression threw away and re-injects
  it into the NEXT round's delta before compressing — the invariant that
  makes aggressive top-k converge. The accumulator update is exact by
  construction: ``new_ef = acc - decompress(compress(acc))``.

Composition order (one wire hop, applied left to right)::

    delta --+ef--> [cast comm_dtype] --> top-k --> int8 --> wire
                 \\________________ error feedback ________________/

i.e. the ``comm_dtype`` cast happens FIRST (matching the scattered
exchange's existing bf16 narrowing — cast, then quantize) and the error
feedback captures the TOTAL wire error including the cast.

Everything under ``compress_flat``/``decompress_flat`` is pure jax and
jit/vmap/scan-safe (no host syncs, no impure calls — the v6lint tracer
pass checks the traced closure). The host-level entries
(:func:`compress_delta` / :func:`decompress_delta`) wrap the jitted ops in
``device.compress`` / ``device.decompress`` trace spans and feed the
``v6t_compress_*`` telemetry series.
"""
from __future__ import annotations

import dataclasses
import math
import os
import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from vantage6_tpu.common.serialization import SparseVector
from vantage6_tpu.common.telemetry import REGISTRY
from vantage6_tpu.runtime.profiling import observed_jit as _observed_jit

Pytree = Any

# wire payload marker: decompress_delta recognizes payloads by this key so
# a pass-through (no compressor) tree is returned unchanged
WIRE_TAG = "v6t.compressed"
_WIRE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class CompressorSpec:
    """One composable compressor configuration (hashable — jit-static).

    ``topk_ratio``: fraction of delta entries kept (None = dense).
    ``int8``: stochastic int8 quantization of the (kept) values.
    ``chunk``: elements sharing one quantization scale.
    ``error_feedback``: per-station accumulators re-injecting compression
    error into the next round's delta (keep on unless ablating).
    """

    topk_ratio: float | None = None
    int8: bool = False
    chunk: int = 256
    error_feedback: bool = True

    def validate(self) -> None:
        if self.topk_ratio is not None and not 0.0 < self.topk_ratio <= 1.0:
            raise ValueError(
                f"topk_ratio must be in (0, 1], got {self.topk_ratio}"
            )
        if self.chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {self.chunk}")

    @property
    def identity(self) -> bool:
        """True when this spec compresses nothing (dense f32 pass-through)."""
        return self.topk_ratio is None and not self.int8

    def k_for(self, n: int) -> int:
        """Static survivor count for an n-element delta."""
        if self.topk_ratio is None:
            return n
        return max(1, min(n, int(round(self.topk_ratio * n))))

    # ------------------------------------------------------ wire accounting
    def wire_nbytes(self, n: int) -> int:
        """On-wire bytes of ONE station's compressed n-element delta —
        metadata-only (never touches data), the number `serialization.
        wire_nbytes` and the bench's reduction ratio are built from."""
        if self.identity:
            return 4 * n
        k = self.k_for(n)
        total = 0
        if self.topk_ratio is not None:
            total += 4 * k  # int32 index buffer
        if self.int8:
            total += k  # int8 values (codes)
            # scales are DENSE-layout (see compress_flat): one f32 per
            # dense chunk regardless of sparsification
            total += 4 * math.ceil(n / self.chunk)
        else:
            total += 4 * k  # f32 values
        return total

    def ratio(self, n: int) -> float:
        """Dense-f32 bytes / compressed bytes for an n-element delta."""
        return 4.0 * n / max(1, self.wire_nbytes(n))


# ---------------------------------------------------------------- jitted ops
# All functions below are traced (jit/vmap): pure jax, no host syncs.


def _chunk_pad(n: int, chunk: int) -> tuple[int, int]:
    """(n_chunks, pad) for an n-element vector at this chunk size."""
    c = -(-n // chunk)
    return c, c * chunk - n


def quantize_int8(
    x: jax.Array, key: jax.Array, chunk: int
) -> tuple[jax.Array, jax.Array]:
    """Stochastic int8 quantization with per-chunk scale.

    Returns ``(q int8 [n], scales f32 [ceil(n/chunk)])`` with
    ``scale_c = max(|x_c|) / 127`` per chunk and UNBIASED rounding:
    ``q = floor(x/scale + u)``, u ~ U[0,1) — E[q * scale] == x exactly
    (an all-zero chunk quantizes to zeros at scale 0).
    """
    n = x.shape[0]
    c, pad = _chunk_pad(n, chunk)
    xp = jnp.pad(x, (0, pad)).reshape(c, chunk)
    scales = jnp.max(jnp.abs(xp), axis=1) / 127.0
    scaled = jnp.where(scales[:, None] > 0, xp / scales[:, None], 0.0)
    u = jax.random.uniform(key, xp.shape)
    q = jnp.clip(jnp.floor(scaled + u), -127, 127).astype(jnp.int8)
    return q.reshape(-1)[:n], scales


def dequantize_int8(
    q: jax.Array, scales: jax.Array, chunk: int
) -> jax.Array:
    """Inverse of :func:`quantize_int8` (exact given the same scales)."""
    n = q.shape[0]
    c, pad = _chunk_pad(n, chunk)
    qp = jnp.pad(q, (0, pad)).reshape(c, chunk).astype(jnp.float32)
    return (qp * scales[:, None]).reshape(-1)[:n]


def topk_sparsify(x: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Indices (int32, ascending) and values of the k largest-|x| entries."""
    _, idx = jax.lax.top_k(jnp.abs(x), k)
    idx = jnp.sort(idx).astype(jnp.int32)
    return idx, jnp.take(x, idx)


def compress_flat(
    spec: CompressorSpec, flat: jax.Array, key: jax.Array
) -> dict[str, jax.Array]:
    """flat [n] f32 -> payload dict of arrays (static structure per spec):
    ``indices`` (top-k), then ``q``+``scales`` (int8) or ``values``.

    LAYOUT CONTRACT: with ``int8``, quantization chunks (and therefore the
    ``scales`` vector) are laid out over the DENSE n-element vector, and
    top-k then selects dense-position codes (``scales[idx // chunk]``
    dequantizes a survivor). This is what makes the legacy-v1 dense
    fallback exact: scattering the int8 codes back to their dense
    positions (code 0 dequantizes to 0.0) and dequantizing with the SAME
    dense-layout scales reproduces the decompressed delta bit-for-bit —
    a compacted-layout scale vector could not survive densification.
    """
    payload: dict[str, jax.Array] = {}
    x = flat.astype(jnp.float32)
    if spec.int8:
        q, scales = quantize_int8(x, key, spec.chunk)
        payload["scales"] = scales
        if spec.topk_ratio is not None:
            idx, _ = topk_sparsify(x, spec.k_for(flat.shape[0]))
            payload["indices"] = idx
            payload["q"] = jnp.take(q, idx)
        else:
            payload["q"] = q
    elif spec.topk_ratio is not None:
        idx, vals = topk_sparsify(x, spec.k_for(flat.shape[0]))
        payload["indices"] = idx
        payload["values"] = vals
    else:
        payload["values"] = x
    return payload


def decompress_flat(
    spec: CompressorSpec, payload: dict[str, jax.Array], n: int
) -> jax.Array:
    """Payload -> dense f32 [n]. Bit-identical to the ``hat`` the
    compressor fed its error-feedback update (same dequantize path)."""
    if spec.topk_ratio is not None:
        idx = payload["indices"]
        if spec.int8:
            # dense-layout scales: a survivor at dense position i
            # dequantizes with its dense chunk's scale
            scale = jnp.take(payload["scales"], idx // spec.chunk)
            vals = payload["q"].astype(jnp.float32) * scale
        else:
            vals = payload["values"].astype(jnp.float32)
        return jnp.zeros((n,), jnp.float32).at[idx].set(vals)
    if spec.int8:
        return dequantize_int8(payload["q"], payload["scales"], spec.chunk)
    return payload["values"].astype(jnp.float32)


def compress_with_feedback(
    spec: CompressorSpec,
    flat: jax.Array,
    ef: jax.Array | None,
    key: jax.Array,
    cast_dtype: Any = None,
) -> tuple[dict[str, jax.Array], jax.Array, jax.Array]:
    """One station's full compress step: EF re-injection -> optional
    ``cast_dtype`` narrowing (cast, then quantize — the comm_dtype
    composition order) -> compress -> exact error-feedback update.

    Returns ``(payload, hat, new_ef)`` where ``hat`` is the dense
    decompressed delta (what the server will reconstruct) and
    ``new_ef = acc - hat`` EXACTLY — the dropped/rounded mass, re-injected
    next round. With ``error_feedback=False`` new_ef stays zero.
    """
    x = flat.astype(jnp.float32)
    acc = x + ef if (spec.error_feedback and ef is not None) else x
    wire_val = (
        acc.astype(cast_dtype).astype(jnp.float32)
        if cast_dtype is not None
        else acc
    )
    payload = compress_flat(spec, wire_val, key)
    hat = decompress_flat(spec, payload, flat.shape[0])
    new_ef = (
        acc - hat if spec.error_feedback else jnp.zeros_like(acc)
    )
    return payload, hat, new_ef


def compress_stacked(
    spec: CompressorSpec,
    flat: jax.Array,      # [S, n] per-station flat deltas
    ef: jax.Array,        # [S, n] per-station error-feedback accumulators
    keys: jax.Array,      # [S] per-station RNG keys
    cast_dtype: Any = None,
) -> tuple[dict[str, jax.Array], jax.Array, jax.Array]:
    """Per-station compress over the leading station axis (vmap) — each
    station draws its own stochastic-rounding noise and keeps its own
    accumulator. Returns stacked (payload, hat [S, n], new_ef [S, n])."""

    def one(x: jax.Array, e: jax.Array, k: jax.Array):
        return compress_with_feedback(spec, x, e, k, cast_dtype=cast_dtype)

    return jax.vmap(one)(flat, ef, keys)


def ef_norm(ef: jax.Array) -> jax.Array:
    """L2 norm of an error-feedback accumulator (per round, on device —
    callers pull it explicitly; nothing in the round program syncs)."""
    return jnp.sqrt(jnp.sum(jnp.square(ef.astype(jnp.float32))))


# ----------------------------------------------------------- host-level API
# jit caches keyed by (spec, shape) — spec is a frozen (hashable)
# dataclass, so marking it static is enough. Dispatch rides the device
# observatory: a compress kernel recompiling per round (a wobbling delta
# length) is a named retrace, not a mystery slowdown.
_compress_jit = _observed_jit(
    "compress.delta", compress_with_feedback,
    static_argnums=(0,), static_argnames=("cast_dtype",),
)
_decompress_jit = _observed_jit(
    "compress.reconstruct", decompress_flat, static_argnums=(0, 2),
)


def _record_compress_telemetry(spec: CompressorSpec, n: int, count: int = 1):
    raw = 4 * n * count
    wire = spec.wire_nbytes(n) * count
    REGISTRY.counter("v6t_compress_calls_total").inc(count)
    REGISTRY.counter("v6t_compress_raw_bytes_total").inc(raw)
    REGISTRY.counter("v6t_compress_wire_bytes_total").inc(wire)
    REGISTRY.gauge("v6t_compress_ratio").set(raw / max(1, wire))


def record_round_telemetry(
    spec: CompressorSpec, n: int, n_stations: int, rounds: int = 1
) -> None:
    """Account an engine round's delta exchange (every station uplinks one
    compressed n-element delta per round) in the ``v6t_compress_*`` series.
    Host-side and metadata-only — called by the FedAvg engine per round()/
    run_rounds(), never from traced code."""
    _record_compress_telemetry(spec, n, count=n_stations * rounds)


def fused_wire_plan(
    spec: CompressorSpec | None, n: int, n_stations: int, n_rounds: int
) -> dict[str, Any]:
    """Static wire accounting for one FUSED K-round dispatch
    (docs/device_speed.md): total raw vs on-wire delta-uplink bytes over
    all ``n_rounds`` fused rounds, plus the per-dispatch host-transfer
    saving the fusion buys — ``host_pulls`` collapses from ``n_rounds``
    (one losses/stats pull per sequential round) to 1. Metadata-only;
    ``spec=None`` (or an identity compressor) accounts the dense case.
    The bench's fused leg and K-selection guidance read exactly this."""
    wire_each = (
        4 * n if spec is None or spec.identity else spec.wire_nbytes(n)
    )
    raw = 4 * n * n_stations * n_rounds
    wire = wire_each * n_stations * n_rounds
    return {
        "n_params": n,
        "n_rounds": n_rounds,
        "raw_bytes": raw,
        "wire_bytes": wire,
        "reduction": round(4.0 * n / max(1, wire_each), 2),
        "host_pulls": 1,
        "host_pulls_sequential": n_rounds,
    }


def compress_delta(
    spec: CompressorSpec,
    flat: Any,
    ef: Any = None,
    key: jax.Array | None = None,
    cast_dtype: Any = None,
    station: int | None = None,
) -> tuple[dict[str, Any], jax.Array, jax.Array]:
    """Host-level compress of one flat delta: the jitted ops recorded as a
    ``device.compress`` trace span (no-op outside a trace) + telemetry.

    Returns ``(payload, hat, new_ef)`` like :func:`compress_with_feedback`;
    ``key=None`` derives a fixed key (deterministic — fine for tests, wrong
    for production unbiasedness; pass a fresh key per round).
    """
    from vantage6_tpu.runtime.tracing import TRACER

    flat = jnp.asarray(flat, jnp.float32)
    n = flat.shape[0]
    if key is None:
        key = jax.random.key(0)
    if ef is None:
        ef = jnp.zeros_like(flat)
    attrs = {
        "n": int(n),
        "raw_bytes": 4 * int(n),
        "wire_bytes": spec.wire_nbytes(int(n)),
    }
    if station is not None:
        attrs["station"] = int(station)
    with TRACER.span(
        "device.compress", kind="device", attrs=attrs, require_parent=True,
    ):
        payload, hat, new_ef = _compress_jit(
            spec, flat, ef, key, cast_dtype=cast_dtype
        )
        jax.block_until_ready(hat)  # span must cover the device work
    _record_compress_telemetry(spec, int(n))
    REGISTRY.gauge("v6t_compress_ef_norm").set(float(ef_norm(new_ef)))
    return payload, hat, new_ef


def decompress_delta(spec: CompressorSpec, payload: dict[str, Any], n: int):
    """Host-level decompress (server side), recorded as a
    ``device.decompress`` span + counted in telemetry."""
    from vantage6_tpu.runtime.tracing import TRACER

    with TRACER.span(
        "device.decompress", kind="device",
        attrs={"n": int(n), "wire_bytes": spec.wire_nbytes(int(n))},
        require_parent=True,
    ):
        dense = _decompress_jit(
            spec, {k: jnp.asarray(v) for k, v in payload.items()}, n
        )
        jax.block_until_ready(dense)
    REGISTRY.counter("v6t_decompress_calls_total").inc()
    return dense


# -------------------------------------------------------------- wire format
def payload_to_wire(
    spec: CompressorSpec, payload: dict[str, Any], n: int
) -> dict[str, Any]:
    """Device payload -> wire-serializable dict: the top-k half becomes a
    first-class `SparseVector` (indices + int8/f32 values over the dense
    length), scales/metadata ride beside it. Legacy v1 peers densify the
    SparseVector automatically (serialization's dense fallback)."""
    out: dict[str, Any] = {
        WIRE_TAG: _WIRE_VERSION,
        "n": int(n),
        "spec": {
            "topk_ratio": spec.topk_ratio,
            "int8": spec.int8,
            "chunk": spec.chunk,
        },
    }
    if spec.topk_ratio is not None:
        vals = payload["q"] if spec.int8 else payload["values"]
        out["sparse"] = SparseVector(
            np.asarray(payload["indices"]), np.asarray(vals), int(n)
        )
    elif spec.int8:
        out["q"] = np.asarray(payload["q"])
    else:
        out["values"] = np.asarray(payload["values"])
    if spec.int8:
        out["scales"] = np.asarray(payload["scales"])
    return out


def spec_from_wire(wire: dict[str, Any]) -> CompressorSpec:
    """Reconstruct the (quantization-relevant) spec a wire payload was
    compressed under — the server must dequantize with the SENDER's
    parameters, not its own config."""
    s = wire.get("spec", {})
    spec = CompressorSpec(
        topk_ratio=s.get("topk_ratio"),
        int8=bool(s.get("int8", False)),
        chunk=int(s.get("chunk", 256)),
    )
    spec.validate()
    return spec


def is_wire_payload(obj: Any) -> bool:
    return isinstance(obj, dict) and WIRE_TAG in obj


# Decompression allocates a dense [n] f32 vector from a payload that can
# be much smaller than n (that is the point of sparse) — an UNTRUSTED
# peer must not turn a 100-byte frame into a terabyte allocation. The cap
# is generous (2**28 elements = 1 GiB f32, ~256M params) and overridable
# for genuinely larger models.
_MAX_ELEMENTS_ENV = "V6T_COMPRESS_MAX_ELEMENTS"
_DEFAULT_MAX_ELEMENTS = 2**28


def _max_elements() -> int:
    raw = os.environ.get(_MAX_ELEMENTS_ENV, "")
    try:
        return int(raw) if raw.strip() else _DEFAULT_MAX_ELEMENTS
    except ValueError:
        return _DEFAULT_MAX_ELEMENTS


def wire_to_payload(
    wire: dict[str, Any],
) -> tuple[CompressorSpec, dict[str, Any], int]:
    """Wire dict -> (spec, device payload, n) for :func:`decompress_delta`.

    Tolerates the v1 dense fallback: a legacy peer that re-encoded the
    frame dense (SparseVector -> ndarray) still decompresses — the dense
    array is scattered back through its nonzero structure losslessly only
    when indices survive, so the fallback path reconstructs from dense
    directly instead.

    VALIDATES the peer-supplied metadata before anything allocates
    (same stance as the sparse decode's bounds check): ``n`` is capped
    (``V6T_COMPRESS_MAX_ELEMENTS``), a sparse half must span exactly
    ``n`` (a disagreeing size would let out-of-range indices be silently
    dropped by the scatter instead of rejected), dense halves must carry
    exactly ``n`` values, int8 payloads exactly ``ceil(n/chunk)`` scales,
    and missing fields raise ValueError, never KeyError.
    """
    if not is_wire_payload(wire):
        raise ValueError("not a v6t compressed delta payload")
    spec = spec_from_wire(wire)
    try:
        n = int(wire["n"])
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed compressed payload: bad n ({e!r})") from e
    if n < 0 or n > _max_elements():
        raise ValueError(
            f"malformed compressed payload: n={n} outside [0, "
            f"{_max_elements()}] (raise {_MAX_ELEMENTS_ENV} for larger "
            "models)"
        )

    def field(key: str) -> Any:
        if key not in wire:
            raise ValueError(
                f"malformed compressed payload: missing {key!r}"
            )
        return wire[key]

    payload: dict[str, Any] = {}
    if spec.topk_ratio is not None:
        sp = field("sparse")
        if isinstance(sp, SparseVector):
            if sp.size != n:
                raise ValueError(
                    "malformed compressed payload: sparse size "
                    f"{sp.size} != n {n}"
                )
            payload["indices"] = sp.indices
            payload["q" if spec.int8 else "values"] = sp.values
        else:
            # densified by a legacy v1 hop (SparseVector -> plain ndarray):
            # values are already scattered to their dense positions, and
            # the scales are dense-layout by the compress_flat contract, so
            # the payload decompresses as a non-sparse one bit-for-bit
            # (dropped positions carry code/value 0 -> 0.0)
            spec = dataclasses.replace(spec, topk_ratio=None)
            if spec.int8:
                payload["q"] = np.asarray(sp, np.int8)
            else:
                payload["values"] = np.asarray(sp, np.float32)
    elif spec.int8:
        payload["q"] = np.asarray(field("q"))
    else:
        payload["values"] = np.asarray(field("values"))
    for key, want in (("q", n), ("values", n)):
        if key in payload and spec.topk_ratio is None and len(
            payload[key]
        ) != want:
            raise ValueError(
                f"malformed compressed payload: {key} carries "
                f"{len(payload[key])} values, expected {want}"
            )
    if spec.int8:
        payload["scales"] = np.asarray(field("scales"))
        want = -(-n // spec.chunk)
        if len(payload["scales"]) != want:
            raise ValueError(
                "malformed compressed payload: "
                f"{len(payload['scales'])} scales, expected {want}"
            )
    return spec, payload, n


def decompress_wire_tree(payload: Any) -> Any:
    """Wire payload -> dense update pytree; anything that is NOT a
    compressed delta passes through unchanged (mixed compressed/plain
    result lists fold uniformly). The decompression spec rides the wire,
    so the receiver needs no configuration — shared by
    ``Federation.decompress_update`` and the REST client."""
    if not is_wire_payload(payload):
        return payload
    spec, dev_payload, n = wire_to_payload(payload)
    flat = np.asarray(decompress_delta(spec, dev_payload, n))
    skeleton = payload.get("skeleton")
    if skeleton is None:
        return flat
    return rebuild_from_skeleton(skeleton, flat)


class DeltaCompressor:
    """Stateful per-process compression endpoint: one spec + named
    error-feedback accumulators.

    For callers not backed by a Federation (the REST algorithm client
    inside a container). NOTE: the accumulators live in THIS process —
    under ``mode="sandbox"`` each run is a fresh subprocess, so error
    feedback only persists for inline/persistent algorithm processes;
    prefer the Federation/engine paths when EF across rounds matters.
    """

    def __init__(self, spec: CompressorSpec):
        spec.validate()
        self.spec = spec
        self._ef: dict[str, np.ndarray] = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._name_locks: dict[str, threading.Lock] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # per-INSTANCE entropy for the stochastic-rounding stream: N
        # station processes (one DeltaCompressor each) must not draw the
        # same U[0,1) noise per coordinate — a fixed seed would correlate
        # their rounding errors perfectly and the cross-station average
        # would stop shrinking as 1/N, defeating the unbiasedness
        # rationale. This trades run-for-run reproducibility for
        # distributed correctness; the FedAvg engine path stays fully
        # deterministic in the caller's round key.
        self._seed = int.from_bytes(os.urandom(4), "little")

    def compress(
        self, tree: Pytree, name: str = "update",
        station: int | None = None,
    ) -> Any:
        if self.spec.identity:
            return tree
        skeleton = tree_skeleton(tree)
        flat = flatten_host(tree)
        n = int(flat.size)
        # The EF update is a read-COMPUTE-write cycle: two concurrent
        # same-name compresses must serialize across the whole cycle or
        # both re-inject the same error mass (shipped twice) and one
        # residual is silently lost. A PER-NAME mutex serializes exactly
        # the exchanges that share an accumulator; different names (and
        # different stations on the Federation path) still compress
        # concurrently. _lock stays bookkeeping-only.
        with self._lock:
            name_lock = self._name_locks.setdefault(name, threading.Lock())
        with name_lock:
            with self._lock:
                ef = self._ef.get(name)
                seq = self._seq
                self._seq += 1
            if ef is None or ef.shape != (n,):
                ef = None  # first exchange (or a reshaped model): fresh EF
            key = jax.random.fold_in(jax.random.key(self._seed), seq)
            payload, _, new_ef = compress_delta(
                self.spec, flat, ef, key=key, station=station
            )
            new_ef = np.asarray(new_ef)
            with self._lock:
                self._ef[name] = new_ef
        wire = payload_to_wire(self.spec, payload, n)
        wire["skeleton"] = skeleton
        return wire

    def decompress(self, payload: Any) -> Any:
        return decompress_wire_tree(payload)


def spec_from_env(environ: Any = None) -> CompressorSpec | None:
    """Build a CompressorSpec from ``V6T_COMPRESS`` (None when unset/off).

    Format: comma-separated knobs — ``topk=0.1``, ``int8``, ``chunk=256``,
    ``no-ef`` — e.g. ``V6T_COMPRESS=topk=0.1,int8``. How a node operator
    arms compression for containerized algorithm code (the REST client
    reads it at construction); ``off``/empty disables. A malformed value
    raises at startup, not per task.
    """
    import os

    raw = (environ or os.environ).get("V6T_COMPRESS", "").strip()
    if not raw or raw.lower() == "off":
        return None
    kw: dict[str, Any] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if part == "int8":
            kw["int8"] = True
        elif part == "no-ef":
            kw["error_feedback"] = False
        elif part.startswith("topk="):
            kw["topk_ratio"] = float(part[5:])
        elif part.startswith("chunk="):
            kw["chunk"] = int(part[6:])
        else:
            raise ValueError(
                f"V6T_COMPRESS: unknown knob {part!r} "
                "(expected topk=F, int8, chunk=N, no-ef)"
            )
    spec = CompressorSpec(**kw)
    spec.validate()
    return spec


# -------------------------------------------------- pytree <-> flat helpers
# The host plane flat-packs by walking the tree in SKELETON order (dict
# insertion order) — NOT jax.tree.leaves order (which sorts dict keys) —
# so the skeleton the wire carries and the flat vector always agree.


def flatten_host(tree: Pytree) -> np.ndarray:
    """Concatenate every array leaf (skeleton walk order) into one flat
    f32 vector — the host-plane twin of ``collectives.flatten_tree``."""
    parts: list[np.ndarray] = []

    def walk(obj: Any) -> None:
        if isinstance(obj, dict):
            for v in obj.values():
                walk(v)
        elif isinstance(obj, (list, tuple)):
            for v in obj:
                walk(v)
        else:
            parts.append(np.asarray(obj, np.float32).ravel())

    walk(tree)
    if not parts:
        raise ValueError("empty pytree")
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


def tree_skeleton(tree: Pytree) -> Any:
    """JSON-able structure of ``tree`` with each array leaf replaced by a
    ``{"__leaf__", "shape", "dtype"}`` placeholder, in SKELETON walk order
    (dict insertion order — NOT ``jax.tree.leaves`` order, which sorts
    dict keys; pair only with ``flatten_host``, never ``flatten_tree``) —
    how the host-plane wire payload carries the pytree structure without
    a treedef.

    Container fidelity: tuples ride a ``{"__v6t_tuple__": [...]}`` marker
    so the round-trip gives TUPLES back (armed compression must not turn
    a working tuple update into a list — jax.tree.map would reject the
    structure change). NamedTuples (optax states) cannot survive a JSON
    hop and are rejected loudly instead of silently downgraded.
    """
    counter = [0]

    def walk(obj: Any) -> Any:
        if isinstance(obj, dict):
            return {k: walk(obj[k]) for k in obj}
        if isinstance(obj, tuple):
            if hasattr(obj, "_fields"):
                raise TypeError(
                    "NamedTuple containers cannot ride the compression "
                    "wire (the class cannot be reconstructed from JSON); "
                    "convert to a dict first"
                )
            return {"__v6t_tuple__": [walk(v) for v in obj]}
        if isinstance(obj, list):
            return [walk(v) for v in obj]
        arr = np.asarray(obj)
        dt = arr.dtype
        # ml_dtypes extended types (bfloat16, fp8): dtype.str degrades to
        # a raw void ('<V2') that np.dtype() parses back as VOID — the
        # NAME ('bfloat16') survives the JSON hop and _resolve_dtype
        # recovers the real type on rebuild
        node = {
            "__leaf__": counter[0],
            "shape": list(arr.shape),
            "dtype": dt.name if dt.kind == "V" else dt.str,
        }
        counter[0] += 1
        return node

    return walk(tree)


def _resolve_dtype(s: str) -> np.dtype:
    """Skeleton dtype string -> dtype: numpy's own strings directly, an
    ml_dtypes NAME (bfloat16/float8_*) via the ml_dtypes registry — a
    void result means the string lost its meaning, which must fail loud,
    never silently reinterpret bytes."""
    try:
        dt = np.dtype(s)
        if dt.kind != "V":
            return dt
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, s))
    except (ImportError, AttributeError, TypeError) as e:
        raise ValueError(
            f"cannot reconstruct leaf dtype {s!r} from the skeleton"
        ) from e


def rebuild_from_skeleton(skeleton: Any, flat: np.ndarray) -> Any:
    """Inverse of :func:`tree_skeleton` + flat-pack: split ``flat`` back
    into the skeleton's leaf shapes/dtypes."""
    sizes: list[int] = []

    def collect(node: Any) -> None:
        if isinstance(node, dict) and "__leaf__" in node:
            sizes.append(int(np.prod(node["shape"], dtype=np.int64)))
        elif isinstance(node, dict):
            for v in node.values():
                collect(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                collect(v)

    collect(skeleton)
    offsets = np.cumsum([0] + sizes)

    def build(node: Any) -> Any:
        if isinstance(node, dict) and "__leaf__" in node:
            i = int(node["__leaf__"])
            chunk = flat[offsets[i]:offsets[i] + sizes[i]]
            return np.asarray(
                chunk, dtype=_resolve_dtype(node["dtype"])
            ).reshape(node["shape"])
        if isinstance(node, dict) and "__v6t_tuple__" in node:
            return tuple(build(v) for v in node["__v6t_tuple__"])
        if isinstance(node, dict):
            return {k: build(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return [build(v) for v in node]
        return node

    return build(skeleton)
