"""Federated aggregation primitives over the station axis.

These replace the reference's application-level aggregation loop
(`client.task.create(partial...)` fan-out + `wait_for_results` polling + HTTPS
result hops; SURVEY.md §3.2): each primitive consumes *stacked* per-station
pytrees (leading axis S, sharded over the mesh's station axis) and reduces
them on-device. Under `jit`, GSPMD lowers the reductions to XLA all-reduce /
reduce-scatter over ICI — the collective IS the aggregation.

All primitives take an optional participation ``mask`` ([S] bool/float): the
SPMD answer to the reference's asynchronous reality (offline nodes,
stragglers, partial participation). A dropped station contributes weight 0 —
bit-accurate FedAvg-with-dropout without breaking the single-program model.
"""
from __future__ import annotations

import math
from typing import Any, TYPE_CHECKING

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from vantage6_tpu.core.mesh import STATION_AXIS, station_shard_map
from vantage6_tpu.runtime.profiling import RunnerCache, observed_jit

if TYPE_CHECKING:  # pragma: no cover
    from vantage6_tpu.core.mesh import FederationMesh

Pytree = Any

# Eager-path runner cache for the shard_map'd reducers, keyed on
# everything the closure bakes in (mesh fingerprint + the pad/dtype the
# body hard-codes). A fresh closure per call would re-trace on EVERY
# eager invocation — here the second same-shaped call reuses one observed
# executable, and the device observatory (runtime.profiling) records each
# compile as a device.compile span. Called inside an outer jit the
# observed function inlines like a plain jitted one, unchanged.
_SCATTER_RUNNERS = RunnerCache("collectives")


def _scatter_runner(key: tuple, label: str, make):
    return _SCATTER_RUNNERS.get_or_create(
        key, lambda: observed_jit(label, make())
    )


def _station_count(stacked: Pytree) -> int:
    leaves = jax.tree.leaves(stacked)
    if not leaves:
        raise ValueError("empty pytree")
    return leaves[0].shape[0]


def _norm_weights(
    n: int, weights: jax.Array | None, mask: jax.Array | None
) -> jax.Array:
    """Normalize ``weights``/``mask`` into one float32 [n] weight vector.

    NUMERICS CONTRACT: weights are always carried as float32 — integer (or
    bf16) ``weights`` are upcast here. The *reduction* dtype is a separate
    question and differs per primitive:

    - ``fed_sum``/``fed_mean`` accumulate and divide **in each leaf's
      dtype** (the f32 weights are cast down to the leaf dtype first). A
      bf16 leaf therefore pays bf16 rounding once per station in the sum
      and once in the division — with S stations the worst-case relative
      error grows like S * 2^-8, which is visible for S >= ~16.
    - ``fed_sum_scattered``/``fed_mean_scattered`` accumulate **in float32**
      regardless of leaf dtype and return float32; ``comm_dtype`` only
      narrows the cross-slot wire format (see their docstrings).

    tests/test_collectives.py::test_bf16_leaf_rounding_contract pins the
    first behavior so the scattered path's contract stays spelled out.
    """
    w = jnp.ones((n,), jnp.float32) if weights is None else jnp.asarray(weights, jnp.float32)
    if mask is not None:
        w = w * jnp.asarray(mask, jnp.float32)
    return w


def _weighted_leaf_sum(x: jax.Array, w: jax.Array) -> jax.Array:
    """sum_i w[i] * x[i] over the leading (station) axis.

    Zero-weight stations are excluded with `where`, not just multiplied by
    0 — a crashed/diverged station whose contribution is inf/nan must not
    poison the aggregate (nan * 0 == nan). This is what makes participation
    masks a real failure-isolation mechanism.
    """
    ww = w.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
    safe_x = jnp.where(ww != 0, x, jnp.zeros((), x.dtype))
    return jnp.sum(safe_x * ww, axis=0)


def fed_sum(stacked: Pytree, mask: jax.Array | None = None) -> Pytree:
    """Sum each leaf over the station axis. Parity: the `sum` half of
    v6-average's central step."""
    if mask is None:
        return jax.tree.map(lambda x: jnp.sum(x, axis=0), stacked)
    m = jnp.asarray(mask)
    return jax.tree.map(lambda x: _weighted_leaf_sum(x, m), stacked)


def fed_mean(
    stacked: Pytree,
    weights: jax.Array | None = None,
    mask: jax.Array | None = None,
) -> Pytree:
    """Weighted mean over stations — the FedAvg aggregator.

    ``weights`` is typically per-station example counts ([S]); ``mask`` drops
    stations (failure injection / partial participation). Division is by the
    *effective* total weight so dropped stations don't bias the mean.

    Accumulation and division happen in each leaf's own dtype (see
    ``_norm_weights`` for the full numerics contract) — use
    ``fed_mean_scattered`` when f32 accumulation over bf16 leaves matters.
    """
    n = _station_count(stacked)
    w = _norm_weights(n, weights, mask)
    total = jnp.sum(w)
    # Guard the all-dropped edge: return zeros rather than NaN.
    denom = jnp.where(total > 0, total, 1.0)
    return jax.tree.map(
        lambda x: _weighted_leaf_sum(x, w) / jnp.asarray(denom, x.dtype), stacked
    )


def fed_weighted_stats(
    sums: Pytree, counts: jax.Array, mask: jax.Array | None = None
) -> tuple[Pytree, jax.Array]:
    """(global sums, global count) from per-station (sums, counts) — the exact
    shape of the reference's federated-average contract: partials return
    {sum, count}, central divides. Returns aggregated sums and total count."""
    g_sums = fed_sum(sums, mask=mask)
    g_count = fed_sum(counts, mask=mask)
    return g_sums, g_count


def fed_concat(stacked: Pytree) -> Pytree:
    """Flatten the station axis into the data axis: [S, n, ...] -> [S*n, ...].

    The on-device analogue of the central step "fetch all partial results and
    concatenate" (e.g. global event-time grids for Kaplan-Meier). With ragged
    true sizes, pair with per-station validity masks.
    """
    return jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), stacked)


# --------------------------------------------------------------------------
# Scattered aggregation: reduce-scatter primitives for the sharded server
# update (ZeRO-1 style; Xu et al., arXiv:2004.13336).
# --------------------------------------------------------------------------
#
# fed_mean above materializes the full aggregate REPLICATED on every mesh
# slot — an all-reduce-shaped round whose per-slot memory and wire bytes
# both scale with full model size. The scattered primitives instead:
#
#   1. each slot locally reduces its S/D stations' contributions (f32),
#   2. flattens the partial-sum pytree into ONE padded f32 vector,
#   3. `psum_scatter`s it over the station axis — each slot keeps only a
#      1/D shard of the global sum (wire: (D-1)/D * N elements per slot,
#      same as one all-reduce's reduce half; memory: N/D instead of N),
#   4. the caller applies the server update shard-locally and re-replicates
#      with `all_gather_stations` only once per round.
#
# ``comm_dtype`` (e.g. jnp.bfloat16) narrows step 3's on-wire dtype only:
# the local accumulation (1) and everything after the scatter stay f32.


def flat_size(tree: Pytree) -> int:
    """Total element count of ``tree``'s leaves (static, host-side)."""
    return sum(math.prod(x.shape) for x in jax.tree.leaves(tree))


def padded_flat_size(n: int, d: int) -> int:
    """``n`` rounded up to a multiple of ``d`` (psum_scatter divisibility)."""
    return n + (-n) % d


def flatten_tree(tree: Pytree, dtype: Any = jnp.float32) -> jax.Array:
    """Ravel + concatenate every leaf into one flat [N] vector."""
    parts = [x.astype(dtype).reshape(-1) for x in jax.tree.leaves(tree)]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def unflatten_like(template: Pytree, flat: jax.Array) -> Pytree:
    """Inverse of ``flatten_tree``: split ``flat`` back into ``template``'s
    shapes/dtypes. Extra trailing elements (scatter padding) are ignored."""
    leaves, treedef = jax.tree.flatten(template)
    out, off = [], 0
    for leaf in leaves:
        size = math.prod(leaf.shape)
        out.append(flat[off : off + size].reshape(leaf.shape).astype(leaf.dtype))
        off += size
    return jax.tree.unflatten(treedef, out)


def flatten_stacked(stacked: Pytree) -> jax.Array:
    """Per-station flat-pack: [S, ...] pytree -> ONE [S, N] f32 matrix
    (row i = station i's delta, leaves concatenated in tree order).

    The seam the gradient-compression stack operates at
    (docs/compression.md): compressors consume flat per-station vectors,
    never pytrees — same flat layout as ``flatten_tree`` per row.
    """
    leaves = jax.tree.leaves(stacked)
    if not leaves:
        raise ValueError("empty pytree")
    s = leaves[0].shape[0]
    parts = [x.astype(jnp.float32).reshape(s, -1) for x in leaves]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)


def unflatten_stacked(template: Pytree, flat: jax.Array) -> Pytree:
    """Inverse of ``flatten_stacked``: [S, N] rows back into a stacked
    pytree shaped/dtyped like ``template`` (a PER-STATION pytree, i.e.
    one station's leaf shapes) with the leading station axis restored."""
    leaves, treedef = jax.tree.flatten(template)
    s = flat.shape[0]
    out, off = [], 0
    for leaf in leaves:
        size = math.prod(leaf.shape)
        out.append(
            flat[:, off:off + size]
            .reshape((s,) + tuple(leaf.shape))
            .astype(leaf.dtype)
        )
        off += size
    return jax.tree.unflatten(treedef, out)


def station_update_stats(
    flat: jax.Array,
    weights: jax.Array | None = None,
    mask: jax.Array | None = None,
    ef: jax.Array | None = None,
) -> dict[str, jax.Array]:
    """Learning-plane statistics of one round's per-station updates — ONE
    fused f32 pass over the flat-packed ``[S, N]`` rows (the same seam the
    gradient-compression stack operates at; docs/observability.md
    "learning plane"):

    - ``station_norm`` [S]: each station's update L2 norm;
    - ``station_cos`` [S]: cosine similarity of each station's delta to
      the pooled (weighted-mean) delta — the per-client update-quality
      signal async aggregation will accept/down-weight on. A label-flipped
      or poisoned station shows up as a NEGATIVE/low cosine; a scaled one
      as an outlier norm at cosine ~1;
    - ``update_norm`` []: L2 norm of the pooled delta, the global
      convergence signal (its decay trajectory is what the
      ``model_divergence``/``non_convergence`` watchdog rules read);
    - ``station_ef_norm`` [S] (only when ``ef`` is passed): per-station
      error-feedback mass — the per-station refinement of the global
      ``v6t_compress_ef_norm`` gauge.

    The pooled delta uses ``fed_mean``'s exact weighting semantics
    (f32, zero-weight stations nan-isolated, all-dropped guard), computed
    here from the SAME formula regardless of the server-update mode — so
    the stats are fp32-identical between the replicated and scattered
    (ZeRO-1) paths by construction (the bench's parity assertion). The
    per-station reductions are row-local (they ship [S] scalars under
    GSPMD); the cosine leg needs the pooled vector once, which in
    scattered mode costs one extra f32 reduction of N elements — cheap
    next to local training, and `FedAvgSpec(learning_stats=False)` turns
    the whole leg off where wire bytes matter.

    Masked-out stations keep their (fictional, SPMD-computed) norm/cos —
    they are excluded from the POOLED delta, and zeroing them here would
    hide exactly the diverging-station evidence the stats exist to
    surface. The effective weight vector rides along as
    ``station_weight`` so host consumers (RoundHistory, the
    ``anomalous_station`` rule) can tell a participating station from a
    masked-out one — an alert must never name a station the operator
    already excluded.
    """
    x = flat.astype(jnp.float32)
    s = x.shape[0]
    w = _norm_weights(s, weights, mask)
    norms = jnp.sqrt(jnp.sum(x * x, axis=1))
    total = jnp.sum(w)
    denom = jnp.where(total > 0, total, 1.0)
    ww = w.reshape(-1, 1)
    # same nan-isolation as _weighted_leaf_sum: a crashed station's
    # inf/nan delta must not poison the pooled update (nan * 0 == nan)
    safe = jnp.where(ww != 0, x, jnp.zeros((), jnp.float32))
    pooled = jnp.sum(safe * ww, axis=0) / denom
    update_norm = jnp.sqrt(jnp.sum(pooled * pooled))
    dots = x @ pooled
    cos = dots / jnp.maximum(norms * update_norm, 1e-12)
    out = {
        "station_norm": norms,
        "station_cos": cos,
        "update_norm": update_norm,
        "station_weight": w,
    }
    if ef is not None:
        e = ef.astype(jnp.float32)
        out["station_ef_norm"] = jnp.sqrt(jnp.sum(e * e, axis=1))
    return out


def per_round_masks(mask: Any, n_rounds: int) -> jax.Array:
    """Participation masks for a fused K-round program as a ``[K, S]``
    f32 matrix — the scan-xs form of the participation seam.

    Accepts a ``[S]`` mask (one roster for every round — broadcast, the
    common case) or an already per-round ``[K, S]`` matrix (buffered-async
    accept masks, per-round fault schedules). Rank is static under
    tracing, so both forms flow through the SAME fused executable without
    retracing; a wrong leading length on the ``[K, S]`` form is a
    host-side error, not a silent truncation.
    """
    m = jnp.asarray(mask, jnp.float32)
    if m.ndim == 1:
        return jnp.broadcast_to(m, (n_rounds,) + m.shape)
    if m.ndim != 2:
        raise ValueError(
            f"mask must be [S] or [n_rounds, S], got rank {m.ndim}"
        )
    if m.shape[0] != n_rounds:
        raise ValueError(
            f"per-round mask has {m.shape[0]} rounds, expected {n_rounds}"
        )
    return m


def _local_weighted_flat_sum(
    local_stacked: Pytree, local_w: jax.Array
) -> jax.Array:
    """One slot's weighted f32 partial sum over its local station block,
    flattened. Keeps fed_mean's nan-isolation: zero-weight stations are
    excluded with `where`, so a crashed station's inf/nan cannot poison
    the aggregate."""

    def leaf_sum(x: jax.Array) -> jax.Array:
        ww = local_w.reshape((-1,) + (1,) * (x.ndim - 1))
        xf = x.astype(jnp.float32)
        safe = jnp.where(ww != 0, xf, jnp.zeros((), jnp.float32))
        return jnp.sum(safe * ww, axis=0)

    return flatten_tree(
        [leaf_sum(x) for x in jax.tree.leaves(local_stacked)]
    )


def fed_sum_scattered(
    mesh: "FederationMesh",
    stacked: Pytree,
    weights: jax.Array | None = None,
    mask: jax.Array | None = None,
    comm_dtype: Any = None,
) -> jax.Array:
    """Weighted sum over stations, reduce-scattered over the station axis.

    Returns ONE flat float32 vector of ``padded_flat_size(N, D)`` elements
    (N = per-station element count of ``stacked`` minus the leading axis),
    sharded over the mesh's station axis — slot i holds elements
    ``[i*N_pad/D, (i+1)*N_pad/D)`` of the global weighted sum. Recover the
    pytree with ``all_gather_stations`` + ``unflatten_like``.

    Participation ``mask`` / ``weights`` semantics are identical to
    ``fed_sum``/``fed_mean`` (zero-weight stations nan-isolated). Local
    accumulation is float32; ``comm_dtype`` narrows only the cross-slot
    psum_scatter exchange (bf16 halves the on-wire bytes; the D partial
    sums then combine in bf16 — document the accuracy caveat to callers).
    """
    n = _station_count(stacked)
    if n != mesh.n_stations:
        raise ValueError(
            f"stacked has {n} stations but mesh federates {mesh.n_stations}"
        )
    w = _norm_weights(n, weights, mask)
    d = mesh.station_axis_size
    n_flat = flat_size(jax.tree.map(lambda x: x[0], stacked))
    pad = padded_flat_size(n_flat, d) - n_flat

    def body(local_stacked: Pytree, local_w: jax.Array) -> jax.Array:
        flat = _local_weighted_flat_sum(local_stacked, local_w)
        if pad:
            flat = jnp.pad(flat, (0, pad))
        if comm_dtype is not None:
            flat = flat.astype(comm_dtype)
        shard = jax.lax.psum_scatter(
            flat, STATION_AXIS, scatter_dimension=0, tiled=True
        )
        return shard.astype(jnp.float32)

    runner = _scatter_runner(
        ("fed_sum_scattered", mesh.fingerprint(), str(comm_dtype),
         n_flat, pad),
        "collectives.fed_sum_scattered",
        lambda: station_shard_map(
            mesh, body,
            in_specs=(P(STATION_AXIS), P(STATION_AXIS)),
            out_specs=P(STATION_AXIS),
        ),
    )
    return runner(stacked, w)


def fed_mean_scattered(
    mesh: "FederationMesh",
    stacked: Pytree,
    weights: jax.Array | None = None,
    mask: jax.Array | None = None,
    comm_dtype: Any = None,
) -> jax.Array:
    """``fed_mean``, reduce-scattered: the FedAvg aggregator returning each
    slot's 1/D shard of the flat weighted mean (float32 — see
    ``fed_sum_scattered`` for layout and the ``comm_dtype`` contract).

    The division by effective total weight happens on the f32 shard AFTER
    the scatter, so the all-dropped guard and dropped-station debiasing
    match ``fed_mean`` exactly.
    """
    n = _station_count(stacked)
    w = _norm_weights(n, weights, mask)
    total = jnp.sum(w)
    denom = jnp.where(total > 0, total, 1.0)
    s = fed_sum_scattered(mesh, stacked, weights=weights, mask=mask,
                          comm_dtype=comm_dtype)
    return s / denom


def all_gather_stations(mesh: "FederationMesh", flat: jax.Array) -> jax.Array:
    """Re-replicate a station-axis-sharded flat vector (the once-per-round
    all-gather that closes the reduce-scatter -> shard-local update ->
    all-gather cycle)."""

    def body(local: jax.Array) -> jax.Array:
        return jax.lax.all_gather(local, STATION_AXIS, tiled=True)

    runner = _scatter_runner(
        ("all_gather_stations", mesh.fingerprint()),
        "collectives.all_gather",
        lambda: station_shard_map(
            mesh, body, in_specs=(P(STATION_AXIS),), out_specs=P(),
        ),
    )
    return runner(flat)


def fed_mean_scattered_tree(
    mesh: "FederationMesh",
    stacked: Pytree,
    weights: jax.Array | None = None,
    mask: jax.Array | None = None,
    comm_dtype: Any = None,
) -> Pytree:
    """Convenience: scattered mean -> all-gather -> original pytree shape.

    Communication-equivalent to reduce-scatter + all-gather (i.e. one
    all-reduce, but with a bf16-narrowable reduce half); result leaves are
    float32 cast back to each leaf's dtype.
    """
    flat = all_gather_stations(
        mesh,
        fed_mean_scattered(mesh, stacked, weights=weights, mask=mask,
                           comm_dtype=comm_dtype),
    )
    template = jax.tree.map(lambda x: x[0], stacked)
    return unflatten_like(template, flat)


# --------------------------------------------------------------------------
# Secure aggregation: additive masking with exact modular-int cancellation.
# --------------------------------------------------------------------------
#
# The reference's crypto story is (a) hybrid RSA+AES end-to-end payload
# encryption in core and (b) Paillier-style secure sums inside algorithm
# repos (SURVEY.md §2.3). Homomorphic bigint is the wrong tool on an MXU; the
# TPU-native fast path is pairwise additive masking (Bonawitz et al. style):
# station i adds sum_{j>i} PRG(k_ij) - sum_{j<i} PRG(k_ji); masks cancel in
# the all-reduce. Values are quantized to int32 and masked modulo 2^32 so
# cancellation is EXACT (float masking would not cancel bit-wise).
#
# HONESTY NOTE (see docs/THREAT_MODEL.md): masks here derive from one `key`,
# so the guarantee is scoped to observers WITHOUT that key (e.g. a log/trace
# reader, or a party shown a single masked tensor). A real deployment where
# the aggregator is untrusted needs per-pair Diffie-Hellman secrets so no
# single party can strip masks; the collective structure is identical — only
# key provisioning changes. Paillier itself stays host-side
# (`vantage6_tpu.common.paillier`) for parity tests.


def _pair_mask(key: jax.Array, i: jax.Array, j: jax.Array, shape) -> jax.Array:
    """Deterministic pairwise mask PRG(k_ij) as int32, same for both parties."""
    k = jax.random.fold_in(jax.random.fold_in(key, i), j)
    return jax.random.randint(k, shape, jnp.iinfo(jnp.int32).min,
                              jnp.iinfo(jnp.int32).max, dtype=jnp.int32)


def mask_station_value(
    key: jax.Array, station: jax.Array, n_stations: int, quantized: jax.Array
) -> jax.Array:
    """Add this station's pairwise masks (mod 2^32) to its quantized value."""

    def body(s, acc):
        m = _pair_mask(key, jnp.minimum(station, s), jnp.maximum(station, s),
                       quantized.shape)
        sign = jnp.where(s == station, 0, jnp.where(s > station, 1, -1))
        return acc + sign.astype(jnp.int32) * m  # int32 wraps (mod 2^32)

    return jax.lax.fori_loop(0, n_stations, body, quantized)


def quantize(x: jax.Array, scale: float) -> jax.Array:
    return jnp.round(x * scale).astype(jnp.int32)


def dequantize(q: jax.Array, scale: float) -> jax.Array:
    return q.astype(jnp.float32) / scale


def secure_sum(
    stacked: jax.Array,
    key: jax.Array,
    scale: float = 2.0**16,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Secure sum over the station axis via pairwise additive masking.

    ``stacked``: [S, ...] float array. Each station's contribution is
    quantized, masked with pairwise PRG masks (unstrippable by an observer who
    does not hold ``key`` — see the honesty note above for the aggregator
    threat model), then summed; masks cancel exactly in int32 modular
    arithmetic. Returns the dequantized float sum. Max representable |sum| is
    2^31/scale; pick ``scale`` to trade range vs precision.

    ``mask`` ([S]) zeroes non-participating stations' VALUES while every
    station still contributes its pairwise PRG masks — cancellation needs all
    mask pairs present (in a real dropout scenario, recovering lost masks
    requires the Bonawitz secret-sharing protocol; in SPMD all stations are
    always able to compute their masks, so exclusion-by-mask is exact).
    """
    s = stacked.shape[0]
    vals = stacked
    if mask is not None:
        m = jnp.asarray(mask, stacked.dtype).reshape(
            (-1,) + (1,) * (stacked.ndim - 1)
        )
        vals = jnp.where(m != 0, stacked, jnp.zeros((), stacked.dtype)) * m
    q = jax.vmap(lambda i, x: mask_station_value(key, i, s, quantize(x, scale)))(
        jnp.arange(s), vals
    )
    return dequantize(jnp.sum(q, axis=0), scale)


def secure_fed_mean(
    stacked: Pytree,
    weights: jax.Array,
    key: jax.Array,
    scale: float = 2.0**16,
) -> Pytree:
    """FedAvg aggregation where both weighted sums and total weight go through
    the secure-sum path — the aggregator never sees an individual station's
    update in the clear."""
    total_w = secure_sum(jnp.asarray(weights, jnp.float32), key, scale)
    denom = jnp.where(total_w > 0, total_w, 1.0)
    leaves, treedef = jax.tree.flatten(stacked)
    out = []
    for idx, x in enumerate(leaves):
        w = jnp.asarray(weights, x.dtype).reshape((-1,) + (1,) * (x.ndim - 1))
        leaf_key = jax.random.fold_in(key, idx + 1)
        out.append(secure_sum(x * w, leaf_key, scale) / denom)
    return jax.tree.unflatten(treedef, out)
