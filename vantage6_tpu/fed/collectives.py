"""Federated aggregation primitives over the station axis.

These replace the reference's application-level aggregation loop
(`client.task.create(partial...)` fan-out + `wait_for_results` polling + HTTPS
result hops; SURVEY.md §3.2): each primitive consumes *stacked* per-station
pytrees (leading axis S, sharded over the mesh's station axis) and reduces
them on-device. Under `jit`, GSPMD lowers the reductions to XLA all-reduce /
reduce-scatter over ICI — the collective IS the aggregation.

All primitives take an optional participation ``mask`` ([S] bool/float): the
SPMD answer to the reference's asynchronous reality (offline nodes,
stragglers, partial participation). A dropped station contributes weight 0 —
bit-accurate FedAvg-with-dropout without breaking the single-program model.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


def _station_count(stacked: Pytree) -> int:
    leaves = jax.tree.leaves(stacked)
    if not leaves:
        raise ValueError("empty pytree")
    return leaves[0].shape[0]


def _norm_weights(
    n: int, weights: jax.Array | None, mask: jax.Array | None
) -> jax.Array:
    w = jnp.ones((n,), jnp.float32) if weights is None else jnp.asarray(weights, jnp.float32)
    if mask is not None:
        w = w * jnp.asarray(mask, jnp.float32)
    return w


def _weighted_leaf_sum(x: jax.Array, w: jax.Array) -> jax.Array:
    """sum_i w[i] * x[i] over the leading (station) axis.

    Zero-weight stations are excluded with `where`, not just multiplied by
    0 — a crashed/diverged station whose contribution is inf/nan must not
    poison the aggregate (nan * 0 == nan). This is what makes participation
    masks a real failure-isolation mechanism.
    """
    ww = w.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
    safe_x = jnp.where(ww != 0, x, jnp.zeros((), x.dtype))
    return jnp.sum(safe_x * ww, axis=0)


def fed_sum(stacked: Pytree, mask: jax.Array | None = None) -> Pytree:
    """Sum each leaf over the station axis. Parity: the `sum` half of
    v6-average's central step."""
    if mask is None:
        return jax.tree.map(lambda x: jnp.sum(x, axis=0), stacked)
    m = jnp.asarray(mask)
    return jax.tree.map(lambda x: _weighted_leaf_sum(x, m), stacked)


def fed_mean(
    stacked: Pytree,
    weights: jax.Array | None = None,
    mask: jax.Array | None = None,
) -> Pytree:
    """Weighted mean over stations — the FedAvg aggregator.

    ``weights`` is typically per-station example counts ([S]); ``mask`` drops
    stations (failure injection / partial participation). Division is by the
    *effective* total weight so dropped stations don't bias the mean.
    """
    n = _station_count(stacked)
    w = _norm_weights(n, weights, mask)
    total = jnp.sum(w)
    # Guard the all-dropped edge: return zeros rather than NaN.
    denom = jnp.where(total > 0, total, 1.0)
    return jax.tree.map(
        lambda x: _weighted_leaf_sum(x, w) / jnp.asarray(denom, x.dtype), stacked
    )


def fed_weighted_stats(
    sums: Pytree, counts: jax.Array, mask: jax.Array | None = None
) -> tuple[Pytree, jax.Array]:
    """(global sums, global count) from per-station (sums, counts) — the exact
    shape of the reference's federated-average contract: partials return
    {sum, count}, central divides. Returns aggregated sums and total count."""
    g_sums = fed_sum(sums, mask=mask)
    g_count = fed_sum(counts, mask=mask)
    return g_sums, g_count


def fed_concat(stacked: Pytree) -> Pytree:
    """Flatten the station axis into the data axis: [S, n, ...] -> [S*n, ...].

    The on-device analogue of the central step "fetch all partial results and
    concatenate" (e.g. global event-time grids for Kaplan-Meier). With ragged
    true sizes, pair with per-station validity masks.
    """
    return jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), stacked)


# --------------------------------------------------------------------------
# Secure aggregation: additive masking with exact modular-int cancellation.
# --------------------------------------------------------------------------
#
# The reference's crypto story is (a) hybrid RSA+AES end-to-end payload
# encryption in core and (b) Paillier-style secure sums inside algorithm
# repos (SURVEY.md §2.3). Homomorphic bigint is the wrong tool on an MXU; the
# TPU-native fast path is pairwise additive masking (Bonawitz et al. style):
# station i adds sum_{j>i} PRG(k_ij) - sum_{j<i} PRG(k_ji); masks cancel in
# the all-reduce. Values are quantized to int32 and masked modulo 2^32 so
# cancellation is EXACT (float masking would not cancel bit-wise).
#
# HONESTY NOTE (see docs/THREAT_MODEL.md): masks here derive from one `key`,
# so the guarantee is scoped to observers WITHOUT that key (e.g. a log/trace
# reader, or a party shown a single masked tensor). A real deployment where
# the aggregator is untrusted needs per-pair Diffie-Hellman secrets so no
# single party can strip masks; the collective structure is identical — only
# key provisioning changes. Paillier itself stays host-side
# (`vantage6_tpu.common.paillier`) for parity tests.


def _pair_mask(key: jax.Array, i: jax.Array, j: jax.Array, shape) -> jax.Array:
    """Deterministic pairwise mask PRG(k_ij) as int32, same for both parties."""
    k = jax.random.fold_in(jax.random.fold_in(key, i), j)
    return jax.random.randint(k, shape, jnp.iinfo(jnp.int32).min,
                              jnp.iinfo(jnp.int32).max, dtype=jnp.int32)


def mask_station_value(
    key: jax.Array, station: jax.Array, n_stations: int, quantized: jax.Array
) -> jax.Array:
    """Add this station's pairwise masks (mod 2^32) to its quantized value."""

    def body(s, acc):
        m = _pair_mask(key, jnp.minimum(station, s), jnp.maximum(station, s),
                       quantized.shape)
        sign = jnp.where(s == station, 0, jnp.where(s > station, 1, -1))
        return acc + sign.astype(jnp.int32) * m  # int32 wraps (mod 2^32)

    return jax.lax.fori_loop(0, n_stations, body, quantized)


def quantize(x: jax.Array, scale: float) -> jax.Array:
    return jnp.round(x * scale).astype(jnp.int32)


def dequantize(q: jax.Array, scale: float) -> jax.Array:
    return q.astype(jnp.float32) / scale


def secure_sum(
    stacked: jax.Array,
    key: jax.Array,
    scale: float = 2.0**16,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Secure sum over the station axis via pairwise additive masking.

    ``stacked``: [S, ...] float array. Each station's contribution is
    quantized, masked with pairwise PRG masks (unstrippable by an observer who
    does not hold ``key`` — see the honesty note above for the aggregator
    threat model), then summed; masks cancel exactly in int32 modular
    arithmetic. Returns the dequantized float sum. Max representable |sum| is
    2^31/scale; pick ``scale`` to trade range vs precision.

    ``mask`` ([S]) zeroes non-participating stations' VALUES while every
    station still contributes its pairwise PRG masks — cancellation needs all
    mask pairs present (in a real dropout scenario, recovering lost masks
    requires the Bonawitz secret-sharing protocol; in SPMD all stations are
    always able to compute their masks, so exclusion-by-mask is exact).
    """
    s = stacked.shape[0]
    vals = stacked
    if mask is not None:
        m = jnp.asarray(mask, stacked.dtype).reshape(
            (-1,) + (1,) * (stacked.ndim - 1)
        )
        vals = jnp.where(m != 0, stacked, jnp.zeros((), stacked.dtype)) * m
    q = jax.vmap(lambda i, x: mask_station_value(key, i, s, quantize(x, scale)))(
        jnp.arange(s), vals
    )
    return dequantize(jnp.sum(q, axis=0), scale)


def secure_fed_mean(
    stacked: Pytree,
    weights: jax.Array,
    key: jax.Array,
    scale: float = 2.0**16,
) -> Pytree:
    """FedAvg aggregation where both weighted sums and total weight go through
    the secure-sum path — the aggregator never sees an individual station's
    update in the clear."""
    total_w = secure_sum(jnp.asarray(weights, jnp.float32), key, scale)
    denom = jnp.where(total_w > 0, total_w, 1.0)
    leaves, treedef = jax.tree.flatten(stacked)
    out = []
    for idx, x in enumerate(leaves):
        w = jnp.asarray(weights, x.dtype).reshape((-1,) + (1,) * (x.ndim - 1))
        leaf_key = jax.random.fold_in(key, idx + 1)
        out.append(secure_sum(x * w, leaf_key, scale) / denom)
    return jax.tree.unflatten(treedef, out)
