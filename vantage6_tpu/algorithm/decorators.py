"""Algorithm decorators — the algorithm-facing ABI.

Parity: vantage6-algorithm-tools decorators (SURVEY.md §2 item 18):

- ``@data(n)`` injects this station's first n DataFrames as leading args;
- ``@algorithm_client`` injects an `AlgorithmClient` as the first arg;
- ``@metadata`` injects a `RunMetadata` as the first arg.

Stacking order matches the reference: ``@data`` listed first (outermost),
``@algorithm_client`` under it, so the injected signature is
``(client, df1, df2, ...)`` — each decorator prepends its injection at call
time, so the innermost decorator's value lands first::

    @data(2)
    @algorithm_client
    def partial(client, df1, df2, *args, **kwargs): ...

The injected values come from the active `AlgorithmEnvironment` (set by the
orchestrator per run) instead of container env-files. Functions additionally
get marker attributes so the executor knows what they need, and a
``.plain(...)`` escape hatch to call the undecorated function in tests.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

from vantage6_tpu.algorithm.context import current_environment


def data(number_of_databases: int = 1) -> Callable:
    """Inject ``number_of_databases`` of this station's DataFrames.

    Like the reference, the decorated function receives the frames as its
    first positional arguments, in the order the task's ``databases`` listed
    them.
    """
    if callable(number_of_databases):  # used bare: @data
        fn = number_of_databases
        return data(1)(fn)
    n = int(number_of_databases)

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            env = current_environment()
            if len(env.dataframes) < n:
                raise RuntimeError(
                    f"{fn.__name__} requests {n} database(s); run has "
                    f"{len(env.dataframes)} (check the task's `databases` "
                    "argument and the station config)"
                )
            return wrapper.__wrapped__(*env.dataframes[:n], *args, **kwargs)

        wrapper.__v6t_n_dataframes__ = n
        _copy_markers(fn, wrapper)
        wrapper.plain = getattr(fn, "plain", fn)
        return wrapper

    return deco


def algorithm_client(fn: Callable) -> Callable:
    """Inject the AlgorithmClient (subtask creation, result fetch) as arg 0."""

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        env = current_environment()
        if env.client is None:
            raise RuntimeError(
                f"{fn.__name__} needs an algorithm client but none is active "
                "(central functions must run through the orchestrator)"
            )
        return wrapper.__wrapped__(env.client, *args, **kwargs)

    wrapper.__v6t_needs_client__ = True
    _copy_markers(fn, wrapper)
    wrapper.plain = getattr(fn, "plain", fn)
    return wrapper


def metadata(fn: Callable) -> Callable:
    """Inject RunMetadata (task/run/node ids, org, collaboration) as arg 0."""

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        env = current_environment()
        return wrapper.__wrapped__(env.metadata, *args, **kwargs)

    wrapper.__v6t_needs_metadata__ = True
    _copy_markers(fn, wrapper)
    wrapper.plain = getattr(fn, "plain", fn)
    return wrapper


def device_step(fn: Callable) -> Callable:
    """Mark a partial as jax-traceable: THE TPU fast path.

    A ``@device_step`` partial has signature ``fn(data, *args, **kwargs)``
    where ``data`` is this station's array pytree; the orchestrator executes
    all stations' calls as ONE SPMD program (`FederationMesh.fed_map`) instead
    of a per-station Python loop, and aggregation of its results can stay on
    device. This marker has no reference equivalent — it is the opt-in that
    turns a vantage6-shaped algorithm into a compiled collective.
    """
    fn.__v6t_device_step__ = True
    return fn


_MARKERS = (
    "__v6t_n_dataframes__",
    "__v6t_needs_client__",
    "__v6t_needs_metadata__",
    "__v6t_device_step__",
)


def _copy_markers(src: Callable, dst: Callable) -> None:
    for m in _MARKERS:
        if getattr(src, m, None):
            setattr(dst, m, getattr(src, m))


def is_v6t_function(fn: Any) -> bool:
    """True if ``fn`` was wrapped by one of this module's decorators.

    Used by algorithm registration to recognise dispatchable functions even
    when they were attached to a dynamically assembled module (their
    ``__module__`` then names the defining file, not the module object).
    """
    return callable(fn) and any(getattr(fn, m, None) for m in _MARKERS)
