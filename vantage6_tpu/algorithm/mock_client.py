"""MockAlgorithmClient — in-process algorithm testing, reference-compatible.

Parity: vantage6-algorithm-tools MockAlgorithmClient (SURVEY.md §2 item 19),
the official story for unit-testing federated algorithms: supply per-
organization datasets, point at the algorithm module, and central+partial
functions run in-process with no server/node/docker.

Here the mock is a thin veneer over the real Federation runtime (the
framework *is* a production-grade mock in the reference's sense — SURVEY.md
§3.5), so algorithms tested against the mock run unchanged on the TPU path.

Reference-shaped usage::

    client = MockAlgorithmClient(
        datasets=[[{"database": df0}], [{"database": df1}]],  # per org
        module=my_algorithm_module,
    )
    ids = [o["id"] for o in client.organization.list()]
    task = client.task.create(
        input_={"method": "central_average", "kwargs": {"column": "x"}},
        organizations=[ids[0]],
    )
    results = client.result.get(task["id"])
"""
from __future__ import annotations

from types import ModuleType
from typing import Any, Callable

from vantage6_tpu.algorithm.client import AlgorithmClient


class MockAlgorithmClient(AlgorithmClient):
    def __init__(
        self,
        datasets: list[list[dict[str, Any]]],
        module: ModuleType | dict[str, Callable] | str,
        collaboration_id: int | None = None,
        organization_ids: list[int] | None = None,
        node_ids: list[int] | None = None,
        devices: Any = None,
    ):
        if isinstance(module, str):
            import importlib

            module = importlib.import_module(module)
        # Reference shape: datasets[i] is a LIST of database dicts for org i,
        # each {"database": <df-or-path>, "db_type": ..., ...}. v1 supports
        # one database per org via this path (multi-db via Federation
        # directly).
        per_org: list[Any] = []
        for i, org_dbs in enumerate(datasets):
            if not org_dbs:
                raise ValueError(f"organization {i} has no datasets")
            first = org_dbs[0]
            per_org.append(
                first["database"] if isinstance(first, dict) else first
            )
        # Imported here, not at module top: algorithm/__init__ loads this
        # module, and runtime.federation imports the algorithm package.
        from vantage6_tpu.runtime.federation import federation_from_datasets

        fed = federation_from_datasets(
            per_org, algorithms={"mock": module}, devices=devices
        )
        del collaboration_id, organization_ids, node_ids  # accepted for parity
        super().__init__(fed, task=None, station=0, image="mock")

    @property
    def federation(self):
        """The underlying runtime (not in the reference API — handy for
        failure injection and device-mode assertions in tests)."""
        return self._fed
