"""Algorithm-facing API (parity with vantage6-algorithm-tools)."""

from vantage6_tpu.algorithm.client import AlgorithmClient  # noqa: F401
from vantage6_tpu.algorithm.decorators import (  # noqa: F401
    algorithm_client,
    data,
    device_step,
    metadata,
)
from vantage6_tpu.algorithm.mock_client import MockAlgorithmClient  # noqa: F401
from vantage6_tpu.algorithm.wrap import wrap_algorithm  # noqa: F401
