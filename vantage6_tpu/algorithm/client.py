"""AlgorithmClient — the in-algorithm SDK.

Parity: vantage6-algorithm-tools AlgorithmClient (SURVEY.md §2 item 17): the
client a *central* function uses to fan out subtasks to organizations and
collect their results. In the reference every call tunnels through the node
proxy to the server over HTTPS with a container JWT; here calls go straight
into the Federation orchestrator, and `wait_for_results` — seconds of polling
per round in the reference (§3.2) — returns results that, for device-mode
partials, are still resident on the TPU as a stacked pytree.

Surface kept reference-shaped::

    task = client.task.create(input_={"method": ..., "kwargs": {...}},
                              organizations=[0, 1, 2])
    results = client.wait_for_results(task_id=task["id"])
    orgs = client.organization.list()
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:  # pragma: no cover
    from vantage6_tpu.runtime.federation import Federation
    from vantage6_tpu.runtime.task import Task


class AlgorithmClient:
    def __init__(
        self,
        federation: "Federation",
        task: "Task | None" = None,
        station: int = 0,
        image: str = "",
    ):
        self._fed = federation
        self._task = task  # the task this algorithm runs as (parent of subtasks)
        self._station = station
        # Algorithm identifier for tasks created without a parent context
        # (top-level client use); inside a run, the parent task's image wins.
        self._image = image or (task.image if task else "")
        self.task = _TaskSubClient(self)
        self.result = _ResultSubClient(self)
        self.run = _RunSubClient(self)
        self.organization = _OrganizationSubClient(self)

    # Reference signature: wait_for_results(task_id, interval=1). With the
    # station executor pool these are REAL polling knobs: a task created
    # with wait=False may still be queued/executing, and this call blocks
    # (helping the pool when called from inside a pooled run — the nested
    # fan-out deadlock-avoidance rule, docs/host_executor.md) until its runs
    # finish or `timeout` passes (TimeoutError, like the reference client).
    def wait_for_results(
        self,
        task_id: int,
        interval: float = 1.0,
        timeout: float | None = None,
    ) -> list[Any]:
        # timeout default None = block until done, like the reference
        # client's defaults (and Federation.wait_for_results); pass a value
        # to opt into TimeoutError-at-deadline polling.
        return self._fed.wait_for_results(
            task_id, timeout=timeout, interval=interval
        )

    def task_timing(self, task_id: int) -> dict[str, Any]:
        """Per-run lifecycle + straggler decomposition + per-round wire
        accounting (bytes out/in, encode/decode seconds, broadcast dedup
        hits) for one of this algorithm's (sub)tasks — see
        ``Federation.task_timing``. Central code uses this to adapt to
        stations that are transfer-bound rather than compute-bound."""
        return self._fed.task_timing(task_id)

    def wait_for_stacked_result(self, task_id: int) -> tuple[Any, Any]:
        """TPU fast path (no reference equivalent): returns ``(stacked,
        mask)`` — the on-device [S, ...] result pytree over the FULL station
        axis plus the [S] participation mask (1.0 where the station was
        targeted and completed). Central code aggregates with
        `vantage6_tpu.fed.collectives` passing ``mask=mask`` and never pulls
        per-station results to host."""
        t = self._fed.get_task(task_id)
        self._fed.wait_for_results(task_id)  # raise on failures
        if t.stacked_result is None:
            raise ValueError(
                f"task {task_id} was not a device-mode partial; use "
                "wait_for_results()"
            )
        return t.stacked_result, t.participation

    def aggregate_stacked(
        self, task_id: int, weights: Any = None,
        agg_mode: str = "replicated",
    ) -> Any:
        """Masked weighted-mean over a device-mode task's stacked result —
        ``agg_mode`` selects replicated (all-reduce) vs scattered
        (reduce-scatter + all-gather, optionally bf16 on the wire)
        aggregation; see Federation.aggregate_stacked."""
        self._fed.wait_for_results(task_id)  # raise on failures
        return self._fed.aggregate_stacked(
            task_id, weights=weights, agg_mode=agg_mode
        )

    def compress_update(self, tree: Any, name: str = "update") -> Any:
        """Compress a model-delta pytree for the uplink under the
        federation's configured compressor, with THIS station's
        error-feedback accumulator (docs/compression.md). A partial
        returns the compressed payload as its result; the central side
        folds it back with ``decompress_update``. Pass-through when no
        compressor is configured, so the call can stay in place
        unconditionally."""
        return self._fed.compress_update(self._station, tree, name=name)

    def decompress_update(self, payload: Any) -> Any:
        """Materialize the dense update from a `compress_update` payload
        (pass-through for uncompressed results)."""
        return self._fed.decompress_update(payload)


class _TaskSubClient:
    def __init__(self, parent: AlgorithmClient):
        self._p = parent

    def create(
        self,
        input_: dict[str, Any],
        organizations: list[int],
        name: str = "subtask",
        databases: list[dict[str, Any]] | None = None,
        session: int | None = None,
        store_as: str | None = None,
        wait: bool = True,
        **_compat: Any,
    ) -> dict[str, Any]:
        """Create a subtask on the given organization ids.

        Returns the task as a dict (reference wire shape, incl. ``id``).
        Subtasks inherit the parent's session when none is given, so a
        central function's fan-out reads/writes the same workspace.
        ``wait=False`` dispatches asynchronously onto the station executor
        pool and returns immediately — create every subtask first, then
        collect with ``wait_for_results``, and the fan-out runs in parallel
        (reference nodes behave exactly this way).
        """
        parent = self._p._task
        image = parent.image if parent else self._p._image
        if not image:
            raise ValueError(
                "no algorithm image in scope — construct AlgorithmClient "
                "with image=... for top-level use"
            )
        if session is None and parent is not None:
            session = parent.session_id
        task = self._p._fed.create_task(
            image=image,
            input_=input_,
            organizations=organizations,
            name=name,
            databases=databases,
            parent=parent,
            session=session,
            store_as=store_as,
            wait=wait,
        )
        return task.to_dict()

    def get(self, task_id: int) -> dict[str, Any]:
        return self._p._fed.get_task(task_id).to_dict()


class _ResultSubClient:
    def __init__(self, parent: AlgorithmClient):
        self._p = parent

    def get(self, task_id: int) -> list[Any]:
        """Reference: GET /api/result?task_id — list of decrypted results."""
        return self._p._fed.wait_for_results(task_id)

    def from_task(self, task_id: int) -> list[Any]:
        return self.get(task_id)


class _RunSubClient:
    def __init__(self, parent: AlgorithmClient):
        self._p = parent

    def from_task(self, task_id: int) -> list[dict[str, Any]]:
        t = self._p._fed.get_task(task_id)
        return [
            {
                "id": r.id,
                "organization": r.organization,
                "status": r.status.value,
                "result": r.result,
                "log": r.log,
            }
            for r in t.runs
        ]


class _OrganizationSubClient:
    def __init__(self, parent: AlgorithmClient):
        self._p = parent

    def list(self) -> list[dict[str, Any]]:
        return self._p._fed.organizations()

    def get(self, id_: int) -> dict[str, Any]:
        return self._p._fed.organizations()[id_]
