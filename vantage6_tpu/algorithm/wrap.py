"""wrap_algorithm — the container-ABI entrypoint, kept for parity.

Parity: vantage6-algorithm-tools `wrap.py` (SURVEY.md §2 item 18). In the
reference every algorithm image's entrypoint calls ``wrap_algorithm()``,
which reads env vars (INPUT_FILE, OUTPUT_FILE, TOKEN_FILE, database URIs),
deserializes ``{"method", "args", "kwargs"}``, dispatches the named function
from the algorithm module, and writes the serialized result to OUTPUT_FILE.

The env-var names are reconstructed ([M] in SURVEY.md — empty reference
mount): ``INPUT_FILE``, ``OUTPUT_FILE``, ``TOKEN_FILE``,
``USER_REQUESTED_DATABASE_LABELS`` (comma-separated) and per label
``DATABASE_<LABEL>_URI`` / ``DATABASE_<LABEL>_TYPE``.

INPUT_FILE/OUTPUT_FILE payloads ride the wire format of
``common.serialization``: reads auto-detect v1 JSON vs the v2 binary frame,
writes follow ``V6T_WIRE_FORMAT`` (the node's TaskRunner forwards its
``wire_format`` policy through this env var, so both sides of the ABI agree
— docs/wire_format.md).

On-pod execution does NOT go through this file — the Federation binds an
`AlgorithmEnvironment` directly (no serialization boundary in the hot loop).
This entrypoint exists so an algorithm written for this framework can still
be shipped as a standalone container against a remote control plane, and so
the ABI is testable. A client is injected only when ``V6T_SERVER_URL`` names
a control-plane REST server (see vantage6_tpu.server); otherwise
client-needing functions fail with a clear error.
"""
from __future__ import annotations

import os
import sys
from types import ModuleType
from typing import Any

from vantage6_tpu.algorithm.context import (
    AlgorithmEnvironment,
    RunMetadata,
    algorithm_environment,
)
from vantage6_tpu.algorithm.data_loading import load_data
from vantage6_tpu.common.serialization import deserialize, serialize
from vantage6_tpu.core.config import DatabaseConfig


def wrap_algorithm(module: ModuleType | str | None = None) -> None:
    """Run one algorithm method per the env-file ABI and exit.

    ``module`` defaults to the main module (the reference resolves the
    algorithm package the same way).
    """
    if module is None:
        module = sys.modules["__main__"]
    elif isinstance(module, str):
        import importlib

        module = importlib.import_module(module)

    input_path = _require_env("INPUT_FILE")
    output_path = _require_env("OUTPUT_FILE")
    with open(input_path, "rb") as f:
        # writable: algorithm code may mutate its input arrays in place
        # (v1 np.load semantics — the v2 zero-copy view is read-only)
        payload = deserialize(f.read(), writable=True)
    method = payload.get("method")
    if not method:
        raise ValueError("input payload needs a 'method'")
    fn = getattr(module, method, None)
    if fn is None:
        raise AttributeError(
            f"method {method!r} not found in {module.__name__}"
        )

    secret_hex = os.environ.get("V6T_STATION_SECRET", "")
    # org identity ABI (advert signing): V6T_IDENTITY_KEY = path to this
    # org's RSA PEM (node config); V6T_ORG_IDENTITIES = JSON
    # {station index: base64 PEM public key} trust roster
    identity = None
    identity_path = os.environ.get("V6T_IDENTITY_KEY", "")
    if identity_path:
        # zero-arg factory, per the AlgorithmEnvironment convention: loading
        # (and on first start GENERATING, seconds of 4096-bit keygen) the
        # key must only happen for algorithms that actually sign
        def identity(path=identity_path):
            from vantage6_tpu.common.encryption import RSACryptor

            return RSACryptor(path)
    org_identities = None
    idents_json = os.environ.get("V6T_ORG_IDENTITIES", "")
    if idents_json:
        import json as _json

        org_identities = {
            int(k): v for k, v in _json.loads(idents_json).items()
        }
    env = AlgorithmEnvironment(
        dataframes=_load_env_databases(),
        client=_maybe_rest_client(),
        station_secret=bytes.fromhex(secret_hex) if secret_hex else None,
        identity=identity,
        org_identities=org_identities,
        metadata=RunMetadata(
            task_id=_int_env("TASK_ID"),
            run_id=_int_env("RUN_ID"),
            node_id=_int_env("NODE_ID"),
            organization=os.environ.get("ORGANIZATION_NAME", ""),
            collaboration=os.environ.get("COLLABORATION_NAME", ""),
            temporary_directory=os.environ.get("TEMPORARY_FOLDER"),
        ),
    )
    args = payload.get("args", []) or []
    kwargs = payload.get("kwargs", {}) or {}
    # distributed tracing across the container ABI: the node's TaskRunner
    # forwards the run's trace context as V6T_TRACEPARENT; executing under
    # a joined span gives THIS process a current context, so every REST
    # hop the algorithm makes (subtask fan-out through the proxy) carries
    # the task's trace onward — nested central→partial rounds stay ONE
    # trace even in sandbox mode. No-op when untraced.
    from vantage6_tpu.runtime.tracing import TRACER

    with TRACER.span(
        "algorithm.run", kind="algorithm",
        parent=os.environ.get("V6T_TRACEPARENT"),
        attrs={"method": method}, require_parent=True,
    ):
        with algorithm_environment(env):
            result = fn(*args, **kwargs)
    with open(output_path, "wb") as f:
        f.write(serialize(result))


def _require_env(name: str) -> str:
    v = os.environ.get(name)
    if not v:
        raise EnvironmentError(f"required env var {name} not set")
    return v


def _int_env(name: str) -> int | None:
    v = os.environ.get(name)
    return int(v) if v else None


def _env_gates() -> tuple[Any, Any]:
    """Rebuild the node's network gates from the sandbox ABI env (set by
    TaskRunner): the sandboxed loader enforces the same egress whitelist and
    ssh-tunnel resolution as the inline path."""
    import json

    from vantage6_tpu.node.gates import OutboundWhitelist, SSHTunnelManager

    whitelist = None
    raw = os.environ.get("V6T_EGRESS")
    if raw:
        whitelist = OutboundWhitelist(**json.loads(raw))
    tunnels = None
    raw = os.environ.get("V6T_SSH_TUNNELS")
    if raw:
        tunnels = SSHTunnelManager.from_config(json.loads(raw))
    return whitelist, tunnels


def _load_env_databases() -> list[Any]:
    labels = [
        l.strip()
        for l in os.environ.get("USER_REQUESTED_DATABASE_LABELS", "").split(",")
        if l.strip()
    ]
    import json

    whitelist, tunnels = _env_gates()
    frames = []
    for label in labels:
        key = label.upper()
        uri = os.environ.get(f"DATABASE_{key}_URI", "")
        typ = os.environ.get(f"DATABASE_{key}_TYPE", "csv")
        opts = json.loads(os.environ.get(f"DATABASE_{key}_OPTIONS", "") or "{}")
        frames.append(
            load_data(
                DatabaseConfig(label=label, type=typ, uri=uri, options=opts),
                whitelist=whitelist,
                ssh_tunnels=tunnels,
            )
        )
    return frames


def _maybe_rest_client() -> Any:
    url = os.environ.get("V6T_SERVER_URL")
    if not url:
        return None
    try:
        from vantage6_tpu.client.rest import RestAlgorithmClient
    except ImportError as e:
        raise NotImplementedError(
            "V6T_SERVER_URL is set but this build has no REST control-plane "
            "client yet (vantage6_tpu.client.rest); run on-pod via the "
            "Federation runtime instead"
        ) from e
    return RestAlgorithmClient(
        url, token_file=os.environ.get("TOKEN_FILE", "")
    )
