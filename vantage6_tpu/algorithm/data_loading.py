"""Per-database-type data readers.

Parity: the reference's `load_data` dispatch keyed by the node config's
database ``type`` (SURVEY.md §2 item 20): csv, parquet, excel, sql, sparql,
omop — each yielding a pandas DataFrame for ``@data`` injection. Added here:
``array`` (npy/npz or in-memory) for the TPU fast path, where a station's
shard is a jax-ready array pytree rather than a DataFrame.

sparql speaks plain HTTP (application/sparql-results+json) so it needs no
SPARQLWrapper; omop treats the CDM as the SQL database it is (marker-table
check + query). Non-sqlite SQL dialects need sqlalchemy at the node (not in
this image) and say so explicitly.
"""
from __future__ import annotations

from pathlib import Path
from typing import TYPE_CHECKING, Any
from urllib.parse import urlparse

import numpy as np

from vantage6_tpu.core.config import DatabaseConfig

if TYPE_CHECKING:  # pragma: no cover
    from vantage6_tpu.node.gates import OutboundWhitelist, SSHTunnelManager


def _check_egress(db: DatabaseConfig, whitelist: "OutboundWhitelist | None"):
    """Node egress gate (reference: squid whitelist, SURVEY.md item 14).

    Any database URI that names a remote host — http(s)/ftp readers or a
    sql URL with a hostname — must pass the node's OutboundWhitelist before
    a single byte leaves the station. Local files (csv paths, sqlite:///)
    never hit the gate."""
    if whitelist is None:
        return
    uri = db.uri or ""
    parsed = urlparse(uri)
    is_remote = bool(parsed.hostname) and (
        parsed.scheme in ("http", "https", "ftp", "ftps")
        or db.type in ("sql", "omop", "sparql")
    )
    if is_remote and not whitelist.allows(uri):
        raise PermissionError(
            f"egress to {parsed.hostname!r} blocked by this node's outbound "
            f"whitelist (database {db.label!r})"
        )


def _resolve_ssh_tunnel(
    db: DatabaseConfig, tunnels: "SSHTunnelManager | None"
) -> DatabaseConfig:
    """Reference item 15: a db may address a named SSH tunnel endpoint
    (``options.ssh_tunnel``). The endpoint's ``local_uri`` — the tunnel's
    station-local end — replaces the database uri; an unknown name fails
    loudly instead of leaking a connection attempt to the raw address."""
    name = (db.options or {}).get("ssh_tunnel")
    if not name:
        return db
    if tunnels is None:
        raise ValueError(
            f"database {db.label!r} wants ssh tunnel {name!r} but this node "
            "has no ssh_tunnels configured"
        )
    ep = tunnels.endpoint(str(name))
    local_uri = ep.get("local_uri")
    if not local_uri:
        raise ValueError(
            f"ssh tunnel {name!r} has no local_uri configured — on this "
            "platform the operator must point it at a station-reachable "
            "address (no WireGuard/ssh transport exists on-pod; see "
            "node.gates.SSHTunnelManager.reason)"
        )
    opts = {k: v for k, v in db.options.items() if k != "ssh_tunnel"}
    return DatabaseConfig(
        label=db.label, type=db.type, uri=str(local_uri), options=opts
    )


def load_data(
    db: DatabaseConfig,
    data: Any = None,
    whitelist: "OutboundWhitelist | None" = None,
    ssh_tunnels: "SSHTunnelManager | None" = None,
) -> Any:
    """Load one database for one station.

    ``data`` short-circuits loading for programmatically supplied datasets
    (MockAlgorithmClient-style in-memory DataFrames/arrays). ``whitelist``
    and ``ssh_tunnels`` are the node's network gates (node.gates), applied
    to remote URIs before any connection is made.
    """
    if data is not None:
        return data
    db = _resolve_ssh_tunnel(db, ssh_tunnels)
    _check_egress(db, whitelist)
    kind = db.type
    if kind == "csv":
        return _pandas().read_csv(db.uri, **db.options)
    if kind == "parquet":
        return _pandas().read_parquet(db.uri, **db.options)
    if kind == "excel":
        return _pandas().read_excel(db.uri, **db.options)
    if kind == "sql":
        return _load_sql(db)
    if kind == "array":
        if not db.uri:
            raise ValueError(
                f"array database {db.label!r} has no uri and no in-memory data"
            )
        p = Path(db.uri)
        if p.suffix == ".npz":
            with np.load(p) as z:
                return {k: z[k] for k in z.files}
        return np.load(p)
    if kind == "sparql":
        return _load_sparql(db)
    if kind == "omop":
        return _load_omop(db)
    if kind == "session":
        # a dataframe an earlier task materialized in this node's session
        # store (node.runner resolves the handle to a local pickle path;
        # reference v4.7 'sessions')
        return _pandas().read_pickle(db.uri)
    raise ValueError(f"unknown database type {kind!r}")


def _load_sql(db: DatabaseConfig) -> Any:
    query = db.options.get("query")
    if not query:
        raise ValueError(f"sql database {db.label!r} needs options.query")
    scheme = urlparse(db.uri).scheme
    if scheme in ("sqlite", ""):
        # stdlib path: sqlite:///file.db or a bare file path — no
        # sqlalchemy needed (and none ships in this image)
        import contextlib
        import sqlite3

        path = db.uri.split("///", 1)[-1] if "///" in db.uri else db.uri
        # closing(): sqlite3's context manager only commits, it does NOT
        # close — a daemon loading per-run would leak one fd per run
        with contextlib.closing(sqlite3.connect(path)) as conn:
            return _pandas().read_sql_query(query, conn)
    try:
        import sqlalchemy
    except ImportError as e:
        raise NotImplementedError(
            f"sql dialect {scheme!r} needs sqlalchemy, which this "
            "environment does not ship; use sqlite:/// or install "
            "sqlalchemy at the node"
        ) from e
    engine = sqlalchemy.create_engine(db.uri)
    with engine.connect() as conn:
        return _pandas().read_sql(sqlalchemy.text(query), conn)


def _load_sparql(db: DatabaseConfig) -> Any:
    """SPARQL endpoint -> DataFrame (reference: SPARQLWrapper-based loader).

    A SPARQL endpoint is plain HTTP: POST the query, ask for
    ``application/sparql-results+json``, flatten the bindings. No
    SPARQLWrapper dependency needed. The egress gate has already vetted the
    endpoint host (http scheme) before this runs.
    """
    query = db.options.get("query")
    if not query:
        raise ValueError(f"sparql database {db.label!r} needs options.query")
    import requests

    try:
        resp = requests.post(
            db.uri,
            data={"query": query},
            headers={"Accept": "application/sparql-results+json"},
            timeout=float(db.options.get("timeout", 60)),
        )
    except requests.RequestException as e:
        raise ConnectionError(
            f"sparql endpoint {db.uri!r} unreachable: {e}"
        ) from None
    if resp.status_code != 200:
        raise ValueError(
            f"sparql endpoint returned {resp.status_code}: {resp.text[:300]}"
        )
    payload = resp.json()
    variables = payload.get("head", {}).get("vars", [])
    rows = [
        {var: binding.get(var, {}).get("value") for var in variables}
        for binding in payload.get("results", {}).get("bindings", [])
    ]
    return _pandas().DataFrame(rows, columns=variables)


def _load_omop(db: DatabaseConfig) -> Any:
    """OMOP CDM database -> DataFrame (reference: OHDSI-tooling loader).

    An OMOP source IS a SQL database holding the CDM schema; the loader
    verifies the CDM marker table (``person``) exists, then runs the
    configured query through the sql path — same URI forms and gates.
    """
    probe = DatabaseConfig(
        label=db.label, type="sql", uri=db.uri,
        options={"query": "SELECT 1 FROM person LIMIT 1"},
    )
    try:
        _load_sql(probe)
    except ValueError:
        raise
    except NotImplementedError:
        raise
    except Exception as e:
        raise ValueError(
            f"database {db.label!r} does not look like an OMOP CDM source "
            f"(no readable 'person' table): {e}"
        ) from None
    return _load_sql(
        DatabaseConfig(label=db.label, type="sql", uri=db.uri,
                       options=db.options)
    )


def _pandas():
    import pandas as pd

    return pd
