"""Per-database-type data readers.

Parity: the reference's `load_data` dispatch keyed by the node config's
database ``type`` (SURVEY.md §2 item 20): csv, parquet, excel, sql, sparql,
omop — each yielding a pandas DataFrame for ``@data`` injection. Added here:
``array`` (npy/npz or in-memory) for the TPU fast path, where a station's
shard is a jax-ready array pytree rather than a DataFrame.

sparql/omop need packages this image doesn't ship (SPARQLWrapper /
pyarrow-omop tooling); they raise a clear error naming the gap instead of
silently misloading.
"""
from __future__ import annotations

from pathlib import Path
from typing import Any

import numpy as np

from vantage6_tpu.core.config import DatabaseConfig


def load_data(db: DatabaseConfig, data: Any = None) -> Any:
    """Load one database for one station.

    ``data`` short-circuits loading for programmatically supplied datasets
    (MockAlgorithmClient-style in-memory DataFrames/arrays).
    """
    if data is not None:
        return data
    kind = db.type
    if kind == "csv":
        return _pandas().read_csv(db.uri, **db.options)
    if kind == "parquet":
        return _pandas().read_parquet(db.uri, **db.options)
    if kind == "excel":
        return _pandas().read_excel(db.uri, **db.options)
    if kind == "sql":
        query = db.options.get("query")
        if not query:
            raise ValueError(f"sql database {db.label!r} needs options.query")
        import sqlalchemy

        engine = sqlalchemy.create_engine(db.uri)
        with engine.connect() as conn:
            return _pandas().read_sql(sqlalchemy.text(query), conn)
    if kind == "array":
        if not db.uri:
            raise ValueError(
                f"array database {db.label!r} has no uri and no in-memory data"
            )
        p = Path(db.uri)
        if p.suffix == ".npz":
            with np.load(p) as z:
                return {k: z[k] for k in z.files}
        return np.load(p)
    if kind in ("sparql", "omop"):
        raise NotImplementedError(
            f"database type {kind!r} requires packages not present in this "
            "environment (SPARQLWrapper / OMOP tooling); supply a DataFrame "
            "directly or use csv/parquet/sql"
        )
    raise ValueError(f"unknown database type {kind!r}")


def _pandas():
    import pandas as pd

    return pd
