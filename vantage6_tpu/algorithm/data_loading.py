"""Per-database-type data readers.

Parity: the reference's `load_data` dispatch keyed by the node config's
database ``type`` (SURVEY.md §2 item 20): csv, parquet, excel, sql, sparql,
omop — each yielding a pandas DataFrame for ``@data`` injection. Added here:
``array`` (npy/npz or in-memory) for the TPU fast path, where a station's
shard is a jax-ready array pytree rather than a DataFrame.

sparql/omop need packages this image doesn't ship (SPARQLWrapper /
pyarrow-omop tooling); they raise a clear error naming the gap instead of
silently misloading.
"""
from __future__ import annotations

from pathlib import Path
from typing import TYPE_CHECKING, Any
from urllib.parse import urlparse

import numpy as np

from vantage6_tpu.core.config import DatabaseConfig

if TYPE_CHECKING:  # pragma: no cover
    from vantage6_tpu.node.gates import OutboundWhitelist, SSHTunnelManager


def _check_egress(db: DatabaseConfig, whitelist: "OutboundWhitelist | None"):
    """Node egress gate (reference: squid whitelist, SURVEY.md item 14).

    Any database URI that names a remote host — http(s)/ftp readers or a
    sql URL with a hostname — must pass the node's OutboundWhitelist before
    a single byte leaves the station. Local files (csv paths, sqlite:///)
    never hit the gate."""
    if whitelist is None:
        return
    uri = db.uri or ""
    parsed = urlparse(uri)
    is_remote = bool(parsed.hostname) and (
        parsed.scheme in ("http", "https", "ftp", "ftps") or db.type == "sql"
    )
    if is_remote and not whitelist.allows(uri):
        raise PermissionError(
            f"egress to {parsed.hostname!r} blocked by this node's outbound "
            f"whitelist (database {db.label!r})"
        )


def _resolve_ssh_tunnel(
    db: DatabaseConfig, tunnels: "SSHTunnelManager | None"
) -> DatabaseConfig:
    """Reference item 15: a db may address a named SSH tunnel endpoint
    (``options.ssh_tunnel``). The endpoint's ``local_uri`` — the tunnel's
    station-local end — replaces the database uri; an unknown name fails
    loudly instead of leaking a connection attempt to the raw address."""
    name = (db.options or {}).get("ssh_tunnel")
    if not name:
        return db
    if tunnels is None:
        raise ValueError(
            f"database {db.label!r} wants ssh tunnel {name!r} but this node "
            "has no ssh_tunnels configured"
        )
    ep = tunnels.endpoint(str(name))
    local_uri = ep.get("local_uri")
    if not local_uri:
        raise ValueError(
            f"ssh tunnel {name!r} has no local_uri configured — on this "
            "platform the operator must point it at a station-reachable "
            "address (no WireGuard/ssh transport exists on-pod; see "
            "node.gates.SSHTunnelManager.reason)"
        )
    opts = {k: v for k, v in db.options.items() if k != "ssh_tunnel"}
    return DatabaseConfig(
        label=db.label, type=db.type, uri=str(local_uri), options=opts
    )


def load_data(
    db: DatabaseConfig,
    data: Any = None,
    whitelist: "OutboundWhitelist | None" = None,
    ssh_tunnels: "SSHTunnelManager | None" = None,
) -> Any:
    """Load one database for one station.

    ``data`` short-circuits loading for programmatically supplied datasets
    (MockAlgorithmClient-style in-memory DataFrames/arrays). ``whitelist``
    and ``ssh_tunnels`` are the node's network gates (node.gates), applied
    to remote URIs before any connection is made.
    """
    if data is not None:
        return data
    db = _resolve_ssh_tunnel(db, ssh_tunnels)
    _check_egress(db, whitelist)
    kind = db.type
    if kind == "csv":
        return _pandas().read_csv(db.uri, **db.options)
    if kind == "parquet":
        return _pandas().read_parquet(db.uri, **db.options)
    if kind == "excel":
        return _pandas().read_excel(db.uri, **db.options)
    if kind == "sql":
        query = db.options.get("query")
        if not query:
            raise ValueError(f"sql database {db.label!r} needs options.query")
        scheme = urlparse(db.uri).scheme
        if scheme in ("sqlite", ""):
            # stdlib path: sqlite:///file.db or a bare file path — no
            # sqlalchemy needed (and none ships in this image)
            import contextlib
            import sqlite3

            path = db.uri.split("///", 1)[-1] if "///" in db.uri else db.uri
            # closing(): sqlite3's context manager only commits, it does NOT
            # close — a daemon loading per-run would leak one fd per run
            with contextlib.closing(sqlite3.connect(path)) as conn:
                return _pandas().read_sql_query(query, conn)
        try:
            import sqlalchemy
        except ImportError as e:
            raise NotImplementedError(
                f"sql dialect {scheme!r} needs sqlalchemy, which this "
                "environment does not ship; use sqlite:/// or install "
                "sqlalchemy at the node"
            ) from e
        engine = sqlalchemy.create_engine(db.uri)
        with engine.connect() as conn:
            return _pandas().read_sql(sqlalchemy.text(query), conn)
    if kind == "array":
        if not db.uri:
            raise ValueError(
                f"array database {db.label!r} has no uri and no in-memory data"
            )
        p = Path(db.uri)
        if p.suffix == ".npz":
            with np.load(p) as z:
                return {k: z[k] for k in z.files}
        return np.load(p)
    if kind in ("sparql", "omop"):
        raise NotImplementedError(
            f"database type {kind!r} requires packages not present in this "
            "environment (SPARQLWrapper / OMOP tooling); supply a DataFrame "
            "directly or use csv/parquet/sql"
        )
    raise ValueError(f"unknown database type {kind!r}")


def _pandas():
    import pandas as pd

    return pd
