"""Per-run execution environment for algorithm functions.

The reference passes an algorithm its world through the container boundary:
env vars (INPUT_FILE, TOKEN_FILE, OUTPUT_FILE, DATABASE_URI...), mounted data
files, and a proxy URL (SURVEY.md §2 item 18). Here a run's world is an
`AlgorithmEnvironment` bound to a context variable while the function
executes — the decorators read from it. The env-file ABI is still supported
for container-parity via `vantage6_tpu.algorithm.wrap`.
"""
from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Any, Iterator


@dataclasses.dataclass
class RunMetadata:
    """Injected by @metadata (reference: algorithm tools' RunMetaData)."""

    task_id: int | None = None
    run_id: int | None = None
    node_id: int | None = None
    organization: str = ""
    collaboration: str = ""
    temporary_directory: str | None = None


@dataclasses.dataclass
class AlgorithmEnvironment:
    """Everything an algorithm function may have injected."""

    dataframes: list[Any] = dataclasses.field(default_factory=list)
    client: Any = None  # AlgorithmClient
    metadata: RunMetadata = dataclasses.field(default_factory=RunMetadata)
    # station-LOCAL secret (node config / federation-provisioned); basis for
    # per-pair DH mask agreement (common.secureagg_dh) — never leaves the
    # station, never crosses the task payload boundary
    station_secret: bytes | None = None
    # this station's organization RSA identity (encryption.RSACryptor) —
    # signs secure-aggregation adverts (secureagg_dh.sign_advert). May be
    # the cryptor itself OR a zero-arg factory returning it (accessors in
    # secureagg_dh resolve either) so second-scale RSA keygen only happens
    # for algorithms that sign.
    identity: Any = None
    # trust registry: station index -> base64 PEM RSA public identity key,
    # distributed at onboarding (NOT through the task relay). When present,
    # secure-aggregation workloads verify peer adverts against it and fail
    # closed on mismatch (active-MitM resistance). Same value-or-factory
    # convention as `identity`.
    org_identities: Any = None


_current: contextvars.ContextVar[AlgorithmEnvironment | None] = (
    contextvars.ContextVar("v6t_algorithm_env", default=None)
)


def current_environment() -> AlgorithmEnvironment:
    env = _current.get()
    if env is None:
        raise RuntimeError(
            "no algorithm environment active — algorithm functions decorated "
            "with @data/@algorithm_client/@metadata must be invoked through a "
            "Federation / MockAlgorithmClient / wrap_algorithm, not called "
            "directly (pass data explicitly to call them standalone)"
        )
    return env


@contextlib.contextmanager
def algorithm_environment(env: AlgorithmEnvironment) -> Iterator[None]:
    token = _current.set(env)
    try:
        yield
    finally:
        _current.reset(token)
