"""Pallas flash-attention block kernel (TPU).

The hot op of the long-context path (fed_transformer + ring attention).
XLA already fuses the einsum softmax chain reasonably; this kernel keeps the
whole online-softmax loop in VMEM with no [Tq, Tk] materialization in HBM —
the standard flash formulation (Dao et al. 2022) written natively for the
MXU: scores and the weighted-value accumulation are back-to-back matmuls per
(block_q, block_k) tile, accumulated in float32.

``q_offset``/``k_offset`` are runtime scalars (prefetched) giving the global
position of this shard's first query/key token, so the SAME kernel serves
monolithic causal attention (offsets 0) and each hop of ring attention
(offsets = shard index × shard length, see parallel.ring_attention).

CPU tests run with ``interpret=True``; the jnp reference path doubles as the
no-TPU fallback.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    qoff_ref,
    koff_ref,
    kvalid_ref,
    q_ref,  # [block_q, d]
    k_ref,  # [t_k, d]
    v_ref,  # [t_k, d]
    o_ref,  # [block_q, d]
    *,
    causal: bool,
    scale: float,
    block_k: int,
):
    block_q, d = q_ref.shape
    t_k = k_ref.shape[0]
    n_kb = t_k // block_k
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)
    q_pos = (
        qoff_ref[0]
        + qi * block_q
        + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )

    def body(kb, carry):
        m, l, acc = carry
        kblk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        k_idx = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        # padded key slots (k_idx >= true Tk) never contribute
        s = jnp.where(k_idx < kvalid_ref[0], s, NEG_INF)
        if causal:
            k_pos = koff_ref[0] + k_idx
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        blk_max = jnp.max(s, axis=1)
        # clamp at a finite floor: for a fully-masked block, exp(s - m_new)
        # must be exp(-huge) = 0, NOT exp(NEG_INF - NEG_INF) = 1
        m_new = jnp.maximum(jnp.maximum(m, blk_max), -1e20)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=1)
        pv = jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr[:, None] + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    denom = jnp.where(l > 0, l, 1.0)
    o_ref[:] = (acc / denom[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,  # [B, H, Tk, D]
    v: jax.Array,  # [B, H, Tk, D]
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention per (batch, head); Tq/Tk padded to block multiples
    internally. Layout [B, H, T, D] (head-major for clean 2D tiles)."""
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    if scale is None:
        scale = 1.0 / (d**0.5)
    block_q = min(block_q, max(t_q, 8))
    block_k = min(block_k, max(t_k, 8))
    pad_q = (-t_q) % block_q
    pad_k = (-t_k) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        # padded key slots are masked INSIDE the kernel via the k_valid
        # scalar (offset arithmetic can otherwise place them inside the
        # causal horizon)
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    tq_p, tk_p = t_q + pad_q, t_k + pad_k

    qh = q.reshape(b * h, tq_p, d)
    kh = k.reshape(b * h, tk_p, d)
    vh = v.reshape(b * h, tk_p, d)

    qoff = jnp.asarray([q_offset], jnp.int32)
    koff = jnp.asarray([k_offset], jnp.int32)
    kvalid = jnp.asarray([t_k], jnp.int32)

    grid = (b * h, tq_p // block_q)
    kernel = functools.partial(
        _kernel, causal=causal, scale=scale, block_k=block_k
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, block_q, d), lambda bh, i, *_: (bh, i, 0)),
                pl.BlockSpec((None, tk_p, d), lambda bh, i, *_: (bh, 0, 0)),
                pl.BlockSpec((None, tk_p, d), lambda bh, i, *_: (bh, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (None, block_q, d), lambda bh, i, *_: (bh, i, 0)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, d), q.dtype),
        interpret=interpret,
    )(qoff, koff, kvalid, qh, kh, vh)
    out = out.reshape(b, h, tq_p, d)
    return out[:, :, :t_q]


def reference(
    q: jax.Array, k: jax.Array, v: jax.Array,
    q_offset: int = 0, k_offset: int = 0, causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """jnp oracle in the same [B, H, T, D] layout (also the CPU fallback)."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d**0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[2])
        k_pos = k_offset + jnp.arange(k.shape[2])
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)
