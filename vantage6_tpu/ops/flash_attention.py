"""Pallas flash-attention block kernel (TPU).

The hot op of the long-context path (fed_transformer + ring attention).
XLA already fuses the einsum softmax chain reasonably; this kernel keeps the
whole online-softmax loop in VMEM with no [Tq, Tk] materialization in HBM —
the standard flash formulation (Dao et al. 2022) written natively for the
MXU: scores and the weighted-value accumulation are back-to-back matmuls per
(block_q, block_k) tile, accumulated in float32.

``q_offset``/``k_offset`` are runtime scalars (prefetched) giving the global
position of this shard's first query/key token, so the SAME kernel serves
monolithic causal attention (offsets 0) and each hop of ring attention
(offsets = shard index × shard length, see parallel.ring_attention).

CPU tests run with ``interpret=True``; the jnp reference path doubles as the
no-TPU fallback.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    qoff_ref,
    koff_ref,
    kvalid_ref,
    q_ref,  # [block_q, d]
    k_ref,  # [t_k, d]
    v_ref,  # [t_k, d]
    o_ref,  # [block_q, d]
    *,
    causal: bool,
    scale: float,
    block_k: int,
):
    block_q, d = q_ref.shape
    t_k = k_ref.shape[0]
    n_kb = t_k // block_k
    qi = pl.program_id(1)
    # keep q/k in their native dtype: on bf16 inputs the MXU runs at bf16
    # rate with float32 accumulation (preferred_element_type below); an
    # upfront astype(f32) would silently demote to the f32 matmul rate
    q = q_ref[:]
    q_pos = (
        qoff_ref[0]
        + qi * block_q
        + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )

    def body(kb, carry):
        m, l, acc = carry
        kblk = k_ref[pl.ds(kb * block_k, block_k), :]
        vblk = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k], f32 accumulation
        k_idx = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        # padded key slots (k_idx >= true Tk) never contribute
        s = jnp.where(k_idx < kvalid_ref[0], s, NEG_INF)
        if causal:
            k_pos = koff_ref[0] + k_idx
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        blk_max = jnp.max(s, axis=1)
        # clamp at a finite floor: for a fully-masked block, exp(s - m_new)
        # must be exp(-huge) = 0, NOT exp(NEG_INF - NEG_INF) = 1
        m_new = jnp.maximum(jnp.maximum(m, blk_max), -1e20)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=1)
        pv = jax.lax.dot_general(
            p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr[:, None] + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    denom = jnp.where(l > 0, l, 1.0)
    o_ref[:] = (acc / denom[:, None]).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,  # [B, H, Tk, D]
    v: jax.Array,  # [B, H, Tk, D]
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention per (batch, head); Tq/Tk padded to block multiples
    internally. Layout [B, H, T, D] (head-major for clean 2D tiles).

    Differentiable: the forward runs the Pallas kernel; the backward
    recomputes attention (flash-style, nothing but q/k/v/o saved) and
    applies the standard softmax-attention VJP in jnp — see
    ``_attention_bwd``."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d**0.5)
    fn = _flash_vjp(causal, float(scale), block_q, block_k, interpret)
    qoff = jnp.asarray(q_offset, jnp.int32)
    koff = jnp.asarray(k_offset, jnp.int32)
    return fn(q, k, v, qoff, koff)


def _attach_recompute_vjp(forward, causal, scale):
    """Wrap `forward(q, k, v, qoff, koff) -> o` in a custom_vjp whose
    backward is the blockwise recompute (_attention_bwd): residuals are
    only (q, k, v, o) — never the [Tq, Tk] score/probability tensors."""

    @jax.custom_vjp
    def fa(q, k, v, qoff, koff):
        return forward(q, k, v, qoff, koff)

    def fwd(q, k, v, qoff, koff):
        o = fa(q, k, v, qoff, koff)
        return o, (q, k, v, o, qoff, koff)

    def bwd(res, do):
        q, k, v, o, qoff, koff = res
        dq, dk, dv = _attention_bwd(
            q, k, v, o, do, qoff, koff, causal, scale
        )
        return dq, dk, dv, None, None

    fa.defvjp(fwd, bwd)
    return fa


@functools.lru_cache(maxsize=None)
def _flash_vjp(causal, scale, block_q, block_k, interpret):
    """custom_vjp wrapper per static config (cached so jax sees ONE callable
    per config — fresh wrappers would defeat jit tracing caches)."""
    return _attach_recompute_vjp(
        functools.partial(
            _flash_forward, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, interpret=interpret,
        ),
        causal,
        scale,
    )


def recompute_attention(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,
    v: jax.Array,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
    causal: bool = False,
    scale: float | None = None,
    block_k: int = 128,
) -> jax.Array:
    """Flash-MEMORY attention without a Pallas kernel: a blockwise
    (lax.scan over key blocks) online-softmax forward in plain jnp/XLA plus
    the same blockwise custom_vjp backward as the kernel path.

    Peak transient memory is O(Tq * block_k) in BOTH directions and the
    residuals are just (q, k, v, o) — the [Tq, Tk] probabilities that a
    naive XLA attention saves for backward (the memory wall for long
    context) never exist. Use this where the Pallas kernel is unavailable
    (e.g. the axon tunnel, which a compiled pallas_call wedges — see
    .claude/skills/verify/SKILL.md); the kernel remains the faster option
    on directly attached TPUs."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d**0.5)
    fn = _recompute_vjp(causal, float(scale), block_k)
    return fn(
        q, k, v,
        jnp.asarray(q_offset, jnp.int32),
        jnp.asarray(k_offset, jnp.int32),
    )


@functools.lru_cache(maxsize=None)
def _recompute_vjp(causal, scale, block_k):
    return _attach_recompute_vjp(
        functools.partial(
            _blockwise_forward, causal=causal, scale=scale, block_k=block_k
        ),
        causal,
        scale,
    )


def _blockwise_forward(q, k, v, q_offset, k_offset, *, causal, scale,
                       block_k):
    """Online-softmax forward over key blocks (jnp; mirrors the kernel)."""
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    block = min(block_k, t_k)
    pad_k = (-t_k) % block
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    n_blocks = (t_k + pad_k) // block
    kb = jnp.moveaxis(k.reshape(b, h, n_blocks, block, d), 2, 0)
    vb = jnp.moveaxis(v.reshape(b, h, n_blocks, block, d), 2, 0)
    base = jnp.arange(n_blocks) * block
    q_pos = jnp.reshape(q_offset, ()) + jnp.arange(t_q)
    k_off = jnp.reshape(k_offset, ())

    def step(carry, blk):
        m, l, acc = carry
        k_j, v_j, idx0 = blk
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k_j, preferred_element_type=jnp.float32
        ) * scale
        k_idx = idx0 + jnp.arange(block)
        valid = (k_idx < t_k)[None, :]
        if causal:
            valid = valid & (q_pos[:, None] >= (k_off + k_idx)[None, :])
        s = jnp.where(valid[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.maximum(jnp.max(s, -1), -1e20))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * corr + jnp.sum(p, -1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_j.dtype), v_j,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l, acc), None

    m0 = jnp.full((b, h, t_q), NEG_INF, jnp.float32)
    (m, l, acc), _ = lax.scan(
        step,
        (m0, jnp.zeros_like(m0), jnp.zeros((b, h, t_q, d), jnp.float32)),
        (kb, vb, base),
    )
    denom = jnp.where(l > 0, l, 1.0)[..., None]
    return (acc / denom).astype(q.dtype)


def _flash_forward(
    q, k, v, q_offset, k_offset, causal, scale, block_q, block_k, interpret
) -> jax.Array:
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    block_q = min(block_q, max(t_q, 8))
    block_k = min(block_k, max(t_k, 8))
    pad_q = (-t_q) % block_q
    pad_k = (-t_k) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        # padded key slots are masked INSIDE the kernel via the k_valid
        # scalar (offset arithmetic can otherwise place them inside the
        # causal horizon)
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    tq_p, tk_p = t_q + pad_q, t_k + pad_k

    qh = q.reshape(b * h, tq_p, d)
    kh = k.reshape(b * h, tk_p, d)
    vh = v.reshape(b * h, tk_p, d)

    qoff = jnp.asarray([q_offset], jnp.int32)
    koff = jnp.asarray([k_offset], jnp.int32)
    kvalid = jnp.asarray([t_k], jnp.int32)

    grid = (b * h, tq_p // block_q)
    kernel = functools.partial(
        _kernel, causal=causal, scale=scale, block_k=block_k
    )
    # under shard_map with VMA checking, pallas_call outputs must declare
    # which mesh axes they vary over — the output varies exactly as q does
    # (frozenset() outside shard_map, i.e. no-op there). jax.typeof and the
    # vma= kwarg are recent-JAX APIs; on older installs neither exists, so
    # build the kwargs conditionally instead of crashing outside shard_map.
    vma = getattr(jax.typeof(q), "vma", None) if hasattr(jax, "typeof") else None
    shape_kwargs = {"vma": vma} if vma is not None else {}
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, block_q, d), lambda bh, i, *_: (bh, i, 0)),
                pl.BlockSpec((None, tk_p, d), lambda bh, i, *_: (bh, 0, 0)),
                pl.BlockSpec((None, tk_p, d), lambda bh, i, *_: (bh, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (None, block_q, d), lambda bh, i, *_: (bh, i, 0)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, d), q.dtype,
                                       **shape_kwargs),
        interpret=interpret,
    )(qoff, koff, kvalid, qh, kh, vh)
    out = out.reshape(b, h, tq_p, d)
    return out[:, :, :t_q]


def interpreter_twin(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,
    v: jax.Array,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Pure-jnp re-execution of the Pallas kernel's EXACT op sequence —
    the bit-exactness oracle for ``flash_attention(..., interpret=True)``.

    Each grid cell of ``_flash_forward`` is replayed as a Python loop
    over ``(batch*head, q-block)`` with the same padding, the same block
    shapes, the same ``dot_general`` dimension numbers and f32
    accumulation, the same iota/where masking and the same online-softmax
    update order as ``_kernel`` — floating-point op-for-op, so the
    comparison is ``==``, not allclose (tests/test_flash_attention.py
    pins it at seq 128 and 1024). CAVEAT: bit-exact against the
    INTERPRETED kernel (CPU, same XLA scalar ops); a real TPU run is
    validated by the allclose oracle instead — MXU accumulation order is
    hardware-defined and not reproducible op-for-op in jnp.
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d**0.5)
    scale = float(scale)
    b, h, t_q, _ = q.shape
    t_k = k.shape[2]
    # identical padding/blocking decisions to _flash_forward
    block_q = min(block_q, max(t_q, 8))
    block_k = min(block_k, max(t_k, 8))
    pad_q = (-t_q) % block_q
    pad_k = (-t_k) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    tq_p, tk_p = t_q + pad_q, t_k + pad_k
    qh = q.reshape(b * h, tq_p, d)
    kh = k.reshape(b * h, tk_p, d)
    vh = v.reshape(b * h, tk_p, d)
    qoff = jnp.asarray([q_offset], jnp.int32)
    koff = jnp.asarray([k_offset], jnp.int32)
    kvalid = jnp.asarray([t_k], jnp.int32)
    out = jnp.zeros((b * h, tq_p, d), q.dtype)
    for bh in range(b * h):
        for qi in range(tq_p // block_q):
            blk = _twin_cell(
                qh[bh, qi * block_q:(qi + 1) * block_q, :],
                kh[bh], vh[bh], qoff, koff, kvalid, qi,
                causal=causal, scale=scale, block_k=block_k,
            )
            out = out.at[bh, qi * block_q:(qi + 1) * block_q, :].set(blk)
    out = out.reshape(b, h, tq_p, d)
    return out[:, :, :t_q]


def _twin_cell(
    q, kfull, vfull, qoff, koff, kvalid, qi, *, causal, scale, block_k
):
    """One grid cell of ``_kernel``, transliterated: ``pl.program_id(1)``
    is ``qi``, refs are plain arrays, ``pl.ds`` is a slice — every
    numeric op (and its order) is byte-identical to the kernel body."""
    block_q, d = q.shape
    t_k = kfull.shape[0]
    n_kb = t_k // block_k
    q_pos = (
        qoff[0]
        + qi * block_q
        + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )

    def body(kb, carry):
        m, l, acc = carry
        kblk = lax.dynamic_slice(kfull, (kb * block_k, 0), (block_k, d))
        vblk = lax.dynamic_slice(vfull, (kb * block_k, 0), (block_k, d))
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        k_idx = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        s = jnp.where(k_idx < kvalid[0], s, NEG_INF)
        if causal:
            k_pos = koff[0] + k_idx
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        blk_max = jnp.max(s, axis=1)
        m_new = jnp.maximum(jnp.maximum(m, blk_max), -1e20)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=1)
        pv = jax.lax.dot_general(
            p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr[:, None] + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    denom = jnp.where(l > 0, l, 1.0)
    return (acc / denom[:, None]).astype(q.dtype)


def _attention_bwd(
    q, k, v, o, do, q_offset, k_offset, causal, scale, block_k: int = 128
):
    """Blockwise softmax-attention VJP with flash-style recompute.

    Nothing from the forward is saved except (q, k, v, o); scores and
    probabilities are recomputed BLOCKWISE over the key axis (lax.scan), so
    peak transient memory is O(Tq * block_k) — linear in sequence length,
    matching the forward kernel's scaling — never the dense [Tq, Tk]. Two
    passes, both f32 regardless of the compute dtype:

      pass 1: online-softmax statistics L = m + log(l)  (no V work)
      pass 2, per key block j, with D = rowsum(do * o):
        P_j = exp(S_j - L);  dV_j = P_j^T do;  dP_j = do V_j^T
        dS_j = P_j * (dP_j - D);  dQ += dS_j K_j * scale;
        dK_j = dS_j^T Q * scale.

    Fully-masked query rows (forward outputs zeros there) have l = 0, so
    every P_j entry underflows to 0 and their gradients vanish, matching
    the forward's zero output.
    """
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    block_k = min(block_k, t_k)
    pad_k = (-t_k) % block_k
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    n_blocks = (t_k + pad_k) // block_k
    qf, of, dof = (x.astype(jnp.float32) for x in (q, o, do))
    # [n_blocks, B, H, block_k, D] scan inputs
    kb = jnp.moveaxis(
        k.astype(jnp.float32).reshape(b, h, n_blocks, block_k, d), 2, 0
    )
    vb = jnp.moveaxis(
        v.astype(jnp.float32).reshape(b, h, n_blocks, block_k, d), 2, 0
    )
    base = jnp.arange(n_blocks) * block_k
    q_pos = jnp.reshape(q_offset, ()) + jnp.arange(t_q)
    k_off = jnp.reshape(k_offset, ())

    def block_scores(k_j, idx0):
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, k_j, preferred_element_type=jnp.float32
        ) * scale
        k_idx = idx0 + jnp.arange(block_k)
        valid = (k_idx < t_k)[None, :]
        if causal:
            valid = valid & (q_pos[:, None] >= (k_off + k_idx)[None, :])
        return jnp.where(valid[None, None], s, NEG_INF)

    def stat_step(carry, blk):
        m, l = carry
        s = block_scores(*blk)
        m_new = jnp.maximum(m, jnp.maximum(jnp.max(s, -1), -1e20))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(s - m_new[..., None]), -1
        )
        return (m_new, l), None

    m0 = jnp.full((b, h, t_q), NEG_INF, jnp.float32)
    (m, l), _ = lax.scan(stat_step, (m0, jnp.zeros_like(m0)), (kb, base))
    # L normalizer; l == 0 rows (fully masked) keep L = m so P stays 0
    big_l = m + jnp.log(jnp.where(l > 0, l, 1.0))
    d_term = jnp.sum(dof * of, axis=-1)  # [B, H, Tq]

    def bwd_step(dq_acc, blk):
        k_j, v_j, idx0 = blk
        p = jnp.exp(block_scores(k_j, idx0) - big_l[..., None])
        dv_j = jnp.einsum(
            "bhqk,bhqd->bhkd", p, dof, preferred_element_type=jnp.float32
        )
        dp = jnp.einsum(
            "bhqd,bhkd->bhqk", dof, v_j, preferred_element_type=jnp.float32
        )
        ds = p * (dp - d_term[..., None])
        dq_acc = dq_acc + jnp.einsum(
            "bhqk,bhkd->bhqd", ds, k_j, preferred_element_type=jnp.float32
        ) * scale
        dk_j = jnp.einsum(
            "bhqk,bhqd->bhkd", ds, qf, preferred_element_type=jnp.float32
        ) * scale
        return dq_acc, (dk_j, dv_j)

    dq, (dkb, dvb) = lax.scan(
        bwd_step, jnp.zeros((b, h, t_q, d), jnp.float32), (kb, vb, base)
    )
    dk = jnp.moveaxis(dkb, 0, 2).reshape(b, h, t_k + pad_k, d)[:, :, :t_k]
    dv = jnp.moveaxis(dvb, 0, 2).reshape(b, h, t_k + pad_k, d)[:, :, :t_k]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def reference(
    q: jax.Array, k: jax.Array, v: jax.Array,
    q_offset: int = 0, k_offset: int = 0, causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """jnp oracle in the same [B, H, T, D] layout (also the CPU fallback)."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d**0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[2])
        k_pos = k_offset + jnp.arange(k.shape[2])
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)
