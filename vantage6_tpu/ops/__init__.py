"""Pallas TPU kernels for the hot ops (flash attention for long context)."""
from vantage6_tpu.ops.flash_attention import flash_attention  # noqa: F401
