"""UserClient: the researcher-facing SDK.

Parity: vantage6-client `UserClient` (SURVEY.md §2 item 16) — subclients per
entity (`.task`, `.run`, `.organization`, `.collaboration`, `.node`,
`.user`, `.role`, `.rule`, `.study`), JWT auth with optional MFA,
`wait_for_results`, and client-side end-to-end encryption: task inputs are
encrypted per destination organization's public key; results are decrypted
with the researcher's own private key.
"""
from __future__ import annotations

import time
from pathlib import Path
from typing import Any

from vantage6_tpu.common.encryption import CryptorBase, DummyCryptor, RSACryptor
from vantage6_tpu.common.log import setup_logging
from vantage6_tpu.common.rest import RestError, RestSession
from vantage6_tpu.common.serialization import deserialize, serialize
from vantage6_tpu.runtime.tracing import TRACER

log = setup_logging("vantage6_tpu/client")

# public alias: callers catch ClientError
ClientError = RestError


class UserClient:
    """``UserClient("http://localhost", 7601)`` or ``UserClient(url)``."""

    def __init__(
        self,
        host: str,
        port: int | None = None,
        path: str = "",
        verbose: bool = False,
    ):
        base = host if port is None else f"{host}:{port}"
        self.base_url = base.rstrip("/") + path
        self.verbose = verbose
        self._access_token: str | None = None
        self._refresh_token: str | None = None
        self.whoami: dict[str, Any] | None = None
        self.cryptor: CryptorBase = DummyCryptor()
        self._encryption_configured = False
        # event long-poll capability (None until probed; see
        # common.rest.await_task_finished)
        self._event_push: bool | None = None
        # task_id -> SpanContext of the client-side root span that created
        # it: wait_for_results and caller-side aggregation spans attach
        # here so a whole federated round stays ONE trace. Bounded FIFO —
        # a long-lived client must not grow it forever.
        self._task_traces: dict[int, Any] = {}
        self._rest = RestSession(
            self.base_url,
            token_getter=lambda: self._access_token,
            refresh=self._refresh,
        )

        self.task = TaskSubClient(self)
        self.run = RunSubClient(self)
        self.result = self.run  # reference alias (Run né Result)
        self.organization = SubClient(self, "organization")
        self.collaboration = SubClient(self, "collaboration")
        self.node = SubClient(self, "node")
        self.user = SubClient(self, "user")
        self.role = SubClient(self, "role")
        self.rule = SubClient(self, "rule")
        self.study = SubClient(self, "study")
        self.session = SessionSubClient(self)
        self.store = StoreSubClient(self)
        self.util = UtilSubClient(self)

    # ------------------------------------------------------------------ http
    def request(
        self,
        method: str,
        endpoint: str,
        json_body: Any = None,
        params: dict[str, Any] | None = None,
        timeout: float | None = None,
        raw: bool = False,
    ) -> Any:
        return self._rest.request(
            method, endpoint, json_body, params, timeout=timeout, raw=raw
        )

    def paginate(
        self, endpoint: str, params: dict[str, Any] | None = None
    ) -> list[dict[str, Any]]:
        return self._rest.paginate(endpoint, params)

    def _refresh(self) -> bool:
        if not self._refresh_token:
            return False
        try:
            data = RestSession(self.base_url).request(
                "POST",
                "token/refresh",
                {"refresh_token": self._refresh_token},
            )
        except RestError:
            self._access_token = None
            return False
        self._access_token = data["access_token"]
        self._refresh_token = data.get("refresh_token", self._refresh_token)
        return True

    # ------------------------------------------------------------------ auth
    def authenticate(
        self, username: str, password: str, mfa_code: str | None = None
    ) -> dict[str, Any]:
        data = self.request(
            "POST",
            "token/user",
            {"username": username, "password": password, "mfa_code": mfa_code},
        )
        self._access_token = data["access_token"]
        self._refresh_token = data["refresh_token"]
        self.whoami = data["user"]
        return data["user"]

    def change_password(self, current_password: str, new_password: str) -> None:
        """Self-service password change (requires the current password).

        Every outstanding session — including THIS client's tokens — is
        invalidated by the change; call authenticate() again after."""
        self.request(
            "POST",
            "password/change",
            {
                "current_password": current_password,
                "new_password": new_password,
            },
        )

    # ------------------------------------------------------------ encryption
    def setup_encryption(self, private_key: str | Path | None) -> None:
        """Enable E2E crypto (None -> explicit opt-out, DummyCryptor).

        Registers our public key at our organization if it differs
        (reference does the same on node start / client setup).
        """
        self._encryption_configured = True
        if private_key is None:
            self.cryptor = DummyCryptor()
            return
        self.cryptor = RSACryptor(private_key)
        if self.whoami:
            org_id = self.whoami["organization"]["id"]
            org = self.organization.get(org_id)
            if org.get("public_key") != self.cryptor.public_key_str:
                self.request(
                    "PATCH",
                    f"organization/{org_id}",
                    {"public_key": self.cryptor.public_key_str},
                )

    # ----------------------------------------------------------- tracing
    def trace_context(self, task_id: int) -> Any:
        """The trace context (SpanContext) of `task.create(task_id)`, or
        None — parent caller-side spans (e.g. an aggregation step) on it
        so they land in the task's own trace:

            with TRACER.span("aggregate", kind="aggregate",
                             parent=client.trace_context(tid)): ...
        """
        return self._task_traces.get(task_id)

    def _remember_trace(self, task_id: int, ctx: Any) -> None:
        if ctx is None:
            return
        self._task_traces[task_id] = ctx
        while len(self._task_traces) > 256:
            self._task_traces.pop(next(iter(self._task_traces)))

    # --------------------------------------------------------------- results
    def wait_for_results(
        self, task_id: int, interval: float = 0.5, timeout: float = 300.0
    ) -> list[Any]:
        """Wait until the task finishes; return decrypted, deserialized
        results (reference: UserClient.wait_for_results).

        Event-driven against a long-poll-capable server: blocks on the
        event stream and wakes the moment a `status-update` reports the
        task finished, re-checking the task itself each cycle as the
        anti-entropy backstop (events can be evicted, and the user's
        rooms may not cover the task's collaboration). Falls back to
        fixed-`interval` polling against an older server.
        """
        from vantage6_tpu.common.rest import await_task_finished

        # joins the trace task.create started (no-op for untraced tasks);
        # the decrypt+deserialize collection loop is inside the span too —
        # that is the client-decode leg of the per-hop table
        with TRACER.span(
            "client.wait_results", kind="client", service="client",
            parent=self.trace_context(task_id),
            attrs={"task_id": task_id}, require_parent=True,
        ):
            status = await_task_finished(self, task_id, interval, timeout)
            if status.has_failed:
                runs = self.paginate(f"task/{task_id}/run")
                logs = {r["organization"]["id"]: r["log"] for r in runs}
                raise ClientError(
                    500, f"task {task_id} {status.value}: {logs}"
                )
            runs = self.paginate(f"task/{task_id}/run")
            out = []
            for run in sorted(runs, key=lambda r: r["id"]):
                blob = run.get("result")
                if not blob:
                    out.append(None)
                    continue
                # writable: researchers get arrays they can mutate
                # (v1 parity)
                out.append(deserialize(
                    self.cryptor.decrypt_str_to_bytes(blob), writable=True
                ))
            return out


class SubClient:
    """Generic CRUD subclient (`client.organization.list()` etc.)."""

    def __init__(self, parent: UserClient, resource: str):
        self.parent = parent
        self.resource = resource

    def list(self, **params: Any) -> list[dict[str, Any]]:
        """All rows (drains every page; pass page/per_page to get one)."""
        if "page" in params:
            return self.parent.request(
                "GET", self.resource, params=params
            )["data"]
        return self.parent.paginate(self.resource, params)

    def get(self, id_: int) -> dict[str, Any]:
        return self.parent.request("GET", f"{self.resource}/{id_}")

    def create(self, **fields: Any) -> dict[str, Any]:
        return self.parent.request("POST", self.resource, fields)

    def update(self, id_: int, **fields: Any) -> dict[str, Any]:
        return self.parent.request("PATCH", f"{self.resource}/{id_}", fields)

    def delete(self, id_: int) -> None:
        self.parent.request("DELETE", f"{self.resource}/{id_}")


class TaskSubClient(SubClient):
    def __init__(self, parent: UserClient):
        super().__init__(parent, "task")

    def create(
        self,
        collaboration: int,
        organizations: list[int],
        name: str = "task",
        image: str = "",
        description: str = "",
        input_: dict[str, Any] | None = None,
        databases: list[dict[str, Any]] | None = None,
        study: int | None = None,
        session: int | None = None,
        store_as: str | None = None,
        engine: str | None = None,
    ) -> dict[str, Any]:
        """Create a task; `input_` is the reference wire shape
        ``{"method", "args", "kwargs"}``, serialized then encrypted per
        destination organization's public key when E2E crypto is on.

        ``engine="device"`` submits a device-engine task: every targeted
        node executes the SAME run as one collective SPMD program over the
        federation's global device mesh (the nodes must be configured with
        ``device_engine`` so their daemons joined the mesh at start)."""
        # ROOT span of the task's distributed trace: encode+encrypt+POST
        # here, server dispatch / daemon claim+exec / result upload attach
        # underneath via the traceparent the POST carries (tracing.py)
        with TRACER.span(
            "client.task_create", kind="client", service="client",
            attrs={"image": image, "n_orgs": len(organizations)},
        ) as span:
            task = self._create_traced(
                collaboration=collaboration,
                organizations=organizations,
                name=name,
                image=image,
                description=description,
                input_=input_,
                databases=databases,
                study=study,
                session=session,
                store_as=store_as,
                engine=engine,
            )
            span.set_attr(task_id=task.get("id"))
            self.parent._remember_trace(task.get("id"), span.context)
            return task

    def _create_traced(
        self,
        collaboration: int,
        organizations: list[int],
        name: str,
        image: str,
        description: str,
        input_: dict[str, Any] | None,
        databases: list[dict[str, Any]] | None,
        study: int | None,
        session: int | None,
        store_as: str | None,
        engine: str | None,
    ) -> dict[str, Any]:
        input_ = input_ or {}
        blob = serialize(input_)
        # the COLLABORATION decides whether payloads are encrypted (the
        # reference refuses mismatches at submit time, not at the node)
        collab = self.parent.collaboration.get(collaboration)
        encrypting = bool(collab.get("encrypted"))
        if encrypting and isinstance(self.parent.cryptor, DummyCryptor):
            raise ClientError(
                400,
                f"collaboration {collaboration} is encrypted: call "
                "setup_encryption(<private key path>) before creating tasks",
            )
        # an unencrypted collaboration always rides plain base64, even when
        # the researcher holds a key (nodes there have no cryptor)
        cryptor = self.parent.cryptor if encrypting else DummyCryptor()
        pubkeys = []
        for org_id in organizations:
            if encrypting:
                org = self.parent.organization.get(org_id)
                pubkey = org.get("public_key")
                if not pubkey:
                    raise ClientError(
                        400,
                        f"organization {org_id} has no public key registered; "
                        "cannot E2E-encrypt the task input for it",
                    )
            else:
                pubkey = ""
            pubkeys.append(pubkey)
        # single-pass broadcast encryption: one AES pass over the payload +
        # one RSA key seal per organization (encrypt_bytes_broadcast), not
        # one full encrypt per destination
        wires = cryptor.encrypt_bytes_to_str_broadcast(blob, pubkeys)
        org_specs = [
            {"id": org_id, "input": wire}
            for org_id, wire in zip(organizations, wires)
        ]
        body = {
            "name": name,
            "description": description,
            "image": image,
            "method": input_.get("method", ""),
            "collaboration_id": collaboration,
            "organizations": org_specs,
            "databases": databases or [],
        }
        if study is not None:
            body["study_id"] = study
        if session is not None:
            body["session_id"] = session
        if store_as is not None:
            body["store_as"] = store_as
        if engine is not None:
            body["engine"] = engine
        return self.parent.request("POST", "task", body)

    def kill(self, task_id: int) -> dict[str, Any]:
        return self.parent.request("POST", "kill/task", {"task_id": task_id})


class SessionSubClient(SubClient):
    """Session workspaces (reference v4.7+): named dataframes persisted AT
    THE NODES between tasks — create a session, run an extraction task with
    ``store_as``, then point later tasks' databases at
    ``{"label": ..., "type": "session", "dataframe": <handle>}``."""

    def __init__(self, parent: UserClient):
        super().__init__(parent, "session")

    def dataframes(self, session_id: int) -> list[dict[str, Any]]:
        return self.parent.paginate(f"session/{session_id}/dataframe")


class RunSubClient(SubClient):
    def __init__(self, parent: UserClient):
        super().__init__(parent, "run")

    def from_task(self, task_id: int) -> list[dict[str, Any]]:
        return self.parent.paginate(f"task/{task_id}/run")


class StoreSubClient:
    """Browse the algorithm store LINKED to this server (reference: the
    UserClient's store surface): the server proxies the store's public
    listing, so researchers discover approved algorithms — including full
    function/argument metadata, the same payload the web UI's task wizard
    consumes — without talking to the store directly."""

    def __init__(self, parent: "UserClient"):
        self.parent = parent

    def info(self) -> dict[str, Any]:
        """{"url": <store url or None>} — whether a store is linked."""
        return self.parent.request("GET", "store")

    def algorithms(self) -> list[dict[str, Any]]:
        """Approved algorithms with functions/arguments metadata; empty
        when no store is linked (the server 404s that case itself)."""
        try:
            return self.parent.request(
                "GET", "store/algorithm"
            ).get("data", [])
        except ClientError as e:
            if e.status == 404:
                return []
            raise


class UtilSubClient:
    def __init__(self, parent: UserClient):
        self.parent = parent

    def health(self) -> dict[str, Any]:
        return self.parent.request("GET", "health")

    def metrics(self) -> str:
        """The server's unified telemetry as Prometheus text (wire, REST,
        HTTP, executor, event-hub, cache and tracing series)."""
        return self.parent.request("GET", "metrics", raw=True)

    def alerts(self) -> dict[str, Any]:
        """The server watchdog's alert state (GET /api/alerts): active +
        recently resolved alerts and the rule catalog explaining each."""
        return self.parent.request("GET", "alerts")

    def fleet(self) -> dict[str, Any]:
        """The store-backed fleet view (GET /api/fleet): per-source
        freshness, the merged counter/gauge census, top fast-window
        deltas, recent fleet events and the daemon-liveness ratio —
        the same view `tools/doctor.py --live` renders."""
        return self.parent.request("GET", "fleet")

    def debug_dump(self) -> dict[str, Any]:
        """Trigger a server-side flight-recorder dump (POST
        /api/debug/dump); returns the bundle path + record census. Feed
        the path to `tools/doctor.py` for the merged timeline."""
        return self.parent.request("POST", "debug/dump")

    def rounds(self, task_id: int | None = None) -> dict[str, Any]:
        """The server's learning-plane observatory (GET /api/rounds):
        with a ``task_id``, that task's per-round history — loss, pooled
        update norm (the convergence trajectory) and per-station
        norms/cosines, the evidence behind `anomalous_station` /
        `non_convergence` / `model_divergence` alerts; without one, the
        index of tracked tasks with their convergence summaries."""
        if task_id is None:
            return self.parent.request("GET", "rounds")
        return self.parent.request("GET", f"rounds/{task_id}")

    def debug_profile(self, seconds: float = 1.0) -> dict[str, Any]:
        """Open an on-demand jax.profiler window on the server (POST
        /api/debug/profile); returns ``{"path", "seconds", "trace_id"}``
        — the Perfetto session lands at ``path`` on server disk and is
        linked to this request's trace. One window at a time (409 while
        one is open)."""
        return self.parent.request(
            "POST", "debug/profile", {"seconds": seconds}
        )

    def version(self) -> dict[str, Any]:
        return self.parent.request("GET", "version")

    def events(self, since: int = 0) -> dict[str, Any]:
        return self.parent.request("GET", "event", params={"since": since})
