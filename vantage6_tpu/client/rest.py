"""RestAlgorithmClient — the in-container SDK over HTTP.

Parity: vantage6-algorithm-tools AlgorithmClient (SURVEY.md §2 item 17) in
its *real* deployment shape: a containerized algorithm talks to its node's
proxy server with the container JWT from TOKEN_FILE; the proxy relays to the
control plane and handles org-key encryption. Method surface matches the
in-process `AlgorithmClient` so algorithm code is identical on-pod and
containerized (the reference's central fns run unchanged too).
"""
from __future__ import annotations

from typing import Any

from vantage6_tpu.common.rest import RestSession
from vantage6_tpu.common.serialization import deserialize, serialize


class RestAlgorithmClient:
    def __init__(self, url: str, token_file: str = "", token: str = ""):
        self.base_url = url.rstrip("/")
        if not token and token_file:
            with open(token_file) as f:
                token = f.read().strip()
        self.token = token
        self._rest = RestSession(self.base_url, token_getter=lambda: self.token)
        # event long-poll capability through the node proxy (None until
        # probed; see common.rest.await_task_finished) — an old proxy
        # without the /api/event forward demotes this client to polling
        self._event_push: bool | None = None
        # gradient compression for containerized algorithm code: armed by
        # the node operator via V6T_COMPRESS (docs/compression.md); lazy —
        # the fed/jax import only happens when compression is armed
        self._compressor: Any = None
        self.task = _TaskSub(self)
        self.result = _ResultSub(self)
        self.run = _RunSub(self)
        self.organization = _OrgSub(self)

    def _delta_compressor(self):
        if self._compressor is None:
            from vantage6_tpu.fed.compression import (
                DeltaCompressor,
                spec_from_env,
            )

            spec = spec_from_env()
            self._compressor = (
                DeltaCompressor(spec) if spec is not None else False
            )
        return self._compressor or None

    # ------------------------------------------------- gradient compression
    # Surface parity with the in-process AlgorithmClient: same two calls,
    # pass-throughs unless the node armed V6T_COMPRESS. NOTE: under
    # mode="sandbox" each run is a fresh subprocess, so error-feedback
    # accumulators only persist for inline/persistent algorithm processes.
    def compress_update(self, tree: Any, name: str = "update") -> Any:
        comp = self._delta_compressor()
        return comp.compress(tree, name) if comp is not None else tree

    def decompress_update(self, payload: Any) -> Any:
        # pass-throughs must not pull in fed/jax: test the wire tag
        # inline (compression.WIRE_TAG — pinned by
        # tests/test_compression.py::test_rest_client_tag_literal_in_sync)
        if not (isinstance(payload, dict) and "v6t.compressed" in payload):
            return payload
        from vantage6_tpu.fed.compression import decompress_wire_tree

        return decompress_wire_tree(payload)

    # ------------------------------------------------------------------ http
    def request(
        self,
        method: str,
        endpoint: str,
        json_body: Any = None,
        params: dict[str, Any] | None = None,
        timeout: float | None = None,
    ) -> Any:
        return self._rest.request(
            method, endpoint, json_body, params, timeout=timeout
        )

    def paginate(
        self, endpoint: str, params: dict[str, Any] | None = None
    ) -> list[dict[str, Any]]:
        return self._rest.paginate(endpoint, params)

    # --------------------------------------------------------------- results
    def wait_for_results(
        self, task_id: int, interval: float = 1.0, timeout: float = 600.0
    ) -> list[Any]:
        """Wait for a subtask fan-out — event-driven when the node proxy
        forwards the server's long-poll event stream (a central algorithm
        then wakes on its partials' completion events instead of paying up
        to `interval` of dead time per wave); fixed-interval polling
        against an older proxy."""
        from vantage6_tpu.common.rest import await_task_finished

        status = await_task_finished(self, task_id, interval, timeout)
        if status.has_failed:
            raise RuntimeError(f"subtask {task_id} {status.value}")
        runs = self.paginate(f"task/{task_id}/run")
        out = []
        for run in sorted(runs, key=lambda r: r["id"]):
            blob = run.get("result")
            # the proxy has already decrypted: blob is base64 of the
            # serialized payload
            # writable: results land in algorithm code (may mutate, v1
            # semantics — the v2 zero-copy view is read-only)
            out.append(
                deserialize(_unb64(blob), writable=True) if blob else None
            )
        return out


def _unb64(data: str) -> bytes:
    import base64

    return base64.b64decode(data)


class _TaskSub:
    def __init__(self, parent: RestAlgorithmClient):
        self.parent = parent

    def create(
        self,
        input_: dict[str, Any],
        organizations: list[int],
        name: str = "subtask",
        **kw: Any,
    ) -> dict[str, Any]:
        """POST to the node proxy, which encrypts the input per org and
        fills in image/collaboration from the container's context."""
        import base64

        return self.parent.request(
            "POST",
            "task",
            {
                "name": name,
                "organizations": list(organizations),
                "input": base64.b64encode(serialize(input_)).decode(),
                "databases": kw.get("databases", []),
            },
        )

    def get(self, task_id: int) -> dict[str, Any]:
        return self.parent.request("GET", f"task/{task_id}")


class _ResultSub:
    def __init__(self, parent: RestAlgorithmClient):
        self.parent = parent

    def get(self, task_id: int) -> list[Any]:
        return self.parent.wait_for_results(task_id)


class _RunSub:
    def __init__(self, parent: RestAlgorithmClient):
        self.parent = parent

    def from_task(self, task_id: int) -> list[dict[str, Any]]:
        return self.parent.paginate(f"task/{task_id}/run")


class _OrgSub:
    def __init__(self, parent: RestAlgorithmClient):
        self.parent = parent

    def list(self) -> list[dict[str, Any]]:
        return self.parent.request("GET", "organization")["data"]
