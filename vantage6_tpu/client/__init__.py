"""Researcher SDK (parity: vantage6-client, SURVEY.md §2 item 16)."""
from vantage6_tpu.client.client import ClientError, UserClient  # noqa: F401
