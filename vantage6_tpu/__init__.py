"""vantage6-tpu: a TPU-native federated analysis framework.

Re-founds IKNL/vantage6's capabilities (privacy-preserving federated analysis:
tasks, collaborations, stations, encrypted aggregation) on a single JAX device
mesh: data stations are sub-meshes, "partial" tasks run per-station under
shard_map, and "central" aggregation lowers to XLA collectives over ICI.
"""

__version__ = "0.1.0"

from vantage6_tpu.common.enums import TaskStatus, RunStatus  # noqa: F401
from vantage6_tpu.core.config import FederationConfig  # noqa: F401
from vantage6_tpu.core.mesh import FederationMesh, Station  # noqa: F401
