"""The control-plane server application.

Parity: vantage6-server's `ServerApp`/`run_server` (SURVEY.md §2 item 1):
bind the database, migrate the schema, seed the rule matrix + default roles,
ensure a root user, register the REST resources and the event hub, serve.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable

from vantage6_tpu.common.context import ServerContext
from vantage6_tpu.common.log import setup_logging
from vantage6_tpu.server import models
from vantage6_tpu.server.auth import TokenAuthority
from vantage6_tpu.server.events import EventHub
from vantage6_tpu.server.permission import PermissionManager
from vantage6_tpu.server.resources import register_resources
from vantage6_tpu.server.web import App, AppServer, TestClient

log = setup_logging("vantage6_tpu/server")

# replica-local: disambiguates in-process replicas sharing one pid
_REPLICA_SEQ = itertools.count(1)


class ServerApp:
    def __init__(
        self,
        uri: str = "sqlite:///:memory:",
        jwt_secret: str | None = None,
        algorithm_policy: Callable[[str], bool] | None = None,
        mailer: Any = None,
        store_url: str | None = None,
        replica_id: str | None = None,
    ):
        self.started_at = time.time()
        self.db = models.init(uri)
        # replica identity: stamped on every request span (web.App), the
        # heartbeat table, and /api/health — how trace_view attributes
        # per-hop latency per replica when N of us share one store
        self.replica_id = replica_id or os.environ.get(
            "V6T_REPLICA_ID"
        ) or f"srv-{os.getpid()}-{next(_REPLICA_SEQ)}"
        self.pm = PermissionManager()
        self.default_roles = self.pm.ensure_default_roles()
        self.tokens = TokenAuthority(jwt_secret)
        # event substrate keyed off the backend: a SHARED store (N replica
        # processes) needs the event stream and cache-invalidation bus IN
        # the store; a single replica keeps the in-process hub unchanged
        if self.db.SHARED:
            from vantage6_tpu.server.pubsub import DbPubSub, record_heartbeat

            self.hub: Any = DbPubSub(self.db, replica_id=self.replica_id)
            record_heartbeat(self.db, self.replica_id, self.started_at)
            # cross-replica cache coherence: start draining CACHE_INVALIDATE
            # events emitted by the peers from "now"
            self._inval_cursor = self.hub.cursor
        else:
            self.hub = EventHub()
            self._inval_cursor = 0
        self._inval_last_drain = 0.0  # replica-local: drain rate limiter
        self._inval_lock = threading.Lock()
        # hot-path caches (server/cache.py): token→principal resolution and
        # org→collaborations visibility. Explicitly invalidated by the
        # mutating endpoints in resources.py; short TTL as backstop.
        from vantage6_tpu.server.cache import AuthCache, VisibilityCache

        self.auth_cache = AuthCache()
        self.vis_cache = VisibilityCache()
        # account recovery mail (reference: SMTP; pluggable here — the
        # default LogMailer records messages for dev/test deployments)
        from vantage6_tpu.server.mail import LogMailer

        self.mailer = mailer or LogMailer()
        # optional algorithm-store gate: image -> allowed? (SURVEY §2 item 9;
        # wired up by the store service or a static allow-list)
        self.algorithm_policy = algorithm_policy
        # linked algorithm store (SURVEY §2 item 9); the UI browses it
        # through the server-side proxy at /api/store/algorithm
        self.store_url = store_url.rstrip("/") if store_url else None
        self.ws_url: str | None = None  # set by an attached WebSocketBridge
        # replica-local: each replica serves its own websocket bridges
        self._bridges: list[Any] = []  # stopped in close()
        self.app = App("server", replica_id=self.replica_id)
        # learning plane over the shared store: round records key on
        # (task, round) in the learning_round table, so a trajectory whose
        # per-round subtasks were served by different replicas still reads
        # back as ONE history from /api/rounds (runtime/learning.py)
        self._learning_store: Any = None
        if self.db.SHARED:
            from vantage6_tpu.runtime.learning import LEARNING, LearningStore

            self._learning_store = LearningStore(self.db)
            LEARNING.attach_store(self._learning_store)
        # unified telemetry (common.telemetry): this server's hot-state
        # gauges — event hub fill/eviction, cache hit rates — join the
        # process-wide wire/REST/executor/tracing series behind
        # GET /api/metrics. Keyed registration: a newer ServerApp in the
        # same process replaces this one's collector.
        from vantage6_tpu.common.telemetry import REGISTRY

        REGISTRY.register_collector("server", self._telemetry_collector)
        # live health watchdog (runtime.watchdog): this server feeds the
        # process singleton its DB view (ACTIVE runs for stuck_run, node
        # ping freshness for daemon_lapsed) and registers the self-checks
        # behind the /api/health verdict. Keyed registration — a newer
        # ServerApp in the same process replaces this one's feed — and the
        # evaluation thread is refcounted (started here, stopped in close).
        from vantage6_tpu.runtime.watchdog import WATCHDOG

        self.watchdog = WATCHDOG
        WATCHDOG.register_feed("server", self._watchdog_feed)
        WATCHDOG.register_component("event_hub", self._hub_check)
        WATCHDOG.register_component("tracer_sink", _tracer_sink_check)
        WATCHDOG.start()
        # autopilot remediation over the store (runtime.autopilot,
        # docs/OPERATOR_GUIDE.md "autopilot"): opt-in via V6T_AUTOPILOT.
        # The server actuator only carries the requeue capabilities —
        # selection/mask/admission policies self-suppress here. Listener
        # key is per-replica: two replicas may both attach, and the
        # store-level CAS keeps their concurrent remediation exactly-once.
        # fleet self-ingest cadence: the server is itself a fleet source —
        # its snapshot lands in the fleet tables on the watchdog tick,
        # rate-limited to the push interval like any remote pusher
        # replica-local: watchdog-thread-only cursor state
        self._fleet_last_push = 0.0
        self._fleet_notes_since = time.time()
        self._fleet_seq = 0
        self.autopilot = None
        if os.environ.get("V6T_AUTOPILOT", "").strip().lower() in (
            "1", "true", "yes", "on",
        ):
            from vantage6_tpu.runtime.autopilot import Autopilot

            self.autopilot = Autopilot(
                actuator=ServerActuator(self),
                listener_key=f"autopilot-{self.replica_id}",
            )
            self.autopilot.attach()
        register_resources(self)
        from vantage6_tpu.server.ui import register_ui

        register_ui(self)

    def drain_invalidations(self) -> None:
        """Apply CACHE_INVALIDATE events other replicas committed to the
        shared stream (resources.py emits them next to its local
        invalidate calls). Called from the auth hot path, rate-limited to
        one stream read per ~25 ms — the cross-replica staleness bound;
        the caches' own TTL stays the backstop. No-op on an in-process
        hub: there a local invalidate already covered the only replica."""
        if not getattr(self.hub, "SHARED", False):
            return
        now = time.monotonic()
        with self._inval_lock:
            if now - self._inval_last_drain < 0.025:
                return
            self._inval_last_drain = now
            cursor = self._inval_cursor
        from vantage6_tpu.server.events import CACHE_INVALIDATE, REPLICA_ROOM

        try:
            events = self.hub.fetch(since=cursor, rooms=[REPLICA_ROOM])
            new_cursor = self.hub.cursor
        except Exception:  # backend busy — next request retries
            return
        for ev in events:
            if ev.name != CACHE_INVALIDATE:
                continue
            entity = (ev.data or {}).get("entity")
            pid = (ev.data or {}).get("id")
            if entity in ("user", "node") and pid is not None:
                self.auth_cache.invalidate_principal(entity, pid)
            elif entity in ("role", "rule"):
                self.auth_cache.invalidate_all()
            elif entity == "collaboration":
                self.vis_cache.invalidate_all()
        with self._inval_lock:
            self._inval_cursor = max(self._inval_cursor, new_cursor)

    def _watchdog_feed(self) -> dict[str, Any]:
        """The server's run/node state for the watchdog rules: every
        ACTIVE run (with the task's traceparent so a stuck_run alert lands
        on the round's own trace) and every online node's ping freshness.
        Runs on the watchdog thread — db.py keeps one sqlite connection
        per thread for exactly this access pattern. On a SHARED backend
        the periodic tick doubles as this replica's heartbeat, and the
        peers' heartbeat rows feed the `replica_lapsed` rule."""
        if models.Model.db is None:  # closed mid-evaluation
            return {}
        runs = []
        task_tp: dict[int, str | None] = {}
        for run in models.TaskRun.list(status="active"):
            if run.task_id not in task_tp:
                task = models.Task.get(run.task_id)
                task_tp[run.task_id] = task.traceparent if task else None
            runs.append({
                "run_id": run.id,
                "task_id": run.task_id,
                "status": "active",
                "assigned_at": run.assigned_at,
                "started_at": run.started_at,
                "organization_id": run.organization_id,
                "node_id": run.node_id,
                "traceparent": task_tp[run.task_id],
            })
        nodes = [
            {
                "node_id": n.id,
                "name": n.name,
                "status": n.status or "offline",
                "last_seen_at": n.last_seen_at,
            }
            for n in models.Node.list(status="online")
        ]
        feed: dict[str, Any] = {"runs": runs, "nodes": nodes}
        if self.db.SHARED:
            from vantage6_tpu.server import pubsub

            try:
                pubsub.record_heartbeat(
                    self.db, self.replica_id, self.started_at
                )
                feed["replicas"] = pubsub.list_replicas(self.db)
            except Exception:  # heartbeat must never break the rule feeds
                pass
        # fleet fabric (server/fleet.py): self-ingest this replica's own
        # compact snapshot on the push cadence — the server is a fleet
        # source like any daemon — then publish the store-backed series
        # and freshness census the SLO rules read. The tick piggybacks
        # the watchdog thread exactly as remote pushers piggyback their
        # ping workers.
        from vantage6_tpu.common import fleet as fleet_push
        from vantage6_tpu.server import fleet

        now = time.time()
        if now - self._fleet_last_push >= fleet_push.push_interval():
            self._fleet_last_push = now
            try:
                payload = fleet_push.build_snapshot(
                    self.replica_id, "server", self._fleet_seq,
                    notes_since=self._fleet_notes_since,
                )
                fleet.ingest(self.db, payload)
                self._fleet_seq += 1
                for note in payload.get("notes") or []:
                    ts = note.get("ts")
                    if isinstance(ts, (int, float)):
                        self._fleet_notes_since = max(
                            self._fleet_notes_since, float(ts)
                        )
            except Exception:  # self-ingest must never break the rule feeds
                pass
        slow = float(self.watchdog.config.get("slo_slow_window_s", 3600.0))
        feed["fleet_sources"] = fleet.sources(self.db, now)
        feed["slo_dispatch"] = fleet.metric_series(
            self.db, "v6t_run_dispatch_seconds", now - slow
        )
        feed["slo_rounds"] = fleet.metric_series(
            self.db, "v6t_round_updates_total", now - slow
        )
        return feed

    def _hub_check(self) -> tuple[bool, str]:
        try:
            stats = self.hub.stats()
        except Exception as e:  # pragma: no cover - hub is in-process
            return False, f"event hub stats raised: {e}"
        return True, (
            f"buffer {stats['buffer_len']}, cursor {stats['cursor']}"
        )

    def _telemetry_collector(self) -> dict[str, float]:
        hub = self.hub.stats()
        return {
            "v6t_event_hub_buffer_len": hub["buffer_len"],
            "v6t_event_hub_cursor": hub["cursor"],
            "v6t_event_hub_evicted_through": hub["evicted_through"],
            "v6t_event_hub_subscribers": hub["subscribers"],
            "v6t_auth_cache_hits_total": self.auth_cache.hits,
            "v6t_auth_cache_misses_total": self.auth_cache.misses,
            "v6t_auth_cache_entries": len(self.auth_cache),
            "v6t_visibility_cache_hits_total": self.vis_cache.hits,
            "v6t_visibility_cache_misses_total": self.vis_cache.misses,
            "v6t_visibility_cache_entries": len(self.vis_cache),
            "v6t_server_uptime_seconds": time.time() - self.started_at,
        }

    def close(self) -> None:
        """Stop attached bridges and release the database binding (required
        before a new ServerApp in the same process — see models.init).
        Idempotent: the watchdog's evaluation thread is refcounted, so a
        second close() must not decrement again (it would stop a newer
        embedder's loop in the same process)."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        for bridge in list(self._bridges):
            try:
                bridge.stop()
            except Exception:  # pragma: no cover
                pass
        self._bridges.clear()
        # symmetric with __init__'s register: a closed server must not
        # keep reporting (or be pinned alive by) the telemetry registry
        from vantage6_tpu.common.telemetry import REGISTRY
        from vantage6_tpu.runtime.watchdog import WATCHDOG

        if self.autopilot is not None:
            self.autopilot.detach()
            self.autopilot = None
        # only if still ours: a newer ServerApp may have replaced the feed
        # (keyed registration — same story as the telemetry collector);
        # the shared components go only when no server feed remains at all
        WATCHDOG.unregister_feed("server", self._watchdog_feed)
        if not WATCHDOG.has_feed("server"):
            WATCHDOG.unregister_component("event_hub")
            WATCHDOG.unregister_component("tracer_sink")
        # reconcile once with the feed gone: alerts THIS server's state
        # raised are proposed by nothing anymore and clear now, instead of
        # haunting the singleton until some future embedder's first tick
        try:
            WATCHDOG.evaluate()
        except Exception:  # pragma: no cover - teardown must not fail
            pass
        WATCHDOG.stop()
        REGISTRY.unregister_collector("server", self._telemetry_collector)
        if self._learning_store is not None:
            from vantage6_tpu.runtime.learning import LEARNING

            LEARNING.detach_store(self._learning_store)
        if self.db.SHARED:
            from vantage6_tpu.server import pubsub

            try:  # clean departure: don't linger as "lapsed" in peers' health
                pubsub.drop_heartbeat(self.db, self.replica_id)
            except Exception:  # pragma: no cover - teardown must not fail
                pass
        if hasattr(self.hub, "close"):
            self.hub.close()
        # refcounted: with in-process replicas over one SHARED store, only
        # the last close actually unbinds/closes the database (models.release)
        models.release(self.db)

    # ----------------------------------------------------------------- seed
    def ensure_root(
        self,
        username: str = "root",
        password: str | None = None,
        organization_name: str = "root",
    ) -> tuple[models.User, str | None]:
        """Idempotently create the root org + root user (reference seeds the
        same at first start). Returns (user, generated_password | None)."""
        user = models.User.first(username=username)
        if user is not None:
            return user, None
        org = models.Organization.first(name=organization_name)
        if org is None:
            org = models.Organization(name=organization_name).save()
        import secrets

        generated = password or secrets.token_urlsafe(16)
        user = models.User(username=username, organization_id=org.id)
        user.set_password(generated)
        user.save()
        user.add_role(self.default_roles["Root"])
        log.info("created root user %r", username)
        return user, generated

    # ---------------------------------------------------------------- serve
    def test_client(self) -> TestClient:
        return TestClient(self.app)

    def serve_ws(self, host: str = "127.0.0.1", port: int = 0):
        """Start the SocketIO-equivalent push bridge (SURVEY §2 item 6)."""
        from vantage6_tpu.server.ws import WebSocketBridge

        bridge = WebSocketBridge(self, host, port).start_background()
        self._bridges.append(bridge)
        return bridge

    def serve(
        self, host: str = "127.0.0.1", port: int = 7601, background: bool = False
    ) -> AppServer:
        server = AppServer(self.app, host, port)
        log.info("serving control plane on %s", server.url)
        if background:
            return server.start_background()
        server.serve_forever()
        return server


class ServerActuator:
    """Autopilot capabilities over the server's store (duck-typed by
    runtime.autopilot): re-queue runs orphaned by a lapsed daemon or a
    lapsed replica. Selection-weight / mask / admission capabilities are
    Federation-side — policies needing them self-suppress here.

    Both requeues are CAS-guarded (`TaskRun.compare_and_swap` with the
    observed status as the expectation), the same idiom as claim-batch's
    orphan reset: two replicas' autopilots remediating the SAME
    daemon_lapsed alert concurrently re-queue each orphan exactly once —
    the loser's swap fails and it leaves the run alone.
    """

    def __init__(self, srv: ServerApp):
        self.srv = srv

    def _requeue(
        self, run: "models.TaskRun", status: Any, message: str
    ) -> bool:
        from vantage6_tpu.common.enums import TaskStatus
        from vantage6_tpu.server import events as ev

        if not models.TaskRun.compare_and_swap(
            run.id,
            sets={"status": TaskStatus.PENDING.value, "log": message},
            expect={"status": status.value},
        ):
            return False
        task = models.Task.get(run.task_id)
        if task is not None:
            self.srv.hub.emit(
                ev.STATUS_UPDATE,
                {
                    "task_id": task.id,
                    "run_id": run.id,
                    "status": TaskStatus.PENDING.value,
                    "organization_id": run.organization_id,
                    "task_status": task.status(),
                },
                room=ev.collaboration_room(task.collaboration_id),
            )
        return True

    def requeue_node_runs(self, node_id: int) -> int:
        """daemon_lapsed remediation: the node stopped pinging mid-run,
        so its INITIALIZING/ACTIVE runs will never report — put them back
        to PENDING for whoever claims next (the restarted daemon, or a
        peer node of the same organization). Returns how many runs THIS
        caller re-queued (a concurrent peer's CAS wins count there)."""
        from vantage6_tpu.common.enums import TaskStatus

        node = models.Node.get(node_id)
        if node is None:
            return 0
        requeued = 0
        for status in (TaskStatus.INITIALIZING, TaskStatus.ACTIVE):
            for run in models.TaskRun.list(
                status=status.value, organization_id=node.organization_id
            ):
                if run.node_id is not None and run.node_id != node_id:
                    continue  # a sibling node's live work
                if self._requeue(
                    run, status,
                    "daemon lapsed mid-run; re-queued by autopilot",
                ):
                    requeued += 1
        return requeued

    def requeue_replica_runs(self, replica_id: str) -> int:
        """replica_lapsed remediation: a peer replica died; any run whose
        node has meanwhile gone offline has lost both its server AND its
        executor — re-queue those. Runs of still-online nodes are left
        alone (any surviving replica serves their reports)."""
        from vantage6_tpu.common.enums import TaskStatus

        requeued = 0
        node_status: dict[int | None, str] = {None: "offline"}
        for status in (TaskStatus.INITIALIZING, TaskStatus.ACTIVE):
            for run in models.TaskRun.list(status=status.value):
                if run.node_id not in node_status:
                    node = models.Node.get(run.node_id)
                    node_status[run.node_id] = (
                        (node.status or "offline") if node else "offline"
                    )
                if node_status[run.node_id] == "online":
                    continue
                if self._requeue(
                    run, status,
                    f"replica {replica_id} lapsed with the node offline; "
                    "re-queued by autopilot",
                ):
                    requeued += 1
        return requeued


def _tracer_sink_check() -> tuple[bool, str]:
    """Tracer health for /api/health: a configured-then-failed span sink
    means trace evidence is being lost — degraded, not fatal."""
    from vantage6_tpu.runtime.tracing import TRACER

    stats = TRACER.stats()
    if stats["sink_errors"] > 0:
        return False, (
            f"JSONL span sink disabled after {stats['sink_errors']} write "
            "failure(s); spans continue in the ring buffer only"
        )
    return True, (
        f"{stats['spans_recorded']} spans recorded, "
        f"{stats['spans_dropped']} evicted"
    )


def run_server(ctx: ServerContext, background: bool = False) -> AppServer:
    """Start a server from an instance context (reference: `v6 server start`)."""
    from vantage6_tpu.common.flight import install as flight_install
    from vantage6_tpu.server.mail import mailer_from_config

    # arm crash forensics for the server process: dump the flight rings on
    # any uncaught exception or `kill -USR2` (docs/observability.md)
    flight_install(service="server")
    srv = ServerApp(
        uri=ctx.uri,
        jwt_secret=ctx.config.get("jwt_secret") or None,
        mailer=mailer_from_config(ctx.config.get("smtp")),
        store_url=ctx.config.get("store_url") or None,
    )
    user, generated = srv.ensure_root()
    if generated:
        # printed once at first start; operators change it immediately
        log.warning("root password (first start): %s", generated)
    return srv.serve(port=ctx.port, background=background)
