"""Input validation schemas for the REST resources.

Parity: the reference validates request bodies with marshmallow schemas
(SURVEY.md §2 item 5) — one per mutating endpoint, `validate()` raising
HTTP 400 via the web layer. Real marshmallow is preferred when installed;
environments without it get `_schemas_fallback`, a drop-in implementing
exactly the subset used here, so input validation (and its 400s) never
silently disappears with the dependency.
"""
from __future__ import annotations

from typing import Any

try:
    from marshmallow import EXCLUDE, Schema, ValidationError, fields, validate
except ModuleNotFoundError:  # pragma: no cover - exercised in CI env
    from vantage6_tpu.server._schemas_fallback import (  # type: ignore
        EXCLUDE, Schema, ValidationError, fields, validate,
    )

from vantage6_tpu.common.enums import TaskStatus
from vantage6_tpu.server.web import HTTPError


class _Base(Schema):
    class Meta:
        unknown = EXCLUDE


class TokenUserInput(_Base):
    username = fields.Str(required=True, validate=validate.Length(min=1))
    password = fields.Str(required=True)
    mfa_code = fields.Str(load_default=None)


class TokenNodeInput(_Base):
    api_key = fields.Str(required=True)


class TokenContainerInput(_Base):
    task_id = fields.Int(required=True)
    image = fields.Str(required=True)


class RefreshInput(_Base):
    refresh_token = fields.Str(required=True)


class RecoverLostInput(_Base):
    username = fields.Str(load_default=None)
    email = fields.Email(load_default=None)


class RecoverResetInput(_Base):
    reset_token = fields.Str(required=True)
    password = fields.Str(required=True, validate=validate.Length(min=8))


class Recover2FAResetInput(_Base):
    reset_token = fields.Str(required=True)


class UserInput(_Base):
    username = fields.Str(required=True, validate=validate.Length(min=1, max=128))
    password = fields.Str(required=True, validate=validate.Length(min=8))
    email = fields.Email(load_default=None)
    firstname = fields.Str(load_default="")
    lastname = fields.Str(load_default="")
    organization_id = fields.Int(load_default=None)
    roles = fields.List(fields.Int(), load_default=list)


class UserPatch(_Base):
    email = fields.Email(load_default=None)
    firstname = fields.Str(load_default=None)
    lastname = fields.Str(load_default=None)
    password = fields.Str(load_default=None, validate=validate.Length(min=8))
    roles = fields.List(fields.Int(), load_default=None)


class OrganizationInput(_Base):
    name = fields.Str(required=True, validate=validate.Length(min=1, max=128))
    address1 = fields.Str(load_default="")
    address2 = fields.Str(load_default="")
    zipcode = fields.Str(load_default="")
    country = fields.Str(load_default="")
    domain = fields.Str(load_default="")
    public_key = fields.Str(load_default="")


class OrganizationPatch(_Base):
    name = fields.Str(load_default=None)
    country = fields.Str(load_default=None)
    domain = fields.Str(load_default=None)
    public_key = fields.Str(load_default=None)


class CollaborationInput(_Base):
    name = fields.Str(required=True, validate=validate.Length(min=1, max=128))
    encrypted = fields.Bool(load_default=False)
    organization_ids = fields.List(fields.Int(), load_default=list)


class StudyInput(_Base):
    name = fields.Str(required=True)
    collaboration_id = fields.Int(required=True)
    organization_ids = fields.List(fields.Int(), load_default=list)


class NodeInput(_Base):
    name = fields.Str(load_default=None)
    organization_id = fields.Int(load_default=None)
    collaboration_id = fields.Int(required=True)
    station_index = fields.Int(load_default=None)


class DatabaseSpec(_Base):
    label = fields.Str(required=True)
    type = fields.Str(load_default=None)
    # sessions: type="session" reads the named dataframe from the node's
    # session store instead of a source database
    dataframe = fields.Str(load_default=None)


class SessionInput(_Base):
    name = fields.Str(required=True, validate=validate.Length(min=1))
    collaboration_id = fields.Int(required=True)
    study_id = fields.Int(load_default=None)
    scope = fields.Str(
        load_default="collaboration",
        validate=validate.OneOf(["own", "collaboration"]),
    )


class SessionDataframePatch(_Base):
    ready = fields.Bool(load_default=None)
    columns = fields.List(fields.Dict(keys=fields.Str()), load_default=None)


class TaskInput(_Base):
    name = fields.Str(load_default="task")
    description = fields.Str(load_default="")
    method = fields.Str(load_default="")
    image = fields.Str(required=True, validate=validate.Length(min=1))
    collaboration_id = fields.Int(required=True)
    study_id = fields.Int(load_default=None)
    # one entry per target organization: {"id": org_id, "input": "<blob>"}
    # (input is pre-encrypted per org when the collaboration is encrypted)
    organizations = fields.List(
        fields.Dict(keys=fields.Str()), required=True,
        validate=validate.Length(min=1),
    )
    databases = fields.List(fields.Nested(DatabaseSpec), load_default=list)
    # sessions
    session_id = fields.Int(load_default=None)
    store_as = fields.Str(load_default=None)
    # execution engine: "process" (node-local sandbox/inline run, default)
    # or "device" (one SPMD program over the nodes' global device mesh —
    # every targeted node joins the same collective computation)
    engine = fields.Str(
        load_default=None,
        validate=validate.OneOf(["process", "device"]),
    )


class PasswordChangeInput(_Base):
    current_password = fields.Str(required=True)
    new_password = fields.Str(required=True, validate=validate.Length(min=8))


class RunPatch(_Base):
    # a free-form status would later make TaskStatus(run.status) raise (500)
    # and Task.status() misclassify the run — reject it at the boundary
    status = fields.Str(
        load_default=None,
        validate=validate.OneOf([s.value for s in TaskStatus]),
    )
    result = fields.Str(load_default=None)
    log = fields.Str(load_default=None)
    started_at = fields.Float(load_default=None)
    finished_at = fields.Float(load_default=None)


class ClaimBatchInput(_Base):
    """POST /api/run/claim-batch — the node sweep/dispatch coalesced."""

    # explicit dispatch: fetch exactly these runs (event fast path);
    # absent -> sweep mode (all claimable pending runs for the node)
    run_ids = fields.List(fields.Int(), load_default=None)
    # runs the daemon is executing right now: never orphan-reset them and
    # don't re-deliver them in the pending listing
    exclude_run_ids = fields.List(fields.Int(), load_default=list)
    # also re-queue INITIALIZING/ACTIVE orphans (anti-entropy sweep mode)
    reset_orphans = fields.Bool(load_default=False)
    max = fields.Int(
        load_default=250, validate=validate.Range(min=1, max=250)
    )


class RunBatchItem(RunPatch):
    """One entry of PATCH /api/run/batch — RunPatch plus the target id."""

    id = fields.Int(required=True)


class RunBatchPatch(_Base):
    runs = fields.List(
        fields.Nested(RunBatchItem), required=True,
        validate=validate.Length(min=1, max=250),
    )


class RoleInput(_Base):
    name = fields.Str(required=True)
    description = fields.Str(load_default="")
    organization_id = fields.Int(load_default=None)
    rules = fields.List(fields.Int(), load_default=list)


class RolePatch(_Base):
    name = fields.Str(load_default=None)
    description = fields.Str(load_default=None)
    rules = fields.List(fields.Int(), load_default=None)


class PortInput(_Base):
    run_id = fields.Int(required=True)
    port = fields.Int(required=True, validate=validate.Range(min=1, max=65535))
    label = fields.Str(load_default="")


def load(schema: Schema, payload: Any) -> dict[str, Any]:
    """Validate `payload` against `schema`, raising HTTP 400 on failure."""
    if not isinstance(payload, dict):
        raise HTTPError(400, "body must be a JSON object")
    try:
        return schema.load(payload)
    except ValidationError as e:
        raise HTTPError(400, f"invalid input: {e.messages}") from None
