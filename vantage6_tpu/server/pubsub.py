"""Shared-store pub/sub: the EventHub surface over the storage backend.

One `EventHub` per process is exactly right for a single-replica server:
every emitter and every long-poller share its lock and condition variable.
With N replicas the emitting mutation can land on the OTHER replica, so
`GET /api/event?wait=` must observe a stream that spans processes. This
module provides `DbPubSub` — the same emit/fetch/collect/stats surface as
`events.EventHub`, backed by the `pubsub_event` table of the shared
storage backend (migration v7):

- **emit** appends a row (the AUTOINCREMENT seq is the global cursor —
  one ordered stream across all replicas), wakes this replica's local
  long-pollers immediately via the in-process condition variable, and
  prunes the table down to the bounded replay window, recording the
  eviction floor in `pubsub_meta` so `truncated` survives pruning.
- **collect** (the long-poll primitive) blocks on the local condition with
  a short ADAPTIVE re-check interval: a local emit wakes it instantly,
  a remote replica's emit is observed within ~`poll_floor`..`poll_ceil`
  seconds — dispatch latency stays event-propagation-shaped without a
  cross-process wakeup channel.
- **subscribers** (the websocket bridge) get local emits pushed inline;
  a lazily-started pump thread tails the table so remote emits reach
  them too.

Replica liveness rides the same store: `record_heartbeat` upserts this
replica's row in `replica_heartbeat`, `list_replicas` is what
`/api/health` and the watchdog's `replica_lapsed` rule read.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable

from vantage6_tpu.server.db import Database
from vantage6_tpu.server.events import Event

# a heartbeat older than this is a lapsed replica (crashed, partitioned,
# or stopped without deregistering) — /api/health and the watchdog agree
# on one number so the operator sees one story
REPLICA_STALE_AFTER = 15.0
# a heartbeat this old is an ancient departure, not worth reporting at all
REPLICA_FORGET_AFTER = 3600.0


class DbPubSub:
    """EventHub-compatible pub/sub over the shared `pubsub_event` table."""

    SHARED = True  # the app layer keys substrate decisions off this

    def __init__(
        self,
        db: Database,
        replica_id: str = "",
        buffer_size: int = 4096,
        poll_floor: float = 0.02,
        poll_ceil: float = 0.25,
    ):
        self.db = db
        self.replica_id = replica_id
        self.buffer_size = buffer_size
        self.poll_floor = poll_floor
        self.poll_ceil = poll_ceil
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # subscriber registry: each replica pushes to ITS websocket
        # replica-local: bridges; the tables carry events between replicas
        self._subs: dict[int, tuple[set[str] | None, Callable[[Event], None]]] = {}  # guarded-by: _lock
        self._next_sub = 1  # guarded-by: _lock
        self._emits = 0  # guarded-by: _lock  (prune cadence counter)
        # replica-local: the pump thread tails the SHARED stream for this
        # replica's push subscribers (started on first subscribe)
        self._pump: threading.Thread | None = None  # guarded-by: _lock
        self._pump_stop = threading.Event()
        self._closed = False

    # ------------------------------------------------------------------ emit
    def emit(self, name: str, data: dict[str, Any], room: str = "all") -> Event:
        ts = time.time()
        cur = self.db.execute(
            "INSERT INTO pubsub_event (name, room, data, ts) "
            "VALUES (?, ?, ?, ?)",
            [name, room, json.dumps(data), ts],
        )
        ev = Event(seq=int(cur.lastrowid), name=name, room=room,
                   data=data, ts=ts)
        with self._cond:
            self._emits += 1
            prune = self._emits % 64 == 0
            self._cond.notify_all()
            subs = list(self._subs.values())
        if prune:
            self._prune(ev.seq)
        # push to local subscribers inline (same contract as EventHub);
        # remote replicas' subscribers get it from their pump thread
        for rooms, cb in subs:
            if rooms is None or room in rooms or room == "all":
                try:
                    cb(ev)
                except Exception:
                    pass  # a broken subscriber must not break the emitter
        return ev

    def _prune(self, newest_seq: int) -> None:
        floor = newest_seq - self.buffer_size
        if floor <= 0:
            return
        try:
            cur = self.db.execute(
                "DELETE FROM pubsub_event WHERE seq <= ?", [floor]
            )
            if cur.rowcount:
                self.db.execute(
                    "INSERT INTO pubsub_meta (key, value) VALUES "
                    "('evicted_through', ?) ON CONFLICT(key) DO UPDATE "
                    "SET value = MAX(value, excluded.value)",
                    [floor],
                )
        except Exception:  # pragma: no cover - pruning must never 500 a poll
            pass

    # ------------------------------------------------------------- subscribe
    def subscribe(
        self,
        callback: Callable[[Event], None],
        rooms: list[str] | None = None,
    ) -> int:
        with self._lock:
            sid = self._next_sub
            self._next_sub += 1
            self._subs[sid] = (
                set(rooms) if rooms is not None else None, callback
            )
            if self._pump is None and not self._closed:
                self._pump_stop.clear()
                self._pump = threading.Thread(
                    target=self._pump_loop, name="dbpubsub-pump", daemon=True
                )
                self._pump.start()
            return sid

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._subs.pop(sid, None)

    def _pump_loop(self) -> None:
        """Tail the shared stream for this replica's push subscribers.
        Local emits were already delivered inline, but re-delivering them
        here would duplicate — so the pump starts at the CURRENT cursor
        and only forwards events it has not yet seen, which by
        construction excludes nothing remote and may re-include a local
        emit raced between cursor read and insert; subscribers (the ws
        bridge) treat events idempotently by seq."""
        cursor = self.cursor
        while not self._pump_stop.wait(self.poll_ceil):
            try:
                events = self.fetch(since=cursor)
            except Exception:
                continue  # backend momentarily busy — next tick retries
            for ev in events:
                cursor = max(cursor, ev.seq)
                with self._lock:
                    subs = list(self._subs.values())
                for rooms, cb in subs:
                    if rooms is None or ev.room in rooms or ev.room == "all":
                        try:
                            cb(ev)
                        except Exception:
                            pass

    # ---------------------------------------------------------------- replay
    def fetch(
        self, since: int = 0, rooms: list[str] | None = None
    ) -> list[Event]:
        return self._fetch(since, rooms, None)

    def _fetch(
        self,
        since: int,
        rooms: list[str] | None,
        names: set[str] | None,
    ) -> list[Event]:
        rows = self.db.query(
            "SELECT seq, name, room, data, ts FROM pubsub_event "
            "WHERE seq > ? ORDER BY seq",
            [since],
        )
        want = set(rooms) if rooms is not None else None
        out = []
        for r in rows:
            if want is not None and r["room"] not in want and r["room"] != "all":
                continue
            if names is not None and r["name"] not in names:
                continue
            out.append(Event(
                seq=r["seq"], name=r["name"], room=r["room"],
                data=json.loads(r["data"]) if r["data"] else {}, ts=r["ts"],
            ))
        return out

    def wait_for(
        self,
        since: int = 0,
        rooms: list[str] | None = None,
        timeout: float = 0.0,
        names: set[str] | None = None,
    ) -> list[Event]:
        events, _, _ = self.collect(since, rooms, timeout, names)
        return events

    def collect(
        self,
        since: int = 0,
        rooms: list[str] | None = None,
        timeout: float = 0.0,
        names: set[str] | None = None,
    ) -> tuple[list[Event], int, bool]:
        """(events, cursor, truncated), blocking up to `timeout` — the
        long-poll primitive. A LOCAL emit wakes the condition instantly;
        a REMOTE replica's emit is caught by the adaptive re-check (the
        wait interval starts at `poll_floor` and stretches toward
        `poll_ceil` the longer nothing arrives). The cursor snapshot is
        taken in the same query round as the event scan."""
        deadline = time.monotonic() + max(0.0, timeout)
        interval = self.poll_floor
        while True:
            events = self._fetch(since, rooms, names)
            cursor = self.cursor
            if events or time.monotonic() >= deadline:
                return events, max(cursor, since if not events else 0), \
                    since < self.evicted_through
            remaining = deadline - time.monotonic()
            with self._cond:
                self._cond.wait(min(interval, max(0.0, remaining)))
            interval = min(interval * 2, self.poll_ceil)

    def truncated(self, since: int) -> bool:
        return since < self.evicted_through

    @property
    def evicted_through(self) -> int:
        rows = self.db.query(
            "SELECT value FROM pubsub_meta WHERE key = 'evicted_through'"
        )
        return int(rows[0]["value"]) if rows else 0

    @property
    def cursor(self) -> int:
        rows = self.db.query("SELECT MAX(seq) AS c FROM pubsub_event")
        return int(rows[0]["c"] or 0)

    def stats(self) -> dict[str, int]:
        rows = self.db.query(
            "SELECT COUNT(*) AS n, MAX(seq) AS c FROM pubsub_event"
        )
        with self._lock:
            subs = len(self._subs)
        return {
            "buffer_len": int(rows[0]["n"]),
            "cursor": int(rows[0]["c"] or 0),
            "evicted_through": self.evicted_through,
            "subscribers": subs,
        }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._subs.clear()
            pump = self._pump
            self._pump = None
        self._pump_stop.set()
        if pump is not None:
            pump.join(timeout=2.0)


# ----------------------------------------------------------- replica status
def record_heartbeat(
    db: Database, replica_id: str, started_at: float
) -> None:
    """Upsert this replica's liveness row (called at startup and from the
    watchdog feed's periodic tick — no dedicated heartbeat thread)."""
    db.execute(
        "INSERT INTO replica_heartbeat "
        "(replica_id, pid, started_at, last_seen_at) VALUES (?, ?, ?, ?) "
        "ON CONFLICT(replica_id) DO UPDATE SET "
        "last_seen_at = excluded.last_seen_at, pid = excluded.pid",
        [replica_id, os.getpid(), started_at, time.time()],
    )


def drop_heartbeat(db: Database, replica_id: str) -> None:
    """Clean departure: a replica shutting down on purpose removes its row
    so it does not linger as 'lapsed' in every peer's health verdict."""
    db.execute(
        "DELETE FROM replica_heartbeat WHERE replica_id = ?", [replica_id]
    )


def list_replicas(db: Database, now: float | None = None) -> list[dict[str, Any]]:
    """Every recently-seen replica with its liveness verdict — the
    shared-store truth behind /api/health's `replicas` block and the
    watchdog's `replica_lapsed` evidence."""
    now = now if now is not None else time.time()
    out = []
    for r in db.query(
        "SELECT replica_id, pid, started_at, last_seen_at "
        "FROM replica_heartbeat ORDER BY replica_id"
    ):
        age = now - r["last_seen_at"]
        if age > REPLICA_FORGET_AFTER:
            continue
        out.append({
            "replica_id": r["replica_id"],
            "pid": r["pid"],
            "started_at": r["started_at"],
            "last_seen_at": r["last_seen_at"],
            "alive": age <= REPLICA_STALE_AFTER,
        })
    return out
