"""Control-plane entities.

Parity: vantage6-server ORM models (SURVEY.md §2 item 2) — `User`, `Node`,
`Organization`, `Collaboration`, `Study`, `Task`, `Run`, `Rule`, `Role`,
`Port` — with the same relationships (collaboration↔organizations m2m,
study⊂collaboration, task→runs fan-out, node = one org's agent in one
collaboration, user/role/rule RBAC graph).
"""
from __future__ import annotations

import hashlib
import os
import secrets
import time
from typing import Any

from vantage6_tpu.common.enums import TaskStatus
from vantage6_tpu.server.db import Database, LinkTable, Model, open_backend

# ------------------------------------------------------------------ entities


class Organization(Model):
    TABLE = "organization"
    COLUMNS = {
        "name": "str",
        "address1": "str",
        "address2": "str",
        "zipcode": "str",
        "country": "str",
        "domain": "str",
        "public_key": "str",  # base64 PEM for E2E payload encryption
    }

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "country": self.country,
            "domain": self.domain,
            "public_key": self.public_key or "",
            "collaborations": collaboration_member.lefts_for(self.id),
        }


class Collaboration(Model):
    TABLE = "collaboration"
    COLUMNS = {
        "name": "str",
        "encrypted": "bool",
    }

    def organization_ids(self) -> list[int]:
        return collaboration_member.rights_for(self.id)

    def add_organization(self, org: Organization) -> None:
        collaboration_member.add(self.id, org.id)

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "encrypted": bool(self.encrypted),
            "organizations": self.organization_ids(),
            "studies": [s.id for s in Study.list(collaboration_id=self.id)],
        }


class Study(Model):
    """A subset of a collaboration's organizations (reference: v4.5+)."""

    TABLE = "study"
    COLUMNS = {
        "name": "str",
        "collaboration_id": "int",
    }

    def organization_ids(self) -> list[int]:
        return study_member.rights_for(self.id)

    def add_organization(self, org: Organization) -> None:
        study_member.add(self.id, org.id)

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "collaboration": self.collaboration_id,
            "organizations": self.organization_ids(),
        }


# ------------------------------------------------------------- authenticate


def hash_password(password: str, salt: bytes | None = None) -> str:
    salt = salt or os.urandom(16)
    digest = hashlib.scrypt(
        password.encode(), salt=salt, n=2**14, r=8, p=1, dklen=32
    )
    return salt.hex() + "$" + digest.hex()


def check_password(password: str, hashed: str) -> bool:
    try:
        salt_hex, digest_hex = hashed.split("$")
    except (ValueError, AttributeError):
        return False
    redo = hashlib.scrypt(
        password.encode(),
        salt=bytes.fromhex(salt_hex),
        n=2**14,
        r=8,
        p=1,
        dklen=32,
    )
    return secrets.compare_digest(redo.hex(), digest_hex)


class User(Model):
    TABLE = "user"
    COLUMNS = {
        "username": "str",
        "password_hash": "str",
        "email": "str",
        "firstname": "str",
        "lastname": "str",
        "organization_id": "int",
        "failed_login_attempts": "int",
        "last_login_attempt": "float",
        "totp_secret": "str",  # set => MFA required
    }

    MAX_FAILED_ATTEMPTS = 5
    LOCKOUT_SECONDS = 60.0

    def set_password(self, password: str) -> None:
        self.password_hash = hash_password(password)

    def check_password(self, password: str) -> bool:
        return check_password(password, self.password_hash or "")

    def is_locked_out(self) -> bool:
        if (self.failed_login_attempts or 0) < self.MAX_FAILED_ATTEMPTS:
            return False
        return (
            time.time() - (self.last_login_attempt or 0.0)
            < self.LOCKOUT_SECONDS
        )

    def record_login(self, success: bool) -> None:
        self.last_login_attempt = time.time()
        self.failed_login_attempts = (
            0 if success else (self.failed_login_attempts or 0) + 1
        )
        self.save()

    # RBAC
    def role_ids(self) -> list[int]:
        return user_role.rights_for(self.id)

    def add_role(self, role: "Role") -> None:
        user_role.add(self.id, role.id)

    def rule_ids(self) -> set[int]:
        """All rules: direct extra rules + via roles.

        `_rules_cache` (set by the auth cache on token resolution) skips
        the 1+R link-table queries per permission check; role/rule
        mutations invalidate the auth cache, which drops the cached user
        object and this snapshot with it.
        """
        cached = getattr(self, "_rules_cache", None)
        if cached is not None:
            return set(cached)
        rules = set(user_rule.rights_for(self.id))
        for rid in self.role_ids():
            rules.update(role_rule.rights_for(rid))
        return rules

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "username": self.username,
            "email": self.email,
            "firstname": self.firstname,
            "lastname": self.lastname,
            "organization": {"id": self.organization_id},
            "roles": self.role_ids(),
        }


class Node(Model):
    """One organization's data-station agent inside one collaboration."""

    TABLE = "node"
    COLUMNS = {
        "name": "str",
        "api_key_hash": "str",
        "organization_id": "int",
        "collaboration_id": "int",
        "station_index": "int",  # TPU mapping: which sub-mesh slot
        "status": "str",  # "online" | "offline"
        "last_seen_at": "float",
    }

    @staticmethod
    def generate_api_key() -> str:
        return secrets.token_urlsafe(32)

    def set_api_key(self, api_key: str) -> None:
        self.api_key_hash = hashlib.sha256(api_key.encode()).hexdigest()

    def check_api_key(self, api_key: str) -> bool:
        return secrets.compare_digest(
            hashlib.sha256(api_key.encode()).hexdigest(),
            self.api_key_hash or "",
        )

    @classmethod
    def by_api_key(cls, api_key: str) -> "Node | None":
        h = hashlib.sha256(api_key.encode()).hexdigest()
        return cls.first(api_key_hash=h)

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "organization": {"id": self.organization_id},
            "collaboration": {"id": self.collaboration_id},
            "station_index": self.station_index,
            "status": self.status or "offline",
            "last_seen_at": self.last_seen_at,
        }


# ------------------------------------------------------------------- RBAC


class Rule(Model):
    """One permission atom: resource × scope × operation (SURVEY §2 item 4)."""

    TABLE = "rule"
    COLUMNS = {
        "name": "str",  # resource, e.g. "task"
        "scope": "str",  # own|organization|collaboration|global
        "operation": "str",  # view|create|edit|delete|send|receive
    }

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "scope": self.scope,
            "operation": self.operation,
        }


class Role(Model):
    TABLE = "role"
    COLUMNS = {
        "name": "str",
        "description": "str",
        "organization_id": "int",  # NULL => default/global role
    }

    def rule_ids(self) -> list[int]:
        return role_rule.rights_for(self.id)

    def add_rule(self, rule: Rule) -> None:
        role_rule.add(self.id, rule.id)

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "description": self.description,
            "organization": (
                {"id": self.organization_id} if self.organization_id else None
            ),
            "rules": self.rule_ids(),
        }


# ------------------------------------------------------------------- tasks


class Task(Model):
    TABLE = "task"
    COLUMNS = {
        "name": "str",
        "description": "str",
        "image": "str",
        "method": "str",
        "collaboration_id": "int",
        "study_id": "int",
        "parent_id": "int",
        "init_org_id": "int",
        "init_user_id": "int",
        "databases": "json",
        "job_id": "int",  # groups a task tree (reference: run_id/job_id)
        "session_id": "int",  # sessions: task runs inside this workspace
        "store_as": "str",    # sessions: nodes persist the run's returned
                              # dataframe under this handle
        "engine": "str",      # "process" (default: node sandbox/inline) or
                              # "device": the run executes as ONE SPMD
                              # program over the nodes' global device mesh
        # distributed tracing (runtime.tracing): the creating request's
        # trace context. trace_id groups every span of this task's
        # federated round; traceparent is the full W3C header the daemons
        # parent their claim/exec/report spans on.
        "trace_id": "str",
        "traceparent": "str",
    }

    def runs(self) -> list["TaskRun"]:
        return TaskRun.list(task_id=self.id)

    def status(self) -> str:
        """Aggregate status rollup over runs (same order as the runtime)."""
        runs = self.runs()
        if not runs:
            return TaskStatus.PENDING.value
        statuses = {r.status for r in runs}
        for bad in (
            TaskStatus.KILLED,
            TaskStatus.NOT_ALLOWED,
            TaskStatus.NO_IMAGE,
            TaskStatus.CRASHED,
            TaskStatus.FAILED,
        ):
            if bad.value in statuses:
                return bad.value
        if statuses == {TaskStatus.COMPLETED.value}:
            return TaskStatus.COMPLETED.value
        if (
            TaskStatus.ACTIVE.value in statuses
            or TaskStatus.INITIALIZING.value in statuses
        ):
            return TaskStatus.ACTIVE.value
        return TaskStatus.PENDING.value

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "description": self.description,
            "image": self.image,
            "method": self.method,
            "status": self.status(),
            "collaboration": {"id": self.collaboration_id},
            "study": {"id": self.study_id} if self.study_id else None,
            "parent": {"id": self.parent_id} if self.parent_id else None,
            "init_org": {"id": self.init_org_id},
            "init_user": {"id": self.init_user_id},
            "job_id": self.job_id,
            "databases": self.databases or [],
            "session": {"id": self.session_id} if self.session_id else None,
            "store_as": self.store_as or None,
            "engine": self.engine or "process",
            "trace_id": self.trace_id or None,
            "traceparent": self.traceparent or None,
            "runs": [r.id for r in self.runs()],
        }


class TaskRun(Model):
    """One organization's run of a task (reference: `Run`, né `Result`)."""

    TABLE = "run"
    COLUMNS = {
        "task_id": "int",
        "organization_id": "int",
        "node_id": "int",
        "status": "str",
        "input": "str",  # (encrypted) serialized input for THIS org
        "result": "str",  # (encrypted) serialized result
        "log": "str",
        "assigned_at": "float",
        "started_at": "float",
        "finished_at": "float",
    }

    def to_dict(self, include_result: bool = True) -> dict[str, Any]:
        d = {
            "id": self.id,
            "task": {"id": self.task_id},
            "organization": {"id": self.organization_id},
            "node": {"id": self.node_id},
            "status": self.status,
            "input": self.input,
            "log": self.log,
            "assigned_at": self.assigned_at,
            "started_at": self.started_at,
            "finished_at": self.finished_at,
        }
        if include_result:
            d["result"] = self.result
        return d


class Session(Model):
    """A workspace persisting named dataframes AT THE NODES between tasks
    (reference: v4.7+ 'sessions' — data-extraction tasks materialize
    dataframes once; later preprocessing/compute tasks reuse them without
    re-reading the source databases). The server stores ONLY bookkeeping;
    dataframe content never leaves its node."""

    TABLE = "session"
    COLUMNS = {
        "name": "str",
        "collaboration_id": "int",
        "study_id": "int",
        "owner_id": "int",  # creating user
        "scope": "str",     # "own" | "collaboration" — who may use it
    }

    def dataframes(self) -> list["SessionDataframe"]:
        return SessionDataframe.list(session_id=self.id)

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "collaboration": {"id": self.collaboration_id},
            "study": {"id": self.study_id} if self.study_id else None,
            "owner": {"id": self.owner_id},
            "scope": self.scope or "collaboration",
            "created_at": self.created_at,
            "dataframes": [d.to_dict() for d in self.dataframes()],
        }


class SessionDataframe(Model):
    """Bookkeeping for one named dataframe in a session: which task last
    (re)built it, whether every node has materialized it, and its column
    metadata — the content itself lives only in the nodes' session stores."""

    TABLE = "session_dataframe"
    COLUMNS = {
        "session_id": "int",
        "handle": "str",
        "last_task_id": "int",
        "ready": "bool",
        "columns": "json",  # [{name, dtype}] as reported by nodes
    }

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "session": {"id": self.session_id},
            "handle": self.handle,
            "last_task": (
                {"id": self.last_task_id} if self.last_task_id else None
            ),
            "ready": bool(self.ready),
            "columns": self.columns or [],
        }


class Port(Model):
    """An exposed algorithm port (reference: VPN inter-container traffic)."""

    TABLE = "port"
    COLUMNS = {
        "run_id": "int",
        "port": "int",
        "label": "str",
    }

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "run": {"id": self.run_id},
            "port": self.port,
            "label": self.label,
        }


# --------------------------------------------------------------- link tables

collaboration_member = LinkTable(
    "collaboration_organization", "collaboration_id", "organization_id"
)
study_member = LinkTable("study_organization", "study_id", "organization_id")
user_role = LinkTable("user_role", "user_id", "role_id")
role_rule = LinkTable("role_rule", "role_id", "rule_id")
user_rule = LinkTable("user_rule", "user_id", "rule_id")

# replica-local: code-derived constant, identical on every replica
ALL_MODELS: list[type[Model]] = [
    Organization,
    Collaboration,
    Study,
    User,
    Node,
    Rule,
    Role,
    Task,
    TaskRun,
    Port,
    Session,
    SessionDataframe,
]
# replica-local: code-derived constant, identical on every replica
ALL_LINKS = [collaboration_member, study_member, user_role, role_rule, user_rule]


# how many ServerApps share the current Model.db binding (SHARED backends
# allow in-process replicas over one store; see init/release)
_BINDING_REFS = 0  # replica-local: refcount of THIS process's db binding


def init(uri: str = "sqlite:///:memory:", replace: bool = False) -> Database:
    """Bind the database and migrate the schema (alembic-equivalent).

    One process hosts ONE control-plane database per model hierarchy
    (`Model.db` is class-level state); a second `init` without closing the
    first would silently redirect live handlers, so it raises instead.
    Services needing their own DB in-process (the algorithm store) use their
    own `Model` subclass hierarchy with its own `db` binding.

    Exception: a SHARED backend (``sqlite+wal``) re-initialised with the
    SAME uri returns the existing binding refcounted — two in-process
    server replicas over one store are exactly the multi-replica topology
    the backend exists for. `release()` unbinds only when the last
    holder lets go.
    """
    global _BINDING_REFS
    if Model.db is not None and not replace:
        if Model.db.SHARED and getattr(Model.db, "uri", None) == uri:
            _BINDING_REFS += 1
            return Model.db
        raise RuntimeError(
            "server models already bound to a database; close it and set "
            "Model.db = None (or pass replace=True) before rebinding"
        )
    db = open_backend(uri)
    Model.db = db
    _BINDING_REFS = 1
    for m in ALL_MODELS:
        m.ensure_schema()
    for link in ALL_LINKS:
        link.ensure_schema()
    # versioned upgrades on top of the additive DDL (constraints, backfills,
    # indexes — recorded in schema_version; see server.migrations)
    from vantage6_tpu.server import migrations

    migrations.migrate(db)
    return db


def release(db: Database) -> None:
    """Drop one holder's claim on the binding `init` returned. The binding
    (and connection) is closed only when the LAST in-process holder
    releases — a shared-backend replica closing must not unbind the other
    replica's live handlers mid-request."""
    global _BINDING_REFS
    if Model.db is not db:
        # already rebound (tests replace=True) — close the orphan quietly
        db.close()
        return
    _BINDING_REFS -= 1
    if _BINDING_REFS <= 0:
        _BINDING_REFS = 0
        db.close()
        Model.db = None
