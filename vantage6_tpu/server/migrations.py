"""Versioned schema migrations (reference: alembic; SURVEY.md §2 item 8).

Two-layer upgrade story, mirroring what alembic gives the reference:

1. **Additive DDL** — `Model.ensure_schema` creates missing tables/columns on
   every start (covers the common "new field" case with zero ceremony).
2. **Versioned migrations** (this module) — ordered, recorded, run-once
   steps for everything additive DDL cannot express: constraints, indexes,
   data backfills, renames. Each applied version is a row in
   ``schema_version`` (version, description, applied_at), so an operator can
   audit exactly which upgrades a database has seen, and an old database
   opened by a new server is upgraded deterministically, in order.

Writing a migration: append ``(N, "description", fn)`` to ``MIGRATIONS``
with N = previous + 1. ``fn(db)`` runs after ``ensure_schema`` (all
tables/columns exist) and must be safe on both fresh and populated
databases. Never reorder or edit an applied migration — append a new one.
"""
from __future__ import annotations

import time
from typing import Callable

from vantage6_tpu.common.log import setup_logging
from vantage6_tpu.server.db import Database

log = setup_logging("vantage6_tpu/server.migrations")


def _m1_baseline(db: Database) -> None:
    """v1: baseline — tables come from ensure_schema's additive DDL."""


def _m2_unique_username(db: Database) -> None:
    """v2: usernames must be unique (login identity). Pre-existing
    duplicates are disambiguated with an id suffix, keeping the OLDEST
    spelling intact (it is the one whose owner expects to log in)."""
    rows = db.query(
        "SELECT username, COUNT(*) AS n FROM user "
        "GROUP BY username HAVING n > 1"
    )
    for r in rows:
        dupes = db.query(
            "SELECT id FROM user WHERE username = ? ORDER BY id",
            [r["username"]],
        )
        for row in dupes[1:]:
            db.execute(
                "UPDATE user SET username = username || '_' || id "
                "WHERE id = ?",
                [row["id"]],
            )
    db.execute(
        "CREATE UNIQUE INDEX IF NOT EXISTS uq_user_username "
        "ON user(username)"
    )


def _m3_unique_org_name(db: Database) -> None:
    """v3: organization names are unique (the reference enforces the same;
    task targeting and node naming key on them)."""
    rows = db.query(
        "SELECT name, COUNT(*) AS n FROM organization "
        "GROUP BY name HAVING n > 1"
    )
    for r in rows:
        dupes = db.query(
            "SELECT id FROM organization WHERE name = ? ORDER BY id",
            [r["name"]],
        )
        for row in dupes[1:]:
            db.execute(
                "UPDATE organization SET name = name || ' (' || id || ')' "
                "WHERE id = ?",
                [row["id"]],
            )
    db.execute(
        "CREATE UNIQUE INDEX IF NOT EXISTS uq_organization_name "
        "ON organization(name)"
    )


def _m4_hot_query_indexes(db: Database) -> None:
    """v4: indexes for the hottest control-plane queries — node run polling
    by status and container job-tree scoping by job_id."""
    db.execute(
        "CREATE INDEX IF NOT EXISTS idx_run_status ON run(status)"
    )
    db.execute(
        "CREATE INDEX IF NOT EXISTS idx_task_job_id ON task(job_id)"
    )
    db.execute(
        "CREATE UNIQUE INDEX IF NOT EXISTS uq_node_org_collab "
        "ON node(organization_id, collaboration_id)"
    )


def _m5_dispatch_indexes(db: Database) -> None:
    """v5: composite indexes for the control-plane fast path — the batched
    claim sweep selects runs by (organization, status) and by
    (node, status); the single-column idx_run_status from v4 still forces
    a scan over every completed run of a busy org."""
    db.execute(
        "CREATE INDEX IF NOT EXISTS idx_run_org_status "
        "ON run(organization_id, status)"
    )
    db.execute(
        "CREATE INDEX IF NOT EXISTS idx_run_node_status "
        "ON run(node_id, status)"
    )


def _m6_trace_metadata(db: Database) -> None:
    """v6: distributed-tracing task metadata. The columns themselves
    (task.trace_id, task.traceparent) arrive via additive DDL; this adds
    the lookup index so "every task of trace X" — the trace_view /
    observability join — is not a table scan on a busy server."""
    db.execute(
        "CREATE INDEX IF NOT EXISTS idx_task_trace_id ON task(trace_id)"
    )


def _m7_replica_tables(db: Database) -> None:
    """v7: shared-store substrate for N server replicas. Four tables:
    the cross-replica event stream (`pubsub_event` — the DbPubSub ring,
    append + bounded prune), its watermark (`pubsub_meta` — eviction
    floor so truncation survives the pruner), replica liveness
    (`replica_heartbeat` — /api/health and the watchdog's replica view),
    and the learning plane keyed by (task, round) (`learning_round` — a
    round trajectory whose per-round subtasks land on different replicas
    still reads back as ONE history)."""
    db.execute(
        "CREATE TABLE IF NOT EXISTS pubsub_event ("
        "seq INTEGER PRIMARY KEY AUTOINCREMENT, "
        "name TEXT NOT NULL, room TEXT NOT NULL, "
        "data TEXT, ts REAL NOT NULL)"
    )
    db.execute(
        "CREATE TABLE IF NOT EXISTS pubsub_meta ("
        "key TEXT PRIMARY KEY, value INTEGER NOT NULL)"
    )
    db.execute(
        "CREATE TABLE IF NOT EXISTS replica_heartbeat ("
        "replica_id TEXT PRIMARY KEY, pid INTEGER, "
        "started_at REAL NOT NULL, last_seen_at REAL NOT NULL)"
    )
    db.execute(
        "CREATE TABLE IF NOT EXISTS learning_round ("
        "task_key TEXT NOT NULL, round INTEGER NOT NULL, "
        "data TEXT NOT NULL, ts REAL NOT NULL, "
        "PRIMARY KEY (task_key, round))"
    )


def _m8_fleet_tables(db: Database) -> None:
    """v8: fleet telemetry fabric. Two append-only tables behind
    `POST /api/telemetry` / `GET /api/fleet` (server/fleet.py):
    `fleet_metric` — timestamped metric samples, one row per (source,
    series) per pushed snapshot, CAS-free appends pruned by the
    retention floor; `fleet_event` — flight-note/alert deltas riding
    the same pushes. Both are keyed for the two hot reads: the census
    ("latest row per source+series") and the SLO engine's windowed
    series scan ("all samples of series X since T")."""
    db.execute(
        "CREATE TABLE IF NOT EXISTS fleet_metric ("
        "id INTEGER PRIMARY KEY AUTOINCREMENT, "
        "source TEXT NOT NULL, service TEXT, seq INTEGER, "
        "name TEXT NOT NULL, kind TEXT, value REAL, ts REAL NOT NULL)"
    )
    db.execute(
        "CREATE INDEX IF NOT EXISTS idx_fleet_metric_name_ts "
        "ON fleet_metric(name, ts)"
    )
    db.execute(
        "CREATE INDEX IF NOT EXISTS idx_fleet_metric_source "
        "ON fleet_metric(source, name, id)"
    )
    db.execute(
        "CREATE TABLE IF NOT EXISTS fleet_event ("
        "id INTEGER PRIMARY KEY AUTOINCREMENT, "
        "source TEXT NOT NULL, service TEXT, kind TEXT NOT NULL, "
        "ts REAL NOT NULL, data TEXT)"
    )
    db.execute(
        "CREATE INDEX IF NOT EXISTS idx_fleet_event_ts ON fleet_event(ts)"
    )


# replica-local: code-derived constant, identical on every replica
MIGRATIONS: list[tuple[int, str, Callable[[Database], None]]] = [
    (1, "baseline schema", _m1_baseline),
    (2, "unique index on user.username (+dedupe)", _m2_unique_username),
    (3, "unique index on organization.name (+dedupe)", _m3_unique_org_name),
    (4, "hot-query indexes: run.status, task.job_id, node uniqueness",
     _m4_hot_query_indexes),
    (5, "dispatch-path indexes: run(org,status), run(node,status)",
     _m5_dispatch_indexes),
    (6, "tracing metadata index: task(trace_id)", _m6_trace_metadata),
    (7, "replica tables: pubsub event stream, heartbeats, learning rounds",
     _m7_replica_tables),
    (8, "fleet telemetry tables: cross-host metric samples + event deltas",
     _m8_fleet_tables),
]

SCHEMA_VERSION = MIGRATIONS[-1][0]


def ensure_version_table(db: Database) -> None:
    db.execute(
        "CREATE TABLE IF NOT EXISTS schema_version ("
        "version INTEGER PRIMARY KEY, "
        "description TEXT NOT NULL, "
        "applied_at REAL NOT NULL)"
    )


def applied_versions(db: Database) -> list[int]:
    ensure_version_table(db)
    return [
        r["version"]
        for r in db.query("SELECT version FROM schema_version ORDER BY version")
    ]


def current_version(db: Database) -> int:
    versions = applied_versions(db)
    return versions[-1] if versions else 0


def migrate(db: Database) -> list[int]:
    """Apply every unapplied migration in order; returns versions applied
    now. Raises if the database is AHEAD of this code (downgrades are not
    supported — run a matching or newer server)."""
    ensure_version_table(db)
    done = set(applied_versions(db))
    ahead = [v for v in done if v > SCHEMA_VERSION]
    if ahead:
        raise RuntimeError(
            f"database schema version {max(ahead)} is newer than this "
            f"server's {SCHEMA_VERSION} — upgrade the server, downgrades "
            "are not supported"
        )
    applied_now = []
    for version, description, fn in MIGRATIONS:
        if version in done:
            continue
        fn(db)
        db.execute(
            "INSERT INTO schema_version (version, description, applied_at) "
            "VALUES (?, ?, ?)",
            [version, description, time.time()],
        )
        log.info("schema migrated to v%d: %s", version, description)
        applied_now.append(version)
    return applied_now
