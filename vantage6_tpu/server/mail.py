"""Outbound mail for account recovery (reference: SMTP password reset,
SURVEY.md §2 item 7).

The reference server sends password-reset emails via configured SMTP. This
image has no network, so the mailer is PLUGGABLE: `ServerApp(mailer=...)`
takes anything with ``send(to, subject, body)``. The default `LogMailer`
logs and records messages (what tests and dev networks read); `SMTPMailer`
is the production implementation for deployments with a mail host.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Protocol

from vantage6_tpu.common.log import setup_logging

log = setup_logging("vantage6_tpu/server.mail")


class Mailer(Protocol):  # pragma: no cover - typing only
    def send(self, to: str, subject: str, body: str) -> None: ...


@dataclasses.dataclass
class Message:
    to: str
    subject: str
    body: str


class LogMailer:
    """Default: log + retain messages in memory (dev/test deployments)."""

    def __init__(self) -> None:
        # replica-local: dev/test capture buffer, never authoritative
        self.sent: list[Message] = []

    def send(self, to: str, subject: str, body: str) -> None:
        self.sent.append(Message(to=to, subject=subject, body=body))
        log.info("mail to %s: %s", to, subject)


class SMTPMailer:
    """SMTP delivery (reference parity); construct from server config
    ``smtp: {host, port, username, password, use_tls, from}``."""

    def __init__(
        self,
        host: str,
        port: int = 587,
        username: str = "",
        password: str = "",
        use_tls: bool = True,
        from_addr: str = "noreply@vantage6",
    ):
        self.host, self.port = host, port
        self.username, self.password = username, password
        self.use_tls = use_tls
        self.from_addr = from_addr

    def send(self, to: str, subject: str, body: str) -> None:
        import smtplib
        from email.message import EmailMessage

        msg = EmailMessage()
        msg["From"], msg["To"], msg["Subject"] = self.from_addr, to, subject
        msg.set_content(body)
        with smtplib.SMTP(self.host, self.port, timeout=30) as smtp:
            if self.use_tls:
                smtp.starttls()
            if self.username:
                smtp.login(self.username, self.password)
            smtp.send_message(msg)


def mailer_from_config(cfg: dict[str, Any] | None) -> LogMailer | SMTPMailer:
    if not cfg or not cfg.get("host"):
        return LogMailer()
    return SMTPMailer(
        host=cfg["host"],
        port=int(cfg.get("port", 587)),
        username=cfg.get("username", ""),
        password=cfg.get("password", ""),
        use_tls=bool(cfg.get("use_tls", True)),
        from_addr=cfg.get("from", "noreply@vantage6"),
    )
