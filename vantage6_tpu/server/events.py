"""Realtime event hub: rooms, push subscriptions, cursor-based catch-up.

Parity: the reference's SocketIO namespace (SURVEY.md §2 item 6) — rooms per
collaboration and per node carry `node-online/offline`, `task-created`,
`status-update`, `kill`, `ping` events between server, nodes and UI. Here
the hub is transport-neutral: in-process subscribers get push callbacks
(same-host federations, tests), remote nodes get the events over a
websocket bridge or by cursor catch-up (`fetch(since=...)` — how a
reconnecting node re-syncs its missed queue, the reference's
`sync_task_queue_with_server`).
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Any, Callable

# canonical event names (reference SocketIO events)
NODE_ONLINE = "node-online"
NODE_OFFLINE = "node-offline"
TASK_CREATED = "task-created"
STATUS_UPDATE = "status-update"
KILL_TASK = "kill-task"
PING = "ping"
SESSION_DELETED = "session-deleted"  # nodes drop their local session store
# server-internal: one replica's cache invalidation, applied by the others
# (data: {"entity": user|node|role|rule|collaboration, "id": int|None});
# rides the shared event stream in REPLICA_ROOM, which no client's room
# set ever includes, so daemons/UIs never see it
CACHE_INVALIDATE = "cache-invalidate"

# server-to-server room for CACHE_INVALIDATE (never granted to clients)
REPLICA_ROOM = "replicas"


def collaboration_room(collaboration_id: int) -> str:
    return f"collaboration_{collaboration_id}"


def node_room(node_id: int) -> str:
    return f"node_{node_id}"


@dataclasses.dataclass(frozen=True)
class Event:
    seq: int
    name: str
    room: str
    data: dict[str, Any]
    ts: float

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class EventHub:
    """Thread-safe pub/sub with a bounded replay buffer.

    Blocking consumers (the REST long-poll, `wait_for`) ride a condition
    variable notified on every emit, so a waiting daemon/client wakes the
    moment an event lands instead of on its next polling sweep. Eviction
    is tracked (`evicted_through`): a `fetch(since=...)` whose cursor
    predates the oldest buffered event has MISSED events the buffer can no
    longer replay — consumers must resync from primary state, and the
    REST layer surfaces this as `truncated` so they know to.
    """

    def __init__(self, buffer_size: int = 4096):
        self.buffer_size = buffer_size
        # EventHub is the SINGLE-replica hub; shared-store deployments
        # replica-local: swap in DbPubSub (app.py selects on db.SHARED)
        self._buffer: deque[Event] = deque(maxlen=buffer_size)  # guarded-by: _lock
        self._seq = itertools.count(1)  # replica-local: see _buffer
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # seq of the newest event the bounded buffer has DROPPED (0: none)
        self._evicted_through = 0  # guarded-by: _lock
        # subscriber id -> (rooms | None for all, callback)
        # replica-local: push subscribers live in THIS process
        self._subs: dict[int, tuple[set[str] | None, Callable[[Event], None]]] = {}  # guarded-by: _lock
        self._sub_ids = itertools.count(1)  # replica-local: see _subs

    # ------------------------------------------------------------------ emit
    def emit(self, name: str, data: dict[str, Any], room: str = "all") -> Event:
        with self._lock:
            ev = Event(
                seq=next(self._seq), name=name, room=room,
                data=data, ts=time.time(),
            )
            if len(self._buffer) == self.buffer_size:
                # deque(maxlen) silently drops the head; remember how far
                # the replay window has moved so fetch() can report gaps
                self._evicted_through = self._buffer[0].seq
            self._buffer.append(ev)
            self._cond.notify_all()
            subs = list(self._subs.values())
        for rooms, cb in subs:
            if rooms is None or room in rooms or room == "all":
                try:
                    cb(ev)
                except Exception:
                    pass  # a broken subscriber must not break the emitter
        return ev

    # ------------------------------------------------------------- subscribe
    def subscribe(
        self,
        callback: Callable[[Event], None],
        rooms: list[str] | None = None,
    ) -> int:
        with self._lock:
            sid = next(self._sub_ids)
            self._subs[sid] = (set(rooms) if rooms is not None else None, callback)
            return sid

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._subs.pop(sid, None)

    # ---------------------------------------------------------------- replay
    def fetch(
        self, since: int = 0, rooms: list[str] | None = None
    ) -> list[Event]:
        """Events after sequence `since`, filtered to `rooms` (None = all).

        A node that reconnects calls this with its last-seen cursor to drain
        whatever it missed.
        """
        with self._lock:
            return self._fetch_locked(since, rooms, None)

    def _fetch_locked(
        self,
        since: int,
        rooms: list[str] | None,
        names: set[str] | None,
    ) -> list[Event]:
        want = set(rooms) if rooms is not None else None
        return [
            ev
            for ev in self._buffer
            if ev.seq > since
            and (want is None or ev.room in want or ev.room == "all")
            and (names is None or ev.name in names)
        ]

    def wait_for(
        self,
        since: int = 0,
        rooms: list[str] | None = None,
        timeout: float = 0.0,
        names: set[str] | None = None,
    ) -> list[Event]:
        """`fetch`, but blocks up to `timeout` seconds until at least one
        matching event exists — the long-poll primitive. Returns [] on
        timeout. Wakes IMMEDIATELY on a matching emit (condition variable),
        so dispatch latency is event propagation, not polling cadence.

        `names` narrows the wake set: a daemon only dispatches on
        task-created/kill-task/session-deleted, and without the filter
        every status-update in its collaboration would wake all N daemons
        — an N× request amplification per event under load.
        """
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cond:
            while True:
                events = self._fetch_locked(since, rooms, names)
                if events:
                    return events
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)

    def collect(
        self,
        since: int = 0,
        rooms: list[str] | None = None,
        timeout: float = 0.0,
        names: set[str] | None = None,
    ) -> tuple[list[Event], int, bool]:
        """ATOMIC (events, cursor, truncated) snapshot, blocking like
        `wait_for`. The cursor is read under the SAME lock as the event
        scan, so it covers exactly the events visible to this snapshot —
        reading `hub.cursor` after a separate fetch would cover an event
        emitted in the gap without delivering it, and a cursor-following
        consumer (the daemon) would then skip it forever."""
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cond:
            while True:
                events = self._fetch_locked(since, rooms, names)
                if events:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            cursor = self._buffer[-1].seq if self._buffer else 0
            return events, cursor, since < self._evicted_through

    def truncated(self, since: int) -> bool:
        """Whether a consumer at cursor `since` has missed events the
        bounded buffer can no longer replay (buffer overflow)."""
        with self._lock:
            return since < self._evicted_through

    @property
    def evicted_through(self) -> int:
        with self._lock:
            return self._evicted_through

    @property
    def cursor(self) -> int:
        """Sequence number of the newest event (0 when empty)."""
        with self._lock:
            return self._buffer[-1].seq if self._buffer else 0

    def stats(self) -> dict[str, int]:
        """One atomic snapshot for the telemetry registry: buffer fill,
        cursor position, eviction watermark, subscriber count."""
        with self._lock:
            return {
                "buffer_len": len(self._buffer),
                "cursor": self._buffer[-1].seq if self._buffer else 0,
                "evicted_through": self._evicted_through,
                "subscribers": len(self._subs),
            }
