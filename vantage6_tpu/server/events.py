"""Realtime event hub: rooms, push subscriptions, cursor-based catch-up.

Parity: the reference's SocketIO namespace (SURVEY.md §2 item 6) — rooms per
collaboration and per node carry `node-online/offline`, `task-created`,
`status-update`, `kill`, `ping` events between server, nodes and UI. Here
the hub is transport-neutral: in-process subscribers get push callbacks
(same-host federations, tests), remote nodes get the events over a
websocket bridge or by cursor catch-up (`fetch(since=...)` — how a
reconnecting node re-syncs its missed queue, the reference's
`sync_task_queue_with_server`).
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Any, Callable

# canonical event names (reference SocketIO events)
NODE_ONLINE = "node-online"
NODE_OFFLINE = "node-offline"
TASK_CREATED = "task-created"
STATUS_UPDATE = "status-update"
KILL_TASK = "kill-task"
PING = "ping"
SESSION_DELETED = "session-deleted"  # nodes drop their local session store


def collaboration_room(collaboration_id: int) -> str:
    return f"collaboration_{collaboration_id}"


def node_room(node_id: int) -> str:
    return f"node_{node_id}"


@dataclasses.dataclass(frozen=True)
class Event:
    seq: int
    name: str
    room: str
    data: dict[str, Any]
    ts: float

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class EventHub:
    """Thread-safe pub/sub with a bounded replay buffer."""

    def __init__(self, buffer_size: int = 4096):
        self._buffer: deque[Event] = deque(maxlen=buffer_size)
        self._seq = itertools.count(1)
        self._lock = threading.RLock()
        # subscriber id -> (rooms | None for all, callback)
        self._subs: dict[int, tuple[set[str] | None, Callable[[Event], None]]] = {}
        self._sub_ids = itertools.count(1)

    # ------------------------------------------------------------------ emit
    def emit(self, name: str, data: dict[str, Any], room: str = "all") -> Event:
        with self._lock:
            ev = Event(
                seq=next(self._seq), name=name, room=room,
                data=data, ts=time.time(),
            )
            self._buffer.append(ev)
            subs = list(self._subs.values())
        for rooms, cb in subs:
            if rooms is None or room in rooms or room == "all":
                try:
                    cb(ev)
                except Exception:
                    pass  # a broken subscriber must not break the emitter
        return ev

    # ------------------------------------------------------------- subscribe
    def subscribe(
        self,
        callback: Callable[[Event], None],
        rooms: list[str] | None = None,
    ) -> int:
        with self._lock:
            sid = next(self._sub_ids)
            self._subs[sid] = (set(rooms) if rooms is not None else None, callback)
            return sid

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._subs.pop(sid, None)

    # ---------------------------------------------------------------- replay
    def fetch(
        self, since: int = 0, rooms: list[str] | None = None
    ) -> list[Event]:
        """Events after sequence `since`, filtered to `rooms` (None = all).

        A node that reconnects calls this with its last-seen cursor to drain
        whatever it missed.
        """
        with self._lock:
            want = set(rooms) if rooms is not None else None
            return [
                ev
                for ev in self._buffer
                if ev.seq > since
                and (want is None or ev.room in want or ev.room == "all")
            ]

    @property
    def cursor(self) -> int:
        """Sequence number of the newest event (0 when empty)."""
        with self._lock:
            return self._buffer[-1].seq if self._buffer else 0
