"""Minimal WSGI web framework for the control-plane services.

Parity: the reference's servers are Flask apps (SURVEY.md §2 items 1, 3);
Flask is not in this image, so this module supplies the slice of it the
control plane needs: routing with typed path params, JSON request/response,
auth hooks, error handling, a threaded dev server, and an in-process test
client (Flask's `app.test_client()` equivalent — SURVEY.md §4 test strategy).
"""
from __future__ import annotations

import io
import json
import re
import threading
import time
import traceback
from typing import Any, Callable
from urllib.parse import parse_qs
from socketserver import ThreadingMixIn
from wsgiref.simple_server import WSGIRequestHandler, WSGIServer, make_server

from vantage6_tpu.common.log import setup_logging
from vantage6_tpu.common.telemetry import REGISTRY
from vantage6_tpu.runtime.tracing import TRACER, parse_traceparent

log = setup_logging("vantage6_tpu/web")

# process-wide HTTP telemetry (covers every App in the process: the
# control-plane server AND the node proxy relay)
_HTTP_REQUESTS = REGISTRY.counter("v6t_http_requests_total")
_HTTP_ERRORS = REGISTRY.counter("v6t_http_errors_total")
_HTTP_SECONDS = REGISTRY.histogram("v6t_http_request_seconds")


_UNPARSED = object()


class HTTPError(Exception):
    def __init__(self, status: int, msg: str = ""):
        super().__init__(msg)
        self.status = status
        self.msg = msg or {
            400: "bad request",
            401: "unauthorized",
            403: "forbidden",
            404: "not found",
            409: "conflict",
        }.get(status, "error")


class Request:
    def __init__(
        self,
        method: str,
        path: str,
        query: dict[str, list[str]],
        headers: dict[str, str],
        body: bytes,
    ):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.identity: dict[str, Any] | None = None  # set by auth middleware
        self._json: Any = _UNPARSED

    @property
    def json(self) -> Any:
        if self._json is _UNPARSED:
            if not self.body:
                self._json = {}
            else:
                try:
                    self._json = json.loads(self.body)
                except json.JSONDecodeError:
                    raise HTTPError(400, "invalid JSON body") from None
        return self._json

    def arg(self, name: str, default: str | None = None) -> str | None:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def int_arg(self, name: str, default: int | None = None) -> int | None:
        v = self.arg(name)
        if v is None:
            return default
        try:
            return int(v)
        except ValueError:
            raise HTTPError(400, f"query param {name!r} must be an int") from None

    @property
    def bearer_token(self) -> str | None:
        h = self.headers.get("authorization", "")
        return h[7:] if h.lower().startswith("bearer ") else None

    @property
    def page(self) -> int:
        return max(1, self.int_arg("page", 1))

    @property
    def per_page(self) -> int:
        return min(250, max(1, self.int_arg("per_page", 50)))


class Response:
    def __init__(
        self,
        data: Any = None,
        status: int = 200,
        headers: dict[str, str] | None = None,
    ):
        self.status = status
        self.headers = headers or {}
        if isinstance(data, (bytes, str)):
            self.body = data.encode() if isinstance(data, str) else data
            self.headers.setdefault("Content-Type", "text/plain")
        else:
            self.body = json.dumps(data if data is not None else {}).encode()
            self.headers.setdefault("Content-Type", "application/json")


_PARAM_RE = re.compile(r"<(?:(int|str):)?(\w+)>")
# replica-local: code-derived constant, identical on every replica
_STATUS_TEXT = {
    200: "OK", 201: "Created", 204: "No Content", 400: "Bad Request",
    401: "Unauthorized", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 409: "Conflict", 500: "Internal Server Error",
}

Handler = Callable[..., Any]


class App:
    """Route registry + WSGI callable."""

    def __init__(self, name: str = "app", replica_id: str | None = None):
        self.name = name
        # stamped on every request span: with N replicas over one store,
        # trace_view attributes per-hop latency to the replica that served
        # it (empty for single-purpose apps like the store service)
        self.replica_id = replica_id
        # (regex, {method: handler}, original pattern — the low-cardinality
        # span/metric label: "/api/run/<int:id>" instead of "/api/run/17")
        # replica-local: route table built from code at startup
        self._routes: list[
            tuple[re.Pattern[str], dict[str, Handler], str]
        ] = []
        # route patterns excluded from the latency histogram: long-poll
        # endpoints block by DESIGN (up to 25 s) and would otherwise
        # dominate the p95 the metric exists to report. Declared at
        # registration (`untimed=True`) — route semantics belong to the
        # route, not to query-param sniffing in the shared request path.
        # replica-local: declared from code at route registration
        self._untimed: set[str] = set()
        self._auth_hook: Callable[[Request], None] | None = None

    def route(
        self,
        pattern: str,
        methods: tuple[str, ...] = ("GET",),
        untimed: bool = False,
    ):
        regex = self._compile(pattern)
        if untimed:
            self._untimed.add(pattern)
        def deco(fn: Handler) -> Handler:
            for existing, table, _pat in self._routes:
                if existing.pattern == regex.pattern:
                    for m in methods:
                        table[m] = fn
                    return fn
            self._routes.append((regex, {m: fn for m in methods}, pattern))
            return fn
        return deco

    @staticmethod
    def _compile(pattern: str) -> re.Pattern[str]:
        out = []
        pos = 0
        for m in _PARAM_RE.finditer(pattern):
            out.append(re.escape(pattern[pos : m.start()]))
            typ = m.group(1) or "str"
            name = m.group(2)
            out.append(
                f"(?P<{name}>\\d+)" if typ == "int" else f"(?P<{name}>[^/]+)"
            )
            pos = m.end()
        out.append(re.escape(pattern[pos:]))
        return re.compile("^" + "".join(out) + "$")

    def set_auth_hook(self, hook: Callable[[Request], None]) -> None:
        """Runs before every handler; sets request.identity or raises 401."""
        self._auth_hook = hook

    # ---------------------------------------------------------------- serve
    def handle(self, request: Request) -> Response:
        for regex, table, pattern in self._routes:
            m = regex.match(request.path)
            if not m:
                continue
            handler = table.get(request.method)
            if handler is None:
                return Response({"msg": "method not allowed"}, 405)
            kwargs = {
                k: int(v) if v.isdigit() else v
                for k, v in m.groupdict().items()
            }
            t0 = time.perf_counter()
            _HTTP_REQUESTS.inc()
            # long-poll routes are counted but not timed (see _untimed)
            observe = pattern not in self._untimed
            # join the caller's trace when the request carries one
            # (require_parent: a bare poll must not mint a root trace per
            # request); the span stays current for the handler's own
            # child spans and any onward pooled_request relays
            parent = parse_traceparent(
                request.headers.get("traceparent")
            )
            with TRACER.span(
                f"http {request.method} {pattern}", kind="server",
                parent=parent, service=self.name, require_parent=True,
            ) as span:
                if self.replica_id:
                    span.set_attr(replica=self.replica_id)
                try:
                    if self._auth_hook is not None:
                        self._auth_hook(request)
                    out = handler(request, **kwargs)
                except HTTPError as e:
                    span.set_attr(status_code=e.status)
                    if e.status >= 500:
                        span.set_status("error")
                        _HTTP_ERRORS.inc()
                    if observe:
                        _HTTP_SECONDS.observe(time.perf_counter() - t0)
                    return Response({"msg": e.msg}, e.status)
                except Exception:
                    # the log record carries trace_id/span_id (the handler
                    # span is current here — TraceContextFilter), so this
                    # 500 is joinable to the request's trace in a dump
                    log.error(
                        "500 on %s %s\n%s",
                        request.method,
                        request.path,
                        traceback.format_exc(limit=8),
                    )
                    try:
                        from vantage6_tpu.common.flight import FLIGHT

                        FLIGHT.note(
                            "http_500", method=request.method,
                            path=request.path, route=pattern,
                        )
                    except Exception:  # pragma: no cover
                        pass
                    span.set_status("error")
                    _HTTP_ERRORS.inc()
                    if observe:
                        _HTTP_SECONDS.observe(time.perf_counter() - t0)
                    return Response({"msg": "internal server error"}, 500)
            if observe:
                _HTTP_SECONDS.observe(time.perf_counter() - t0)
            if isinstance(out, Response):
                return out
            if isinstance(out, tuple):
                return Response(out[0], out[1])
            return Response(out)
        return Response({"msg": "not found"}, 404)

    def __call__(self, environ: dict[str, Any], start_response: Callable):
        try:
            length = int(environ.get("CONTENT_LENGTH") or 0)
        except ValueError:
            length = 0
        body = environ["wsgi.input"].read(length) if length else b""
        headers = {
            k[5:].replace("_", "-").lower(): v
            for k, v in environ.items()
            if k.startswith("HTTP_")
        }
        if environ.get("CONTENT_TYPE"):
            headers["content-type"] = environ["CONTENT_TYPE"]
        request = Request(
            method=environ["REQUEST_METHOD"],
            path=environ.get("PATH_INFO", "/"),
            query=parse_qs(environ.get("QUERY_STRING", "")),
            headers=headers,
            body=body,
        )
        resp = self.handle(request)
        status_line = f"{resp.status} {_STATUS_TEXT.get(resp.status, 'Status')}"
        start_response(status_line, list(resp.headers.items()))
        return [resp.body]


class _QuietHandler(WSGIRequestHandler):
    def log_message(self, fmt: str, *args: Any) -> None:  # noqa: A003
        log.debug("%s %s", self.address_string(), fmt % args)


class _ThreadingWSGIServer(ThreadingMixIn, WSGIServer):
    """Thread-per-request WSGI server. REQUIRED, not an optimization: the
    server's store proxy calls the store, which calls back into this same
    server's /api/whoami for the trust handshake — on a serial server that
    re-entrancy is a deadlock. The db layer keeps one sqlite connection per
    thread for exactly this server model (server/db.py)."""

    daemon_threads = True
    # federation-scale accept queue: 32+ nodes polling plus a researcher
    # burst overflows the wsgiref default of 5 and resets connections
    request_queue_size = 128


class AppServer:
    """Threaded HTTP server wrapper with background start/stop (used by the
    node daemon's proxy and by `v6t server start`)."""

    def __init__(self, app: App, host: str = "127.0.0.1", port: int = 0):
        self._server = make_server(
            host, port, app,
            server_class=_ThreadingWSGIServer, handler_class=_QuietHandler,
        )
        self.host, self.port = self._server.server_address[:2]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start_background(self) -> "AppServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class TestClient:
    """In-process client calling the WSGI app directly (no sockets)."""

    def __init__(self, app: App):
        self.app = app
        self.token: str | None = None

    def open(
        self,
        method: str,
        path: str,
        json_body: Any = None,
        headers: dict[str, str] | None = None,
        token: str | None = None,
    ) -> "TestResponse":
        query: dict[str, list[str]] = {}
        if "?" in path:
            path, _, qs = path.partition("?")
            query = parse_qs(qs)
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        tok = token or self.token
        if tok:
            hdrs.setdefault("authorization", f"Bearer {tok}")
        body = b""
        if json_body is not None:
            body = json.dumps(json_body).encode()
            hdrs.setdefault("content-type", "application/json")
        req = Request(method, path, query, hdrs, body)
        resp = self.app.handle(req)
        return TestResponse(resp)

    def get(self, path: str, **kw: Any) -> "TestResponse":
        return self.open("GET", path, **kw)

    def post(self, path: str, json_body: Any = None, **kw: Any) -> "TestResponse":
        return self.open("POST", path, json_body, **kw)

    def patch(self, path: str, json_body: Any = None, **kw: Any) -> "TestResponse":
        return self.open("PATCH", path, json_body, **kw)

    def delete(self, path: str, **kw: Any) -> "TestResponse":
        return self.open("DELETE", path, **kw)


class TestResponse:
    def __init__(self, resp: Response):
        self.status = resp.status
        self.body = resp.body
        self.headers = resp.headers

    @property
    def json(self) -> Any:
        return json.loads(self.body) if self.body else None

    def __repr__(self) -> str:
        return f"<TestResponse {self.status} {self.body[:200]!r}>"
