"""Authentication: JWT access/refresh tokens, node API keys, container
tokens, optional TOTP MFA.

Parity: SURVEY.md §2 item 7 — `/api/token/user` (username+password [+TOTP]),
`/api/token/node` (api_key), `/api/token/container` (issued by the node for
a running algorithm so subtask creation is authenticated), plus refresh.
JWTs are HS256, implemented on stdlib hmac (PyJWT is not in the image).
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets
import struct
import time
from typing import Any


class AuthError(Exception):
    """Invalid credentials / token (HTTP 401)."""


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _unb64url(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def encode_jwt(claims: dict[str, Any], secret: str) -> str:
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


def decode_jwt(token: str, secret: str) -> dict[str, Any]:
    try:
        header_s, payload_s, sig_s = token.split(".")
        signing_input = f"{header_s}.{payload_s}".encode()
        expect = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
        if not hmac.compare_digest(expect, _unb64url(sig_s)):
            raise AuthError("bad token signature")
        claims = json.loads(_unb64url(payload_s))
    except AuthError:
        raise
    except Exception:
        # bad base64, wrong part count, non-JSON payload, ... — all are a
        # client's malformed token (401), never a server error
        raise AuthError("malformed token") from None
    if claims.get("exp") is not None and claims["exp"] < time.time():
        raise AuthError("token expired")
    return claims


# ------------------------------------------------------------------- TOTP


def generate_totp_secret() -> str:
    return base64.b32encode(secrets.token_bytes(20)).decode("ascii")


def totp_code(secret: str, at: float | None = None, step: int = 30) -> str:
    """RFC 6238 6-digit code (SHA-1, 30s steps)."""
    counter = int((at if at is not None else time.time()) // step)
    key = base64.b32decode(secret)
    msg = struct.pack(">Q", counter)
    digest = hmac.new(key, msg, hashlib.sha1).digest()
    offset = digest[-1] & 0x0F
    code = struct.unpack(">I", digest[offset : offset + 4])[0] & 0x7FFFFFFF
    return f"{code % 1_000_000:06d}"


def verify_totp(secret: str, code: str, at: float | None = None) -> bool:
    """Accept the current step ±1 (clock skew), constant-time compare."""
    now = at if at is not None else time.time()
    return any(
        hmac.compare_digest(totp_code(secret, now + drift * 30), code)
        for drift in (-1, 0, 1)
    )


# ---------------------------------------------------------------- token mint


class TokenAuthority:
    """Mints and validates the three principal token types."""

    ACCESS_TTL = 6 * 3600.0
    REFRESH_TTL = 48 * 3600.0

    def __init__(self, secret: str | None = None):
        self.secret = secret or secrets.token_urlsafe(32)

    def _mint(self, claims: dict[str, Any], ttl: float) -> str:
        now = time.time()
        return encode_jwt(
            {**claims, "iat": now, "exp": now + ttl, "jti": secrets.token_hex(8)},
            self.secret,
        )

    def user_tokens(
        self, user_id: int, fingerprint: str | None = None
    ) -> dict[str, str]:
        """``fingerprint`` (credential_fingerprint of the user's current
        password hash + TOTP secret) binds BOTH tokens to the credentials
        they were issued under: a password change rotates the fingerprint
        and every outstanding session dies — stateless revocation, the
        same construction reset tokens use."""
        sub = {"type": "user", "id": user_id}
        extra = {"pwh": fingerprint} if fingerprint else {}
        return {
            "access_token": self._mint(
                {"sub": sub, "use": "access", **extra}, self.ACCESS_TTL
            ),
            "refresh_token": self._mint(
                {"sub": sub, "use": "refresh", **extra}, self.REFRESH_TTL
            ),
        }

    def node_tokens(self, node_id: int) -> dict[str, str]:
        sub = {"type": "node", "id": node_id}
        return {
            "access_token": self._mint({"sub": sub, "use": "access"}, self.ACCESS_TTL),
            "refresh_token": self._mint({"sub": sub, "use": "refresh"}, self.REFRESH_TTL),
        }

    def container_token(
        self, node_id: int, task_id: int, image: str, organization_id: int
    ) -> str:
        """Short-lived token a node issues to a running algorithm."""
        sub = {
            "type": "container",
            "node_id": node_id,
            "task_id": task_id,
            "image": image,
            "organization_id": organization_id,
        }
        return self._mint({"sub": sub, "use": "access"}, self.ACCESS_TTL)

    # -------------------------------------------------------- password reset
    RESET_TTL = 3600.0

    @staticmethod
    def _credential_fingerprint(
        password_hash: str | None, totp_secret: str | None
    ) -> str:
        """Fingerprint of BOTH credentials: a reset token dies the moment
        either the password or the TOTP secret changes — so one token can
        perform exactly one reset (password OR 2FA), never be replayed."""
        material = (password_hash or "") + ":" + (totp_secret or "")
        return hashlib.sha256(material.encode()).hexdigest()[:16]

    def password_reset_token(
        self, user_id: int, password_hash: str | None,
        totp_secret: str | None = None,
    ) -> str:
        """Single-use-by-construction reset token — stateless revocation via
        the credential fingerprint (see _credential_fingerprint)."""
        return self._mint(
            {
                "sub": {"type": "user", "id": user_id},
                "use": "password_reset",
                "pwh": self._credential_fingerprint(
                    password_hash, totp_secret
                ),
            },
            self.RESET_TTL,
        )

    def validate_password_reset(
        self, token: str, current_password_hash: str | None,
        current_totp_secret: str | None = None,
    ) -> int:
        """Returns the user id; raises AuthError on any mismatch."""
        claims = decode_jwt(token, self.secret)
        if claims.get("use") != "password_reset":
            raise AuthError("not a password reset token")
        if not hmac.compare_digest(
            claims.get("pwh", ""),
            self._credential_fingerprint(
                current_password_hash, current_totp_secret
            ),
        ):
            raise AuthError("reset token already used or superseded")
        sub = claims.get("sub") or {}
        if sub.get("type") != "user" or "id" not in sub:
            raise AuthError("malformed subject")
        return int(sub["id"])

    # ------------------------------------------------------------ validation
    def identity(self, token: str, use: str = "access") -> dict[str, Any]:
        return self.identity_claims(token, use)[0]

    def identity_claims(
        self, token: str, use: str = "access"
    ) -> tuple[dict[str, Any], dict[str, Any]]:
        """(subject, full claims) — callers needing the credential
        fingerprint ("pwh") read it from the claims."""
        claims = decode_jwt(token, self.secret)
        if claims.get("use") != use:
            raise AuthError(f"expected a {use} token")
        sub = claims.get("sub")
        if not isinstance(sub, dict) or "type" not in sub:
            raise AuthError("malformed subject")
        return sub, claims

    def fingerprint_ok(
        self,
        claims: dict[str, Any],
        password_hash: str | None,
        totp_secret: str | None,
    ) -> bool:
        """False when the token carries a credential fingerprint that no
        longer matches — i.e. the password/2FA changed after issuance. A
        token WITHOUT a fingerprint passes (node/container tokens; the
        claim cannot be stripped — the JWT is signed)."""
        pwh = claims.get("pwh")
        if not pwh:
            return True
        return hmac.compare_digest(
            pwh, self._credential_fingerprint(password_hash, totp_secret)
        )

    def refresh(self, refresh_token: str) -> dict[str, str]:
        sub = self.identity(refresh_token, use="refresh")
        if sub["type"] == "user":
            return self.user_tokens(sub["id"])
        if sub["type"] == "node":
            return self.node_tokens(sub["id"])
        raise AuthError("container tokens cannot be refreshed")
