"""Control plane (parity: vantage6-server, SURVEY.md §2 items 1-8)."""
from vantage6_tpu.server.app import ServerApp, run_server  # noqa: F401
