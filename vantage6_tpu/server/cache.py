"""Server hot-path caches: token→principal auth and collaboration visibility.

The control plane re-resolves the SAME bearer token (JWT verify + principal
row + rule graph) and re-derives the SAME org→collaborations visibility set
on every request of a polling daemon or a paginating client. Both are
read-mostly with rare, well-identified writers, so each gets a small cache
with EXPLICIT invalidation at every mutation site (resources.py calls the
invalidate hooks) plus a short TTL as belt-and-braces:

- `AuthCache` — token string → (kind, principal). For users the principal
  carries its precomputed rule-id set (`User.rule_ids` honors it), so a
  permission check costs zero queries on a warm token. Invalidation:
  per-principal on user/node mutation, global on role/rule mutation (a
  role's rule set reaches arbitrarily many users). Entries also die at the
  token's own `exp` — a cache hit must never outlive the JWT.
- `VisibilityCache` — organization_id → frozenset of collaboration ids the
  org belongs to (the check `resources.py` used to re-query per run/row).
  Invalidation: global on any collaboration-membership mutation.

Both caches are process-local. On a single-replica server that matches the
consistency domain exactly: every mutation that must invalidate goes
through this same process's REST handlers. With N replicas over a shared
store (docs/control_plane.md), a mutation can land on a DIFFERENT replica —
there `resources._invalidate` also publishes a `CACHE_INVALIDATE` event on
the shared stream and every replica's auth hot path drains it
(`ServerApp.drain_invalidations`, rate-limited to ~25 ms), so cross-replica
staleness is bounded by the drain interval with the TTL as the backstop.
"""
from __future__ import annotations

import threading
import time
from typing import Any


class AuthCache:
    """Bounded TTL cache: token → (kind, principal, expires_at)."""

    def __init__(self, ttl: float = 30.0, maxsize: int = 2048):
        self.ttl = ttl
        self.maxsize = maxsize
        self._lock = threading.Lock()
        # replica-local: coherent via the CACHE_INVALIDATE bus + TTL
        self._entries: dict[str, tuple[float, str, Any]] = {}  # guarded-by: _lock
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock

    def get(self, token: str) -> tuple[str, Any] | None:
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(token)
            if entry is None or entry[0] < now:
                if entry is not None:
                    del self._entries[token]
                self.misses += 1
                return None
            self.hits += 1
            return entry[1], entry[2]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def put(
        self, token: str, kind: str, principal: Any,
        token_exp: float | None = None,
    ) -> None:
        now = time.monotonic()
        expires = now + self.ttl
        if token_exp is not None:
            # token_exp is wall-clock; convert the remaining lifetime
            expires = min(expires, now + max(0.0, token_exp - time.time()))
        with self._lock:
            if len(self._entries) >= self.maxsize:
                # simple pressure valve: drop everything (cheap, rare, and
                # correctness never depends on residency)
                self._entries.clear()
            self._entries[token] = (expires, kind, principal)

    # ------------------------------------------------------- invalidation
    def invalidate_principal(self, kind: str, principal_id: int) -> None:
        """Evict every token resolving to this user/node — called on any
        mutation of the principal (credentials, roles, fields, deletion)."""
        with self._lock:
            dead = [
                tok for tok, (_, k, p) in self._entries.items()
                if k == kind and getattr(p, "id", None) == principal_id
            ]
            for tok in dead:
                del self._entries[tok]

    def invalidate_all(self) -> None:
        with self._lock:
            self._entries.clear()


class VisibilityCache:
    """organization_id → frozenset(collaboration ids containing the org)."""

    def __init__(self, ttl: float = 30.0):
        self.ttl = ttl
        self._lock = threading.Lock()
        # replica-local: coherent via the CACHE_INVALIDATE bus + TTL
        self._entries: dict[int, tuple[float, frozenset[int]]] = {}  # guarded-by: _lock
        # hit/miss accounting for the unified telemetry registry — the
        # same observability the AuthCache already had
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock

    def get(self, org_id: int) -> frozenset[int] | None:
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(org_id)
            if entry is None or entry[0] < now:
                if entry is not None:
                    # drop the expired entry NOW: a quiet org must not
                    # keep inflating the v6t_visibility_cache_entries gauge
                    del self._entries[org_id]
                self.misses += 1
                return None
            self.hits += 1
            return entry[1]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def put(self, org_id: int, collab_ids: frozenset[int]) -> None:
        with self._lock:
            self._entries[org_id] = (time.monotonic() + self.ttl, collab_ids)

    def invalidate_all(self) -> None:
        """Collaboration membership changed — the mapping is many-to-many,
        so any mutation can affect any org's entry."""
        with self._lock:
            self._entries.clear()
