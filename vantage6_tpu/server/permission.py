"""Rule-based access control: scope × operation per resource.

Parity: vantage6-server `PermissionManager` (SURVEY.md §2 item 4). A *rule*
grants one operation on one resource at one scope; roles bundle rules;
users hold roles (plus optional extra rules). Default roles (Root, …)
mirror the reference's seeded set.
"""
from __future__ import annotations

import enum
from typing import Iterable

from vantage6_tpu.server import models as m


class Scope(str, enum.Enum):
    OWN = "own"
    ORGANIZATION = "organization"
    COLLABORATION = "collaboration"
    GLOBAL = "global"

    @property
    def level(self) -> int:
        return _SCOPE_ORDER.index(self)


# replica-local: code-derived constant, identical on every replica
_SCOPE_ORDER = [Scope.OWN, Scope.ORGANIZATION, Scope.COLLABORATION, Scope.GLOBAL]


class Operation(str, enum.Enum):
    VIEW = "view"
    CREATE = "create"
    EDIT = "edit"
    DELETE = "delete"
    SEND = "send"
    RECEIVE = "receive"


# resource -> operations that exist for it (the rule matrix the reference
# seeds at server start)
# replica-local: code-derived constant, identical on every replica
RESOURCE_OPERATIONS: dict[str, list[Operation]] = {
    "user": [Operation.VIEW, Operation.CREATE, Operation.EDIT, Operation.DELETE],
    "organization": [Operation.VIEW, Operation.CREATE, Operation.EDIT, Operation.DELETE],
    "collaboration": [Operation.VIEW, Operation.CREATE, Operation.EDIT, Operation.DELETE],
    "study": [Operation.VIEW, Operation.CREATE, Operation.EDIT, Operation.DELETE],
    "node": [Operation.VIEW, Operation.CREATE, Operation.EDIT, Operation.DELETE],
    "task": [Operation.VIEW, Operation.CREATE, Operation.EDIT, Operation.DELETE],
    "run": [Operation.VIEW],
    "role": [Operation.VIEW, Operation.CREATE, Operation.EDIT, Operation.DELETE],
    "rule": [Operation.VIEW],
    "event": [Operation.SEND, Operation.RECEIVE],
    "port": [Operation.VIEW],
    "session": [
        Operation.VIEW, Operation.CREATE, Operation.EDIT, Operation.DELETE
    ],
}

# scopes that make sense per resource: OWN only where a row has an owner
# replica-local: code-derived constant, identical on every replica
_OWNED = {"user", "task", "run", "session"}


def applicable_scopes(resource: str) -> list[Scope]:
    scopes = [Scope.ORGANIZATION, Scope.COLLABORATION, Scope.GLOBAL]
    if resource in _OWNED:
        scopes = [Scope.OWN, *scopes]
    return scopes


class PermissionManager:
    """Seeds the rule matrix and answers 'may user U do O on R at scope S?'"""

    def __init__(self) -> None:
        # cache of store-seeded rule ids — every replica derives the
        # replica-local: identical mapping from the shared store
        self._rule_ids: dict[tuple[str, str, str], int] = {}
        self.seed_rules()

    # ------------------------------------------------------------------ seed
    def seed_rules(self) -> None:
        existing = {
            (r.name, r.scope, r.operation): r.id for r in m.Rule.list()
        }
        for resource, ops in RESOURCE_OPERATIONS.items():
            for scope in applicable_scopes(resource):
                for op in ops:
                    key = (resource, scope.value, op.value)
                    if key not in existing:
                        rule = m.Rule(
                            name=resource, scope=scope.value, operation=op.value
                        ).save()
                        existing[key] = rule.id
        self._rule_ids = existing

    def rule(self, resource: str, scope: Scope, operation: Operation) -> int:
        try:
            return self._rule_ids[(resource, scope.value, operation.value)]
        except KeyError:
            raise KeyError(
                f"no rule {resource}/{scope.value}/{operation.value}"
            ) from None

    # ----------------------------------------------------------------- roles
    def ensure_default_roles(self) -> dict[str, m.Role]:
        """Seed the reference's default roles (Root, Collaboration Admin,
        Organization Admin, Researcher, Viewer, Container)."""
        out: dict[str, m.Role] = {}

        def role(name: str, desc: str, rules: Iterable[int]) -> m.Role:
            r = m.Role.first(name=name, organization_id=None)
            if r is None:
                r = m.Role(name=name, description=desc).save()
            for rid in rules:
                m.role_rule.add(r.id, rid)
            out[name] = r
            return r

        role("Root", "all permissions", self._rule_ids.values())
        org_admin = [
            rid
            for (res, sc, _), rid in self._rule_ids.items()
            if sc == Scope.ORGANIZATION.value
        ]
        role("Organization Admin", "manage own organization", org_admin)
        collab = [
            rid
            for (res, sc, _), rid in self._rule_ids.items()
            if sc == Scope.COLLABORATION.value
        ]
        role("Collaboration Admin", "manage own collaborations", collab)
        researcher = [
            self.rule("task", Scope.COLLABORATION, Operation.VIEW),
            self.rule("task", Scope.COLLABORATION, Operation.CREATE),
            self.rule("run", Scope.COLLABORATION, Operation.VIEW),
            self.rule("organization", Scope.COLLABORATION, Operation.VIEW),
            self.rule("collaboration", Scope.ORGANIZATION, Operation.VIEW),
            self.rule("node", Scope.COLLABORATION, Operation.VIEW),
            self.rule("event", Scope.COLLABORATION, Operation.RECEIVE),
            self.rule("session", Scope.COLLABORATION, Operation.VIEW),
            self.rule("session", Scope.COLLABORATION, Operation.CREATE),
            self.rule("session", Scope.OWN, Operation.DELETE),
        ]
        role("Researcher", "create and view tasks", researcher)
        viewer = [
            rid
            for (res, sc, op), rid in self._rule_ids.items()
            if sc == Scope.ORGANIZATION.value and op == Operation.VIEW.value
        ]
        role("Viewer", "view everything in own organization", viewer)
        return out

    # ----------------------------------------------------------------- check
    def user_scope(
        self, user: m.User, resource: str, operation: Operation
    ) -> Scope | None:
        """Widest scope at which the user may perform the operation."""
        rules = user.rule_ids()
        best: Scope | None = None
        for scope in applicable_scopes(resource):
            key = (resource, scope.value, operation.value)
            rid = self._rule_ids.get(key)
            if rid is not None and rid in rules:
                if best is None or scope.level > best.level:
                    best = scope
        return best

    def allowed(
        self,
        user: m.User,
        resource: str,
        operation: Operation,
        *,
        organization_id: int | None = None,
        collaboration_id: int | None = None,
        owner_id: int | None = None,
    ) -> bool:
        """Check against a concrete target.

        A GLOBAL rule always passes; COLLABORATION requires the user's org in
        the target collaboration; ORGANIZATION requires same org; OWN
        requires the user to own the row.
        """
        scope = self.user_scope(user, resource, operation)
        if scope is None:
            return False
        if scope == Scope.GLOBAL:
            return True
        if scope == Scope.COLLABORATION:
            if collaboration_id is None:
                # no collaboration context: org-level fallback
                return (
                    organization_id is not None
                    and organization_id == user.organization_id
                ) or owner_id == user.id
            collab = m.Collaboration.get(collaboration_id)
            return (
                collab is not None
                and user.organization_id in collab.organization_ids()
            )
        if scope == Scope.ORGANIZATION:
            if organization_id is not None:
                return organization_id == user.organization_id
            return owner_id == user.id
        # OWN
        return owner_id is not None and owner_id == user.id
