"""REST resources: one section per entity, CRUD + pagination + RBAC.

Parity: vantage6-server's resource modules (SURVEY.md §2 item 3) and the
auth endpoints of item 7. Routes live under `/api/*` with the reference's
wire shapes (`{"data": [...]}` lists, task fan-out to runs, node PATCH of
run status/result, kill events, cursor-based event sync).
"""
from __future__ import annotations

import time
from typing import TYPE_CHECKING, Any

from vantage6_tpu.common.enums import TaskStatus
from vantage6_tpu.server import events as ev
from vantage6_tpu.server import models as m
from vantage6_tpu.server import schemas as sch
from vantage6_tpu.server.auth import AuthError, decode_jwt, verify_totp
from vantage6_tpu.server.permission import Operation, Scope
from vantage6_tpu.server.web import HTTPError, Request

if TYPE_CHECKING:  # pragma: no cover
    from vantage6_tpu.server.app import ServerApp


# --------------------------------------------------------------- auth helpers


def identity_from_token(srv: "ServerApp", token: str | None) -> tuple[str, Any]:
    """Resolve a bearer token to (kind, principal); raises HTTPError(401).
    Shared by the REST auth path and the websocket bridge.

    Resolutions are cached (`srv.auth_cache`, token → principal, with the
    user's rule-id set precomputed) so a polling daemon or batching client
    pays the JWT verify + principal/rule queries once per TTL, not per
    request. Every mutation that could change the answer — credential
    rotation, role/rule edits, principal deletion — explicitly invalidates
    (see the endpoints below); the entry also dies at the token's own exp.
    """
    if not token:
        raise HTTPError(401, "missing bearer token")
    # cross-replica coherence: apply peers' pending cache invalidations
    # BEFORE consulting this replica's caches (rate-limited; no-op on an
    # in-process hub — see ServerApp.drain_invalidations)
    srv.drain_invalidations()
    cached = srv.auth_cache.get(token)
    if cached is not None:
        return cached
    try:
        sub, claims = srv.tokens.identity_claims(token)
    except AuthError as e:
        raise HTTPError(401, str(e)) from None
    kind = sub["type"]
    if kind == "user":
        user = m.User.get(sub["id"])
        if user is None:
            raise HTTPError(401, "unknown user")
        if not srv.tokens.fingerprint_ok(
            claims, user.password_hash, user.totp_secret
        ):
            # credentials rotated after issuance: the session is dead —
            # this is what makes a password change evict a stolen session
            raise HTTPError(401, "token superseded by a credential change")
        # warm the rule set so permission checks on this cached principal
        # cost zero queries (User.rule_ids honors _rules_cache)
        user._rules_cache = frozenset(user.rule_ids())
        srv.auth_cache.put(token, "user", user, claims.get("exp"))
        return "user", user
    if kind == "node":
        node = m.Node.get(sub["id"])
        if node is None:
            raise HTTPError(401, "unknown node")
        srv.auth_cache.put(token, "node", node, claims.get("exp"))
        return "node", node
    if kind == "container":
        srv.auth_cache.put(token, "container", sub, claims.get("exp"))
        return "container", sub
    raise HTTPError(401, "unknown principal type")


def _visible_collab_ids(srv: "ServerApp", org_id: int) -> frozenset[int]:
    """Collaboration ids containing `org_id` — THE visibility check the
    listing endpoints and event-room scoping previously re-derived from a
    full Collaboration scan per request (and per run, in the run listing).
    Cached on the server; invalidated on any membership mutation."""
    cached = srv.vis_cache.get(org_id)
    if cached is not None:
        return cached
    ids = frozenset(
        c.id for c in m.Collaboration.list() if org_id in c.organization_ids()
    )
    srv.vis_cache.put(org_id, ids)
    return ids


def _identity(srv: "ServerApp", req: Request) -> tuple[str, Any]:
    return identity_from_token(srv, req.bearer_token)


def _invalidate(srv: "ServerApp", entity: str, id_: int | None = None) -> None:
    """ONE invalidation call per mutation site: applies to this replica's
    caches immediately and — when the event hub is the shared-store bus —
    publishes a CACHE_INVALIDATE event so every OTHER replica applies it
    too (ServerApp.drain_invalidations). Entities: user/node evict the
    principal's tokens; role/rule evict the whole auth cache (a role's
    rule set reaches arbitrarily many users); collaboration evicts the
    visibility cache."""
    if entity in ("user", "node") and id_ is not None:
        srv.auth_cache.invalidate_principal(entity, id_)
    elif entity in ("role", "rule"):
        srv.auth_cache.invalidate_all()
    elif entity == "collaboration":
        srv.vis_cache.invalidate_all()
    if getattr(srv.hub, "SHARED", False):
        from vantage6_tpu.server.events import CACHE_INVALIDATE, REPLICA_ROOM

        try:
            srv.hub.emit(
                CACHE_INVALIDATE, {"entity": entity, "id": id_},
                room=REPLICA_ROOM,
            )
        except Exception:  # the local invalidation already happened;
            pass  # peers' TTL is the backstop if the emit is lost


def _require_user(srv: "ServerApp", req: Request) -> m.User:
    kind, principal = _identity(srv, req)
    if kind != "user":
        raise HTTPError(403, "user credentials required")
    return principal


def _require_node(srv: "ServerApp", req: Request) -> m.Node:
    kind, principal = _identity(srv, req)
    if kind != "node":
        raise HTTPError(403, "node credentials required")
    return principal


def _check(ok: bool) -> None:
    if not ok:
        raise HTTPError(403)


def _paginate(req: Request, rows: list[Any]) -> dict[str, Any]:
    start = (req.page - 1) * req.per_page
    return {
        "data": [r.to_dict() for r in rows[start : start + req.per_page]],
        "pagination": {
            "page": req.page,
            "per_page": req.per_page,
            "total": len(rows),
        },
    }


def _get_or_404(model: type, id_: int) -> Any:
    row = model.get(id_)
    if row is None:
        raise HTTPError(404)
    return row


def _node_for_org(collaboration_id: int, organization_id: int) -> m.Node | None:
    return m.Node.first(
        collaboration_id=collaboration_id, organization_id=organization_id
    )


def _container_task(principal: dict[str, Any]) -> m.Task:
    """The parent task of a container principal; 401 if it was deleted
    (container tokens outlive task deletion)."""
    task = m.Task.get(principal["task_id"])
    if task is None:
        raise HTTPError(401, "container's task no longer exists")
    return task


def _user_for_reset_token(srv: "ServerApp", token: str) -> m.User:
    """Resolve a password-reset token to its user; 401 on expiry, tamper,
    or reuse (the token binds the password hash it was issued against)."""
    # peek at the subject first so the pwh check runs against the right user
    try:
        sub = decode_jwt(token, srv.tokens.secret).get("sub") or {}
        user = m.User.get(int(sub.get("id", -1))) if sub else None
        if user is None:
            raise AuthError("unknown user")
        srv.tokens.validate_password_reset(
            token, user.password_hash, user.totp_secret
        )
    except AuthError as e:
        raise HTTPError(401, str(e)) from None
    return user


def _grant_role_rules(
    user: m.User, role: m.Role, rule_ids: list[int], *, replace: bool = False
) -> None:
    """Attach rules to a role — the grantor may only hand out rules they
    hold themselves (reference rule; without this, any role-CREATE/EDIT
    holder could mint a super-role). Shared by role create and PATCH."""
    own = user.rule_ids()
    for rid in rule_ids:
        if rid not in own:
            raise HTTPError(403, f"cannot grant rule {rid} you do not have")
    if replace:
        for rid in list(role.rule_ids()):
            m.role_rule.remove(role.id, rid)
    for rid in rule_ids:
        role.add_rule(_get_or_404(m.Rule, rid))


def _check_role_grant(user: m.User, role_ids: list[int]) -> list[m.Role]:
    """A grantor may only hand out roles whose rules they hold themselves —
    without this, any user-EDIT holder could self-assign Root."""
    own = user.rule_ids()
    roles = []
    for rid in role_ids:
        role = _get_or_404(m.Role, rid)
        missing = set(role.rule_ids()) - own
        if missing:
            raise HTTPError(
                403,
                f"cannot assign role {role.name!r}: it grants rules you "
                "do not have",
            )
        roles.append(role)
    return roles


def register_resources(srv: "ServerApp") -> None:
    app = srv.app
    pm = srv.pm

    # ------------------------------------------------------------- service
    @app.route("/api/health")
    def health(req: Request):
        """Real health verdict, not just a capability card: `status` is
        "ok" or "degraded" — degraded when any registered component
        (event hub, tracer sink, the watchdog's own evaluation loop)
        fails its self-check, or a critical alert is active. The
        capability flags the clients probe stay unchanged."""
        from vantage6_tpu import __version__
        from vantage6_tpu.runtime.tracing import TRACER

        verdict = srv.watchdog.health()
        out = {
            "status": verdict["status"],
            "components": verdict["components"],
            "alerts": {**verdict["alerts"], "url": "/api/alerts"},
            "uptime": time.time() - srv.started_at,
            "replica_id": srv.replica_id,
            "version": __version__,
            # advertised so nodes/UIs can upgrade from polling to push
            "websocket_url": srv.ws_url,
            # capability flags the clients probe (see docs/observability.md)
            "long_poll": True,
            "metrics": "/api/metrics",
            "tracing": TRACER.enabled,
        }
        if srv.db.SHARED:
            # shared-store deployments: the fleet view, read from DB truth
            # (replica_heartbeat) — "did a replica die" is answered here
            from vantage6_tpu.server import pubsub

            out["replicas"] = pubsub.list_replicas(srv.db)
        # fleet telemetry census: how many sources push here, how many
        # went quiet (full view at /api/fleet)
        from vantage6_tpu.server import fleet

        out["fleet"] = {**fleet.health_block(srv.db), "url": "/api/fleet"}
        return out

    @app.route("/api/alerts")
    def alerts(req: Request):
        """Watchdog alert state: active alerts, recently resolved ones,
        and the rule catalog (what each alert means + its runbook line).
        Unauthenticated like /api/health and /api/metrics — it carries
        operational state (rule names, run/node ids), never payloads or
        principals."""
        from vantage6_tpu.runtime.watchdog import RULE_CATALOG

        return {
            "status": srv.watchdog.health()["status"],
            "active": srv.watchdog.active_alerts(),
            "recent": srv.watchdog.recent_alerts(
                limit=min(200, max(1, req.int_arg("limit", 50)))
            ),
            "rules": RULE_CATALOG,
        }

    @app.route("/api/telemetry", methods=("POST",))
    def telemetry_push(req: Request):
        """Fleet push ingest: daemons and Federation processes POST their
        compact telemetry snapshot + flight-note deltas here (wire-v2
        blob, base64 in a JSON envelope — see `common.fleet.encode_push`).
        Samples land as CAS-free appends in the fleet tables, so pushing
        through ANY replica of a shared store feeds the same fleet view.
        Any authenticated principal may push: nodes push their daemon's
        snapshot, users push a Federation's — the payload carries
        aggregate counters and ops notes, never secrets."""
        _identity(srv, req)
        from vantage6_tpu.common.fleet import decode_push
        from vantage6_tpu.server import fleet

        body = req.json
        if not isinstance(body, dict):
            raise HTTPError(400, "body must be a JSON object")
        try:
            payload = decode_push(body)
        except ValueError as e:
            from vantage6_tpu.common.telemetry import REGISTRY

            REGISTRY.counter("v6t_fleet_ingest_rejects_total").inc()
            raise HTTPError(400, f"undecodable telemetry push: {e}") from None
        return {"accepted": True, **fleet.ingest(srv.db, payload)}, 201

    @app.route("/api/fleet")
    def fleet_index(req: Request):
        """The aggregated fleet view: per-source freshness, the merged
        counter/gauge census, top-k counter deltas over the SLO fast
        window, recent cross-host events, and the daemon-liveness ratio.
        Read from the shared store, so every replica serves the SAME
        answer. Unauthenticated like /api/health and /api/metrics — it
        carries aggregate operational state only, never payloads or
        principals."""
        from vantage6_tpu.server import fleet

        return fleet.fleet_view(srv.db)

    @app.route("/api/rounds")
    def rounds_index(req: Request):
        """Learning-plane index: every task the process learning registry
        tracks, with its convergence summary (rounds, first/last/peak
        pooled-update norm, decay, per-station contribution table).
        Unauthenticated like /api/alerts — it carries aggregate update
        STATISTICS (norms, cosines), never payloads or principals."""
        from vantage6_tpu.runtime.learning import LEARNING

        return {"tasks": LEARNING.summaries()}

    @app.route("/api/rounds/<int:id>")
    def rounds_for_task(req: Request, id: int):
        """One task's learning-plane round history: per-round loss, the
        pooled update norm (convergence trajectory), and per-station
        norms/cosines/EF mass — what the `anomalous_station` /
        `non_convergence` / `model_divergence` watchdog rules read, served
        raw so an operator (or the doctor) can see WHY an alert fired.
        404 for tasks the learning registry never tracked (host-mode
        tasks without an engine/aggregation recording). Served from the
        MERGED view: on a shared backend, rounds recorded via other
        replicas (per-round subtasks land wherever the daemon's poll
        lands) are part of this task's one trajectory."""
        from vantage6_tpu.runtime.learning import LEARNING

        hist = LEARNING.merged(id)
        if hist is None:
            raise HTTPError(
                404,
                f"no learning-plane history for task {id} (not an "
                "engine/aggregated task, or evicted)",
            )
        limit = min(512, max(1, req.int_arg("limit", 128)))
        return {
            "task_id": id,
            "summary": hist.summary(),
            "rounds": hist.rounds(limit=limit),
        }

    @app.route("/api/debug/dump", methods=("POST",))
    def debug_dump(req: Request):
        """Dump this server process's flight recorder to a JSONL bundle
        (crash forensics on demand — the REST twin of `kill -USR2`).
        User-only: each call writes a fresh file to server disk, so a
        compromised node/container credential must not be able to fill
        the disk one bundle at a time — operators dump, stations don't."""
        _require_user(srv, req)
        from vantage6_tpu.common.flight import FLIGHT

        path = FLIGHT.dump(reason="api")
        if path is None:
            raise HTTPError(500, "flight dump failed (disk unwritable?)")
        return {"path": path, "counts": FLIGHT.stats()}, 201

    @app.route("/api/debug/profile", methods=("POST",))
    def debug_profile(req: Request):
        """Open an on-demand jax.profiler window on THIS server process
        (body: ``{"seconds": 1.0}``, clamped server-side) and return the
        artifact path. The window is recorded as a ``device.profile``
        span inside the requesting trace (the handler runs in the joined
        request span) and registered in the flight recorder, so a later
        doctor of a bundle names where the Perfetto session lives.
        User-only like debug/dump — each call writes server disk and
        holds a worker for the window; operators profile, stations
        don't. 409 when a window is already open."""
        _require_user(srv, req)
        from vantage6_tpu.runtime.profiling import (
            ProfileBusyError,
            profile_window,
        )

        body = req.json
        if body is None:
            body = {}
        if not isinstance(body, dict):
            raise HTTPError(400, "body must be a JSON object")
        seconds = body.get("seconds", 1.0)
        if isinstance(seconds, bool) or not isinstance(seconds, (int, float)):
            raise HTTPError(400, "seconds must be a number")
        try:
            out = profile_window(float(seconds))
        except ProfileBusyError as e:
            raise HTTPError(409, str(e)) from None
        return out, 201

    @app.route("/api/metrics")
    def metrics(req: Request):
        """Prometheus text exposition of the unified telemetry registry:
        wire, REST, HTTP, executor-queue, event-hub, cache-hit and tracing
        series in one scrape (docs/observability.md). Unauthenticated by
        design, like /api/health — it carries aggregate counters only,
        never payloads or principals."""
        from vantage6_tpu.common.telemetry import (
            PROMETHEUS_CONTENT_TYPE,
            REGISTRY,
        )
        from vantage6_tpu.server.web import Response

        return Response(
            REGISTRY.render_prometheus(),
            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
        )

    @app.route("/api/version")
    def version(req: Request):
        from vantage6_tpu import __version__

        return {"version": __version__}

    # -------------------------------------------------------------- tokens
    @app.route("/api/token/user", methods=("POST",))
    def token_user(req: Request):
        body = sch.load(sch.TokenUserInput(), req.json)
        user = m.User.first(username=body["username"])
        if user is None:
            raise HTTPError(401, "invalid username or password")
        if user.is_locked_out():
            raise HTTPError(401, "account locked, retry later")
        if not user.check_password(body["password"]):
            user.record_login(False)
            raise HTTPError(401, "invalid username or password")
        if user.totp_secret:
            code = body.get("mfa_code")
            if not code or not verify_totp(user.totp_secret, code):
                user.record_login(False)
                raise HTTPError(401, "MFA code required or invalid")
        user.record_login(True)
        return {
            **srv.tokens.user_tokens(
                user.id,
                fingerprint=srv.tokens._credential_fingerprint(
                    user.password_hash, user.totp_secret
                ),
            ),
            "user": user.to_dict(),
        }

    @app.route("/api/token/node", methods=("POST",))
    def token_node(req: Request):
        body = sch.load(sch.TokenNodeInput(), req.json)
        node = m.Node.by_api_key(body["api_key"])
        if node is None:
            raise HTTPError(401, "invalid api key")
        return {**srv.tokens.node_tokens(node.id), "node": node.to_dict()}

    @app.route("/api/token/container", methods=("POST",))
    def token_container(req: Request):
        node = _require_node(srv, req)
        body = sch.load(sch.TokenContainerInput(), req.json)
        task = _get_or_404(m.Task, body["task_id"])
        if task.collaboration_id != node.collaboration_id:
            raise HTTPError(403, "task is not in this node's collaboration")
        return {
            "container_token": srv.tokens.container_token(
                node_id=node.id,
                task_id=task.id,
                image=body["image"],
                organization_id=node.organization_id,
            )
        }

    @app.route("/api/token/refresh", methods=("POST",))
    def token_refresh(req: Request):
        body = sch.load(sch.RefreshInput(), req.json)
        try:
            sub, claims = srv.tokens.identity_claims(
                body["refresh_token"], use="refresh"
            )
        except AuthError as e:
            raise HTTPError(401, str(e)) from None
        if sub["type"] == "user":
            user = m.User.get(sub["id"])
            if user is None:
                raise HTTPError(401, "unknown user")
            if not srv.tokens.fingerprint_ok(
                claims, user.password_hash, user.totp_secret
            ):
                # a stolen refresh token must not outlive a password change
                raise HTTPError(
                    401, "token superseded by a credential change"
                )
            return srv.tokens.user_tokens(
                user.id,
                fingerprint=srv.tokens._credential_fingerprint(
                    user.password_hash, user.totp_secret
                ),
            )
        if sub["type"] == "node":
            return srv.tokens.node_tokens(sub["id"])
        raise HTTPError(401, "container tokens cannot be refreshed")

    # ------------------------------------------------------------- recovery
    # Parity: the reference's recover.py — password reset (and 2FA reset)
    # over emailed single-use tokens (SURVEY.md §2 item 7). Responses never
    # reveal whether an account exists.
    @app.route("/api/recover/lost", methods=("POST",))
    def recover_lost(req: Request):
        body = sch.load(sch.RecoverLostInput(), req.json)
        user = None
        if body.get("username"):
            user = m.User.first(username=body["username"])
        if user is None and body.get("email"):
            user = m.User.first(email=body["email"])
        if user is not None and user.email:
            token = srv.tokens.password_reset_token(
                user.id, user.password_hash, user.totp_secret
            )
            srv.mailer.send(
                user.email,
                "vantage6: password reset",
                "A password reset was requested for your account "
                f"{user.username!r}.\n\nReset token (valid "
                f"{int(srv.tokens.RESET_TTL // 60)} minutes, single use):\n\n"
                f"{token}\n\nIf you did not request this, ignore this mail.",
            )
        return {
            "msg": "if the account exists and has an email address, a "
            "reset token was sent"
        }

    @app.route("/api/recover/reset", methods=("POST",))
    def recover_reset(req: Request):
        body = sch.load(sch.RecoverResetInput(), req.json)
        user = _user_for_reset_token(srv, body["reset_token"])
        user.set_password(body["password"])
        user.failed_login_attempts = 0
        user.save()
        # the fingerprint rotation must bite NOW, not at cache TTL
        _invalidate(srv, "user", user.id)
        return {"msg": "password updated"}

    @app.route("/api/password/change", methods=("POST",))
    def password_change(req: Request):
        """Self-service password change (reference: /password/change):
        the CURRENT password is required even with a valid token, so a
        stolen session cannot silently take over the account. Wrong
        guesses feed the lockout counter — a token holder must not get a
        free password-guessing oracle (same stance as recover_2fa_lost) —
        and a successful change rotates the credential fingerprint, which
        kills every outstanding token including the attacker's."""
        user = _require_user(srv, req)
        body = sch.load(sch.PasswordChangeInput(), req.json)
        if user.is_locked_out():
            raise HTTPError(401, "account locked, retry later")
        if not user.check_password(body["current_password"]):
            user.record_login(False)
            raise HTTPError(401, "current password is incorrect")
        user.set_password(body["new_password"])
        user.failed_login_attempts = 0
        user.save()
        # every outstanding token (incl. a cached attacker session) dies now
        _invalidate(srv, "user", user.id)
        return {"msg": "password updated — all sessions are now invalid; "
                       "log in again"}

    @app.route("/api/recover/2fa/lost", methods=("POST",))
    def recover_2fa_lost(req: Request):
        """Lost authenticator: prove password, get an emailed reset token
        (the reference gates 2FA reset on the password the same way)."""
        body = sch.load(sch.TokenUserInput(), req.json)
        user = m.User.first(username=body["username"])
        if user is not None and not user.is_locked_out():
            if not user.check_password(body["password"]):
                # same lockout accounting as /api/token/user — this endpoint
                # must not be a password-guessing oracle outside the counter
                user.record_login(False)
            elif user.email:
                user.record_login(True)
                token = srv.tokens.password_reset_token(
                    user.id, user.password_hash, user.totp_secret
                )
                srv.mailer.send(
                    user.email,
                    "vantage6: two-factor reset",
                    f"Reset token for account {user.username!r}:\n\n{token}",
                )
        return {
            "msg": "if the credentials are valid and the account has an "
            "email address, a reset token was sent"
        }

    @app.route("/api/recover/2fa/reset", methods=("POST",))
    def recover_2fa_reset(req: Request):
        from vantage6_tpu.server.auth import generate_totp_secret

        body = sch.load(sch.Recover2FAResetInput(), req.json)
        user = _user_for_reset_token(srv, body["reset_token"])
        user.totp_secret = generate_totp_secret()
        user.save()
        _invalidate(srv, "user", user.id)
        # the new secret is returned ONCE for authenticator re-enrollment
        return {"totp_secret": user.totp_secret}

    # --------------------------------------------------------------- users
    @app.route("/api/user", methods=("GET", "POST"))
    def users(req: Request):
        user = _require_user(srv, req)
        if req.method == "GET":
            scope = pm.user_scope(user, "user", Operation.VIEW)
            _check(scope is not None)
            rows = m.User.list()
            if scope != Scope.GLOBAL:
                rows = [
                    u
                    for u in rows
                    if u.organization_id == user.organization_id
                    or u.id == user.id
                ]
            return _paginate(req, rows)
        body = sch.load(sch.UserInput(), req.json)
        org_id = body["organization_id"] or user.organization_id
        _check(pm.allowed(user, "user", Operation.CREATE, organization_id=org_id))
        if m.User.first(username=body["username"]) is not None:
            raise HTTPError(409, "username taken")
        new = m.User(
            username=body["username"],
            email=body["email"],
            firstname=body["firstname"],
            lastname=body["lastname"],
            organization_id=org_id,
        )
        roles = _check_role_grant(user, body["roles"])
        new.set_password(body["password"])
        new.save()
        for role in roles:
            m.user_role.add(new.id, role.id)
        return new.to_dict(), 201

    @app.route("/api/user/<int:id>", methods=("GET", "PATCH", "DELETE"))
    def user_one(req: Request, id: int):
        user = _require_user(srv, req)
        target = _get_or_404(m.User, id)
        if req.method == "GET":
            _check(
                pm.allowed(
                    user, "user", Operation.VIEW,
                    organization_id=target.organization_id, owner_id=target.id,
                )
                or user.id == target.id
            )
            return target.to_dict()
        if req.method == "DELETE":
            _check(
                pm.allowed(
                    user, "user", Operation.DELETE,
                    organization_id=target.organization_id, owner_id=target.id,
                )
            )
            target.delete()
            _invalidate(srv, "user", target.id)
            return {}, 204
        _check(
            pm.allowed(
                user, "user", Operation.EDIT,
                organization_id=target.organization_id, owner_id=target.id,
            )
            or user.id == target.id
        )
        body = sch.load(sch.UserPatch(), req.json)
        for field in ("email", "firstname", "lastname"):
            if body[field] is not None:
                setattr(target, field, body[field])
        if body["password"]:
            target.set_password(body["password"])
        if body["roles"] is not None:
            # assigning roles is an admin action even on yourself
            _check(
                pm.allowed(
                    user, "user", Operation.EDIT,
                    organization_id=target.organization_id,
                )
            )
            roles = _check_role_grant(user, body["roles"])
            for rid in set(target.role_ids()):
                m.user_role.remove(target.id, rid)
            for role in roles:
                m.user_role.add(target.id, role.id)
        target.save()
        # fields/credentials/roles may all have changed: drop cached tokens
        _invalidate(srv, "user", target.id)
        return target.to_dict()

    # ------------------------------------------------------- organizations
    @app.route("/api/organization", methods=("GET", "POST"))
    def organizations(req: Request):
        kind, principal = _identity(srv, req)
        if req.method == "GET":
            if kind == "user":
                scope = pm.user_scope(principal, "organization", Operation.VIEW)
                _check(scope is not None)
                rows = m.Organization.list()
                if scope == Scope.ORGANIZATION:
                    rows = [
                        o for o in rows if o.id == principal.organization_id
                    ]
                elif scope == Scope.COLLABORATION:
                    visible: set[int] = {principal.organization_id}
                    for cid in _visible_collab_ids(
                        srv, principal.organization_id
                    ):
                        visible.update(
                            m.Collaboration.get(cid).organization_ids()
                        )
                    rows = [o for o in rows if o.id in visible]
                return _paginate(req, rows)
            # nodes/containers see their collaboration's organizations (needed
            # for task fan-out and E2E encryption pubkeys)
            collab_id = (
                principal.collaboration_id
                if kind == "node"
                else _container_task(principal).collaboration_id
            )
            ids = m.Collaboration.get(collab_id).organization_ids()
            rows = [o for o in m.Organization.list() if o.id in ids]
            return _paginate(req, rows)
        user = _require_user(srv, req)
        _check(pm.user_scope(user, "organization", Operation.CREATE) == Scope.GLOBAL)
        body = sch.load(sch.OrganizationInput(), req.json)
        org = m.Organization(**body).save()
        return org.to_dict(), 201

    @app.route("/api/organization/<int:id>", methods=("GET", "PATCH"))
    def organization_one(req: Request, id: int):
        kind, principal = _identity(srv, req)
        org = _get_or_404(m.Organization, id)
        if req.method == "GET":
            if kind == "user":
                _check(
                    pm.allowed(
                        principal, "organization", Operation.VIEW,
                        organization_id=org.id,
                    )
                    or any(
                        org.id
                        in m.Collaboration.get(cid).organization_ids()
                        for cid in _visible_collab_ids(
                            srv, principal.organization_id
                        )
                    )
                )
            else:
                # nodes/containers: own org or a fellow collaboration member
                collab_id = (
                    principal.collaboration_id
                    if kind == "node"
                    else _container_task(principal).collaboration_id
                )
                own_org = (
                    principal.organization_id
                    if kind == "node"
                    else principal["organization_id"]
                )
                _check(
                    org.id == own_org
                    or org.id
                    in m.Collaboration.get(collab_id).organization_ids()
                )
            return org.to_dict()
        if kind == "node":
            # a node registers/rotates its OWN organization's public key
            # (reference: node start uploads the org pubkey) — nothing else
            _check(principal.organization_id == org.id)
            body = sch.load(sch.OrganizationPatch(), req.json)
            if body.get("public_key") is not None:
                org.public_key = body["public_key"]
                org.save()
            return org.to_dict()
        user = _require_user(srv, req)
        _check(
            pm.allowed(user, "organization", Operation.EDIT, organization_id=org.id)
        )
        body = sch.load(sch.OrganizationPatch(), req.json)
        for field, value in body.items():
            if value is not None:
                setattr(org, field, value)
        org.save()
        return org.to_dict()

    # ------------------------------------------------------ collaborations
    @app.route("/api/collaboration", methods=("GET", "POST"))
    def collaborations(req: Request):
        kind, principal = _identity(srv, req)
        if req.method == "GET":
            rows = m.Collaboration.list()
            if kind == "user":
                scope = pm.user_scope(principal, "collaboration", Operation.VIEW)
                _check(scope is not None)
                if scope != Scope.GLOBAL:
                    rows = [
                        c
                        for c in rows
                        if principal.organization_id in c.organization_ids()
                    ]
            elif kind == "node":
                rows = [c for c in rows if c.id == principal.collaboration_id]
            else:
                raise HTTPError(403)
            return _paginate(req, rows)
        user = _require_user(srv, req)
        _check(
            pm.user_scope(user, "collaboration", Operation.CREATE) == Scope.GLOBAL
        )
        body = sch.load(sch.CollaborationInput(), req.json)
        collab = m.Collaboration(
            name=body["name"], encrypted=body["encrypted"]
        ).save()
        for oid in body["organization_ids"]:
            collab.add_organization(_get_or_404(m.Organization, oid))
        _invalidate(srv, "collaboration")
        return collab.to_dict(), 201

    @app.route("/api/collaboration/<int:id>", methods=("GET", "PATCH", "DELETE"))
    def collaboration_one(req: Request, id: int):
        kind, principal = _identity(srv, req)
        collab = _get_or_404(m.Collaboration, id)
        if req.method == "GET":
            if kind == "user":
                _check(
                    pm.allowed(
                        principal, "collaboration", Operation.VIEW,
                        collaboration_id=collab.id,
                        organization_id=principal.organization_id
                        if principal.organization_id in collab.organization_ids()
                        else None,
                    )
                )
            elif kind == "node":
                _check(principal.collaboration_id == collab.id)
            else:  # container: its own collaboration only
                _check(
                    _container_task(principal).collaboration_id == collab.id
                )
            return collab.to_dict()
        user = _require_user(srv, req)
        if req.method == "DELETE":
            _check(
                pm.user_scope(user, "collaboration", Operation.DELETE)
                == Scope.GLOBAL
            )
            collab.delete()
            _invalidate(srv, "collaboration")
            return {}, 204
        _check(
            pm.allowed(
                user, "collaboration", Operation.EDIT, collaboration_id=collab.id
            )
        )
        body = sch.load(sch.CollaborationInput(partial=True), req.json)
        if body.get("name"):
            collab.name = body["name"]
        if "encrypted" in body:
            collab.encrypted = body["encrypted"]
        collab.save()
        if body.get("organization_ids"):
            for oid in body["organization_ids"]:
                collab.add_organization(_get_or_404(m.Organization, oid))
            _invalidate(srv, "collaboration")
        return collab.to_dict()

    # -------------------------------------------------------------- studies
    @app.route("/api/study", methods=("GET", "POST"))
    def studies(req: Request):
        user = _require_user(srv, req)
        if req.method == "GET":
            scope = pm.user_scope(user, "study", Operation.VIEW)
            _check(scope is not None)
            rows = m.Study.list()
            if scope != Scope.GLOBAL:
                rows = [
                    s
                    for s in rows
                    if user.organization_id
                    in m.Collaboration.get(s.collaboration_id).organization_ids()
                ]
            return _paginate(req, rows)
        body = sch.load(sch.StudyInput(), req.json)
        collab = _get_or_404(m.Collaboration, body["collaboration_id"])
        _check(
            pm.allowed(
                user, "study", Operation.CREATE, collaboration_id=collab.id
            )
        )
        study = m.Study(name=body["name"], collaboration_id=collab.id).save()
        for oid in body["organization_ids"]:
            if oid not in collab.organization_ids():
                raise HTTPError(400, f"organization {oid} not in collaboration")
            study.add_organization(_get_or_404(m.Organization, oid))
        return study.to_dict(), 201

    @app.route("/api/study/<int:id>", methods=("GET", "DELETE"))
    def study_one(req: Request, id: int):
        user = _require_user(srv, req)
        study = _get_or_404(m.Study, id)
        if req.method == "GET":
            _check(
                pm.allowed(
                    user, "study", Operation.VIEW,
                    collaboration_id=study.collaboration_id,
                )
            )
            return study.to_dict()
        _check(
            pm.allowed(
                user, "study", Operation.DELETE,
                collaboration_id=study.collaboration_id,
            )
        )
        study.delete()
        return {}, 204

    # ------------------------------------------------------------- sessions
    def _session_visible(user: m.User, s: m.Session) -> bool:
        if (s.scope or "collaboration") == "own" and s.owner_id != user.id:
            return False
        return pm.allowed(
            user, "session", Operation.VIEW,
            collaboration_id=s.collaboration_id, owner_id=s.owner_id,
        )

    @app.route("/api/session", methods=("GET", "POST"))
    def sessions(req: Request):
        user = _require_user(srv, req)
        if req.method == "GET":
            rows = [
                s for s in m.Session.list() if _session_visible(user, s)
            ]
            return _paginate(req, rows)
        body = sch.load(sch.SessionInput(), req.json)
        collab = _get_or_404(m.Collaboration, body["collaboration_id"])
        _check(
            pm.allowed(
                user, "session", Operation.CREATE,
                collaboration_id=collab.id,
            )
        )
        if body["study_id"] is not None:
            study = _get_or_404(m.Study, body["study_id"])
            if study.collaboration_id != collab.id:
                raise HTTPError(400, "study not in collaboration")
        session = m.Session(
            name=body["name"],
            collaboration_id=collab.id,
            study_id=body["study_id"],
            owner_id=user.id,
            scope=body["scope"],
        ).save()
        return session.to_dict(), 201

    @app.route("/api/session/<int:id>", methods=("GET", "DELETE"))
    def session_one(req: Request, id: int):
        kind, principal = _identity(srv, req)
        session = _get_or_404(m.Session, id)
        if req.method == "GET":
            if kind == "node":
                # nodes probe session existence to reconcile their local
                # stores after downtime (a 404 means: drop the store)
                _check(
                    principal.collaboration_id == session.collaboration_id
                )
                return session.to_dict()
            _check(kind == "user")
            _check(_session_visible(principal, session))
            return session.to_dict()
        user = _require_user(srv, req)
        _check(
            pm.allowed(
                user, "session", Operation.DELETE,
                collaboration_id=session.collaboration_id,
                owner_id=session.owner_id,
            )
        )
        for df in session.dataframes():
            df.delete()
        session.delete()
        # nodes drop their local stores on this event
        srv.hub.emit(
            ev.SESSION_DELETED,
            {"session_id": id},
            room=ev.collaboration_room(session.collaboration_id),
        )
        return {}, 204

    @app.route("/api/session/<int:id>/dataframe", methods=("GET",))
    def session_dataframes(req: Request, id: int):
        user = _require_user(srv, req)
        session = _get_or_404(m.Session, id)
        _check(_session_visible(user, session))
        return _paginate(req, session.dataframes())

    @app.route("/api/session/<int:id>/dataframe/<handle>", methods=("PATCH",))
    def session_dataframe_patch(req: Request, id: int, handle: str):
        """Nodes report materialization: ready flag + column metadata.
        Content never crosses this endpoint — bookkeeping only."""
        kind, principal = _identity(srv, req)
        session = _get_or_404(m.Session, id)
        df = m.SessionDataframe.first(session_id=id, handle=handle)
        if df is None:
            raise HTTPError(404, f"session has no dataframe {handle!r}")
        if kind == "node":
            if principal.collaboration_id != session.collaboration_id:
                raise HTTPError(403, "node outside session collaboration")
        else:
            raise HTTPError(403, "only nodes report dataframe state")
        body = sch.load(sch.SessionDataframePatch(), req.json)
        if body["ready"]:
            # ready means "EVERY node has materialized it": each node
            # reports after completing its extraction run, so recompute
            # from the task's run statuses — the LAST reporter flips it
            task = m.Task.get(df.last_task_id) if df.last_task_id else None
            runs = task.runs() if task else []
            df.ready = bool(runs) and all(
                r.status == TaskStatus.COMPLETED.value for r in runs
            )
        elif body["ready"] is not None:
            df.ready = False
        if body["columns"] is not None:
            df.columns = body["columns"]
        df.save()
        return df.to_dict()

    # ---------------------------------------------------------------- nodes
    @app.route("/api/node", methods=("GET", "POST"))
    def nodes(req: Request):
        kind, principal = _identity(srv, req)
        if req.method == "GET":
            rows = m.Node.list()
            if kind == "user":
                scope = pm.user_scope(principal, "node", Operation.VIEW)
                _check(scope is not None)
                if scope == Scope.ORGANIZATION:
                    rows = [
                        n
                        for n in rows
                        if n.organization_id == principal.organization_id
                    ]
                elif scope == Scope.COLLABORATION:
                    rows = [
                        n
                        for n in rows
                        if principal.organization_id
                        in m.Collaboration.get(n.collaboration_id).organization_ids()
                    ]
            elif kind == "node":
                rows = [
                    n
                    for n in rows
                    if n.collaboration_id == principal.collaboration_id
                ]
            else:
                raise HTTPError(403)
            return _paginate(req, rows)
        user = _require_user(srv, req)
        body = sch.load(sch.NodeInput(), req.json)
        org_id = body["organization_id"] or user.organization_id
        collab = _get_or_404(m.Collaboration, body["collaboration_id"])
        if org_id not in collab.organization_ids():
            raise HTTPError(400, "organization is not in the collaboration")
        _check(pm.allowed(user, "node", Operation.CREATE, organization_id=org_id))
        if _node_for_org(collab.id, org_id) is not None:
            raise HTTPError(409, "node already exists for this org+collaboration")
        api_key = m.Node.generate_api_key()
        node = m.Node(
            name=body["name"]
            or f"{m.Organization.get(org_id).name} {collab.name} node",
            organization_id=org_id,
            collaboration_id=collab.id,
            station_index=body["station_index"],
            status="offline",
        )
        node.set_api_key(api_key)
        node.save()
        # the api key is returned exactly once, at creation
        return {**node.to_dict(), "api_key": api_key}, 201

    @app.route("/api/node/<int:id>", methods=("GET", "PATCH", "DELETE"))
    def node_one(req: Request, id: int):
        kind, principal = _identity(srv, req)
        node = _get_or_404(m.Node, id)
        if req.method == "GET":
            if kind == "user":
                _check(
                    pm.allowed(
                        principal, "node", Operation.VIEW,
                        organization_id=node.organization_id,
                        collaboration_id=node.collaboration_id,
                    )
                )
            elif kind == "node":
                _check(node.collaboration_id == principal.collaboration_id)
            else:  # container: nodes of its own collaboration only
                _check(
                    node.collaboration_id
                    == _container_task(principal).collaboration_id
                )
            return node.to_dict()
        if kind == "node":
            # a node may PATCH its own status (online/offline heartbeat) —
            # nothing else
            _check(req.method == "PATCH" and principal.id == node.id)
            status = (req.json or {}).get("status")
            if status in ("online", "offline"):
                _set_node_status(srv, node, status)
            return node.to_dict()
        user = _require_user(srv, req)
        if req.method == "DELETE":
            _check(
                pm.allowed(
                    user, "node", Operation.DELETE,
                    organization_id=node.organization_id,
                )
            )
            node.delete()
            _invalidate(srv, "node", node.id)
            return {}, 204
        _check(
            pm.allowed(
                user, "node", Operation.EDIT,
                organization_id=node.organization_id,
            )
        )
        name = (req.json or {}).get("name")
        if name:
            node.name = name
            node.save()
        return node.to_dict()

    # ---------------------------------------------------------------- tasks
    @app.route("/api/task", methods=("GET", "POST"))
    def tasks(req: Request):
        kind, principal = _identity(srv, req)
        if req.method == "GET":
            if kind == "user":
                scope = pm.user_scope(principal, "task", Operation.VIEW)
                _check(scope is not None)
                rows = m.Task.list()
                if scope != Scope.GLOBAL:
                    visible_collabs = _visible_collab_ids(
                        srv, principal.organization_id
                    )
                    rows = [
                        t
                        for t in rows
                        if t.collaboration_id in visible_collabs
                        or t.init_user_id == principal.id
                    ]
            elif kind == "node":
                rows = m.Task.list(collaboration_id=principal.collaboration_id)
            else:
                # container: its own task tree (job) only — a malicious
                # algorithm must not enumerate other tasks' inputs/results
                # across the collaboration
                rows = m.Task.list(job_id=_container_task(principal).job_id)
            return _paginate(req, rows)
        return _create_task(srv, req)

    @app.route("/api/task/<int:id>", methods=("GET", "DELETE"))
    def task_one(req: Request, id: int):
        kind, principal = _identity(srv, req)
        task = _get_or_404(m.Task, id)
        if req.method == "GET":
            if kind == "user":
                _check(
                    pm.allowed(
                        principal, "task", Operation.VIEW,
                        collaboration_id=task.collaboration_id,
                        owner_id=task.init_user_id,
                    )
                )
            elif kind == "node":
                _check(task.collaboration_id == principal.collaboration_id)
            else:  # container: its own task tree (job) only
                _check(task.job_id == _container_task(principal).job_id)
            return task.to_dict()
        user = _require_user(srv, req)
        _check(
            pm.allowed(
                user, "task", Operation.DELETE,
                collaboration_id=task.collaboration_id,
                owner_id=task.init_user_id,
            )
        )
        for run in task.runs():
            run.delete()
        task.delete()
        return {}, 204

    @app.route("/api/task/<int:id>/run", methods=("GET",))
    def task_runs(req: Request, id: int):
        kind, principal = _identity(srv, req)
        task = _get_or_404(m.Task, id)
        if kind == "user":
            _check(
                pm.allowed(
                    principal, "run", Operation.VIEW,
                    collaboration_id=task.collaboration_id,
                    owner_id=task.init_user_id,
                )
            )
        runs = task.runs()
        if kind == "node":
            # same policy as GET /api/run: a node sees only its own org's
            # runs (others' inputs/results are not its business)
            _check(task.collaboration_id == principal.collaboration_id)
            if (task.engine or "process") == "device":
                # collective coordination: a member daemon decides whether
                # to ENTER the SPMD program by watching every peer run's
                # status (node._await_device_peers). Statuses are shared
                # with all member nodes; payloads stay private — redact
                # input/result/log.
                start = (req.page - 1) * req.per_page
                return {
                    "data": [
                        {
                            "id": r.id,
                            "task": {"id": r.task_id},
                            "organization": {"id": r.organization_id},
                            "node": {"id": r.node_id},
                            "status": r.status,
                            "assigned_at": r.assigned_at,
                            "started_at": r.started_at,
                            "finished_at": r.finished_at,
                        }
                        for r in runs[start : start + req.per_page]
                    ],
                    "pagination": {
                        "page": req.page,
                        "per_page": req.per_page,
                        "total": len(runs),
                    },
                }
            runs = [
                r for r in runs if r.organization_id == principal.organization_id
            ]
        elif kind == "container":
            # own task tree (job) only, mirroring GET /api/run
            _check(task.job_id == _container_task(principal).job_id)
        return _paginate(req, runs)

    @app.route("/api/kill/task", methods=("POST",))
    def kill_task(req: Request):
        user = _require_user(srv, req)
        task_id = (req.json or {}).get("task_id")
        if not task_id:
            raise HTTPError(400, "task_id required")
        task = _get_or_404(m.Task, task_id)
        _check(
            pm.allowed(
                user, "task", Operation.EDIT,
                collaboration_id=task.collaboration_id,
                owner_id=task.init_user_id,
            )
        )
        killed = []
        for run in task.runs():
            if run.status not in (
                TaskStatus.COMPLETED.value,
                TaskStatus.FAILED.value,
                TaskStatus.CRASHED.value,
            ):
                run.status = TaskStatus.KILLED.value
                run.finished_at = time.time()
                run.save()
                killed.append(run.id)
                node = _node_for_org(task.collaboration_id, run.organization_id)
                if node:
                    srv.hub.emit(
                        ev.KILL_TASK,
                        {"task_id": task.id, "run_id": run.id},
                        room=ev.node_room(node.id),
                    )
        return {"killed_runs": killed}

    # ----------------------------------------------------------------- runs
    @app.route("/api/run", methods=("GET",))
    def runs(req: Request):
        kind, principal = _identity(srv, req)
        task_id = req.int_arg("task_id")
        where: dict[str, Any] = {}
        if task_id is not None:
            where["task_id"] = task_id
        status = req.arg("status")
        if status is not None:
            where["status"] = status
        rows = m.TaskRun.list(**where)
        # request-scoped task memo: the visibility filters below resolve
        # the task of EVERY run — without this, a busy listing is an N+1
        # query storm (one Task.get per run, most of them duplicates)
        tasks: dict[int, m.Task | None] = {}

        def _task_of(r: m.TaskRun) -> m.Task | None:
            if r.task_id not in tasks:
                tasks[r.task_id] = m.Task.get(r.task_id)
            return tasks[r.task_id]

        if kind == "user":
            scope = pm.user_scope(principal, "run", Operation.VIEW)
            _check(scope is not None)
            if scope != Scope.GLOBAL:
                visible = _visible_collab_ids(srv, principal.organization_id)
                rows = [
                    r
                    for r in rows
                    if (t := _task_of(r)) is not None
                    and t.collaboration_id in visible
                ]
        elif kind == "node":
            # org AND collaboration: a node is per (org, collaboration), and
            # a sibling node of the same org in another collaboration must
            # not see (or reclaim — daemon._sync_missed_runs) these runs
            rows = [
                r for r in rows
                if r.organization_id == principal.organization_id
                and (t := _task_of(r)) is not None
                and t.collaboration_id == principal.collaboration_id
            ]
        else:  # container: runs of its own task tree (job) only
            own_job = _container_task(principal).job_id
            job_tasks = {t.id for t in m.Task.list(job_id=own_job)}
            rows = [r for r in rows if r.task_id in job_tasks]
        return _paginate(req, rows)

    @app.route("/api/run/<int:id>", methods=("GET", "PATCH"))
    def run_one(req: Request, id: int):
        kind, principal = _identity(srv, req)
        run = _get_or_404(m.TaskRun, id)
        task = m.Task.get(run.task_id)
        if req.method == "GET":
            if kind == "user":
                _check(
                    pm.allowed(
                        principal, "run", Operation.VIEW,
                        collaboration_id=task.collaboration_id,
                        owner_id=task.init_user_id,
                    )
                )
            elif kind == "node":
                _check(
                    run.organization_id == principal.organization_id
                    and task.collaboration_id == principal.collaboration_id
                )
            else:  # container: its own task tree (job) only
                _check(task.job_id == _container_task(principal).job_id)
            return run.to_dict()
        # PATCH: only the executing node updates status/result (org AND
        # collaboration — same scoping as the node's run listing)
        node = _require_node(srv, req)
        body = sch.load(sch.RunPatch(), req.json)
        return _apply_run_patch(srv, node, run, task, body)

    @app.route("/api/run/claim-batch", methods=("POST",))
    def run_claim_batch(req: Request):
        """Batched node dispatch: the whole claim sweep in ONE request.

        Sweep mode (no `run_ids`): optionally re-queue this node's
        INITIALIZING/ACTIVE orphans (excluding `exclude_run_ids` — the
        runs the daemon is executing right now), then return up to `max`
        claimable PENDING runs. Dispatch mode (`run_ids`): return exactly
        those runs if still pending and in scope. Either way each entry
        carries the run, its full task, and a pre-minted container token —
        collapsing the daemon's per-run GET run + GET task +
        POST token/container round-trips into none.

        "Claiming" mints no lease: runs stay PENDING until the daemon
        PATCHes them ACTIVE, exactly as on the per-run path, so an
        un-upgraded daemon (or a restarted one) interoperates unchanged —
        idempotency still comes from the daemon's claim set plus the
        terminal-status 409 guard.
        """
        node = _require_node(srv, req)
        body = sch.load(sch.ClaimBatchInput(), req.json)
        exclude = set(body["exclude_run_ids"] or [])
        tasks: dict[int, m.Task | None] = {}

        def _task_of(run: m.TaskRun) -> m.Task | None:
            if run.task_id not in tasks:
                tasks[run.task_id] = m.Task.get(run.task_id)
            return tasks[run.task_id]

        def _in_scope(run: m.TaskRun) -> bool:
            t = _task_of(run)
            return (
                t is not None
                and run.organization_id == node.organization_id
                and t.collaboration_id == node.collaboration_id
            )

        claimable: list[m.TaskRun] = []
        if body["run_ids"] is not None:
            # explicit dispatch: `exclude_run_ids` does not apply — the
            # daemon claims BEFORE fetching, so its own id is in there
            for rid in body["run_ids"][: body["max"]]:
                run = m.TaskRun.get(rid)
                # batch semantics: out-of-scope / non-pending entries are
                # silently skipped, not errors — the daemon treats absence
                # as "nothing to execute" (same as a non-pending GET run)
                if (
                    run is None
                    or not _in_scope(run)
                    or run.status != TaskStatus.PENDING.value
                ):
                    continue
                claimable.append(run)
        else:
            n_reset = 0
            if body["reset_orphans"]:
                for status in (TaskStatus.INITIALIZING, TaskStatus.ACTIVE):
                    for run in m.TaskRun.list(
                        status=status.value,
                        organization_id=node.organization_id,
                    ):
                        if run.id in exclude or not _in_scope(run):
                            continue
                        # compare-and-swap, not save(): between the
                        # listing and this write the run may have been
                        # COMPLETED by a concurrent report — or ACTIVATED
                        # by the daemon through ANOTHER replica. A stale
                        # full-row save would clobber the result or
                        # re-queue live work; the status guard makes the
                        # reset atomic, and a False return means someone
                        # else moved the run on — leave it alone.
                        if not m.TaskRun.compare_and_swap(
                            run.id,
                            sets={
                                "status": TaskStatus.PENDING.value,
                                "log": (
                                    "orphaned mid-run (daemon restart or "
                                    "lost report); re-queued by claim-batch"
                                ),
                            },
                            expect={"status": status.value},
                        ):
                            continue
                        n_reset += 1
                        task = _task_of(run)
                        srv.hub.emit(
                            ev.STATUS_UPDATE,
                            {
                                "task_id": task.id,
                                "run_id": run.id,
                                "status": TaskStatus.PENDING.value,
                                "organization_id": run.organization_id,
                                "task_status": task.status(),
                            },
                            room=ev.collaboration_room(task.collaboration_id),
                        )
            for run in m.TaskRun.list(
                status=TaskStatus.PENDING.value,
                organization_id=node.organization_id,
            ):
                if run.id in exclude or not _in_scope(run):
                    continue
                claimable.append(run)
                if len(claimable) >= body["max"]:
                    break
        data = []
        for run in claimable:
            task = _task_of(run)
            entry = run.to_dict()
            entry["task"] = task.to_dict()
            entry["container_token"] = srv.tokens.container_token(
                node_id=node.id,
                task_id=task.id,
                image=task.image,
                organization_id=node.organization_id,
            )
            data.append(entry)
        out: dict[str, Any] = {"data": data}
        if body["run_ids"] is None and body["reset_orphans"]:
            out["n_reset"] = n_reset
        return out

    @app.route("/api/run/batch", methods=("PATCH",))
    def run_patch_batch(req: Request):
        """Batched status/result upload: N run PATCHes in one request,
        with PER-ITEM outcomes (200/403/404/409 + msg) so one conflicting
        run — e.g. killed mid-execution — doesn't fail its batch-mates.
        Semantics per item are EXACTLY `PATCH /api/run/<id>`, including
        terminal-state immutability and the status-update event."""
        node = _require_node(srv, req)
        body = sch.load(sch.RunBatchPatch(), req.json)
        results = []
        for item in body["runs"]:
            rid = item["id"]
            run = m.TaskRun.get(rid)
            if run is None:
                results.append(
                    {"id": rid, "status_code": 404, "msg": "not found"}
                )
                continue
            task = m.Task.get(run.task_id)
            try:
                _apply_run_patch(srv, node, run, task, item)
            except HTTPError as e:
                results.append(
                    {"id": rid, "status_code": e.status, "msg": e.msg}
                )
                continue
            results.append({"id": rid, "status_code": 200})
        return {"data": results}

    # ------------------------------------------------------------ rbac views
    @app.route("/api/role", methods=("GET", "POST"))
    def roles(req: Request):
        user = _require_user(srv, req)
        if req.method == "GET":
            _check(pm.user_scope(user, "role", Operation.VIEW) is not None)
            return _paginate(req, m.Role.list())
        body = sch.load(sch.RoleInput(), req.json)
        org_id = body["organization_id"]
        _check(
            pm.allowed(user, "role", Operation.CREATE, organization_id=org_id)
            if org_id
            else pm.user_scope(user, "role", Operation.CREATE) == Scope.GLOBAL
        )
        role = m.Role(
            name=body["name"],
            description=body["description"],
            organization_id=org_id,
        ).save()
        _grant_role_rules(user, role, body["rules"])
        return role.to_dict(), 201

    @app.route("/api/role/<int:id>", methods=("GET", "PATCH", "DELETE"))
    def role_one(req: Request, id: int):
        user = _require_user(srv, req)
        role = _get_or_404(m.Role, id)
        if req.method == "GET":
            _check(pm.user_scope(user, "role", Operation.VIEW) is not None)
            return role.to_dict()
        op = Operation.EDIT if req.method == "PATCH" else Operation.DELETE
        _check(
            pm.allowed(user, "role", op, organization_id=role.organization_id)
            if role.organization_id
            else pm.user_scope(user, "role", op) == Scope.GLOBAL
        )
        if req.method == "DELETE":
            role.delete()
            # the role's rules reached arbitrarily many users: global evict
            _invalidate(srv, "role")
            return {}, 204
        body = sch.load(sch.RolePatch(), req.json)
        for field in ("name", "description"):
            if body[field] is not None:
                setattr(role, field, body[field])
        if body["rules"] is not None:
            _grant_role_rules(user, role, body["rules"], replace=True)
            _invalidate(srv, "role")
        role.save()
        return role.to_dict()

    @app.route("/api/rule", methods=("GET",))
    def rules(req: Request):
        _require_user(srv, req)
        return _paginate(req, m.Rule.list())

    # ---------------------------------------------------------------- ports
    @app.route("/api/port", methods=("GET", "POST"))
    def ports(req: Request):
        kind, principal = _identity(srv, req)
        if req.method == "GET":
            run_id = req.int_arg("run_id")
            where = {"run_id": run_id} if run_id is not None else {}
            rows = m.Port.list(**where)
            # request-scoped run→collaboration memo (ports of one run share
            # the same resolution; previously two queries PER PORT)
            port_collabs: dict[int, int | None] = {}

            def _collab_of(p: m.Port) -> int | None:
                if p.run_id not in port_collabs:
                    run = m.TaskRun.get(p.run_id)
                    task = m.Task.get(run.task_id) if run else None
                    port_collabs[p.run_id] = (
                        task.collaboration_id if task else None
                    )
                return port_collabs[p.run_id]

            # scope to collaborations the principal can see (port VIEW rule
            # for users; own collaboration for nodes/containers)
            if kind == "user":
                scope = pm.user_scope(principal, "port", Operation.VIEW)
                _check(scope is not None)
                if scope != Scope.GLOBAL:
                    visible = _visible_collab_ids(
                        srv, principal.organization_id
                    )
                    rows = [p for p in rows if _collab_of(p) in visible]
            else:
                own_collab = (
                    principal.collaboration_id
                    if kind == "node"
                    else _container_task(principal).collaboration_id
                )
                rows = [p for p in rows if _collab_of(p) == own_collab]
            return _paginate(req, rows)
        node = _require_node(srv, req)
        body = sch.load(sch.PortInput(), req.json)
        run = _get_or_404(m.TaskRun, body["run_id"])
        _check(run.organization_id == node.organization_id)
        port = m.Port(**body).save()
        return port.to_dict(), 201

    # ----------------------------------------------------------------- store
    @app.route("/api/store", methods=("GET",))
    def store_info(req: Request):
        """The linked algorithm store, if any (UI + clients discover it
        here instead of each needing their own store config)."""
        _identity(srv, req)
        return {"url": srv.store_url}

    def _store_forward(
        req: Request, path: str, *,
        params: dict[str, Any] | None = None,
        forward_auth: bool = True,
    ):
        """Same-origin proxy to the linked store, so the browser UI drives
        the FULL store workflow (submit → review → approve) without
        cross-origin requests or separate store credentials. The caller's
        bearer token is forwarded together with a ``Server-Url`` naming THIS
        server (derived from the request's Host — the URL the browser used
        IS the URL the store's trust handshake will call ``whoami`` on)."""
        _identity(srv, req)
        if not srv.store_url:
            raise HTTPError(404, "no algorithm store linked to this server")
        import requests

        headers: dict[str, str] = {}
        if forward_auth and req.bearer_token:
            host = req.headers.get("host")
            if host:
                proto = req.headers.get("x-forwarded-proto", "http")
                headers["Authorization"] = f"Bearer {req.bearer_token}"
                headers["Server-Url"] = f"{proto}://{host}"
        body = req.json if req.method in ("POST", "PATCH") else None
        try:
            resp = requests.request(
                req.method,
                f"{srv.store_url}/api/{path}",
                json=body,
                params=params or {},
                headers=headers,
                timeout=10,
            )
        except requests.RequestException as e:
            raise HTTPError(502, f"store unreachable: {e}") from None
        if resp.status_code >= 400:
            try:
                msg = resp.json().get("msg", "")
            except Exception:
                msg = resp.text[:200]
            raise HTTPError(resp.status_code, f"store: {msg}")
        data = {} if resp.status_code == 204 else resp.json()
        return data, resp.status_code

    @app.route("/api/store/algorithm", methods=("GET", "POST"))
    def store_algorithms(req: Request):
        """GET: the algorithm registry (token forwarded only when a status
        filter asks for non-public rows, so the default listing stays the
        approved set exactly as before). POST: submit an algorithm."""
        params = {
            k: req.arg(k)
            for k in ("status", "name")
            if req.arg(k) is not None
        }
        return _store_forward(
            req, "algorithm", params=params,
            forward_auth=req.method == "POST" or "status" in params,
        )

    @app.route("/api/store/algorithm/<int:id>", methods=("GET", "DELETE"))
    def store_algorithm_one(req: Request, id: int):
        return _store_forward(req, f"algorithm/{id}")

    @app.route("/api/store/algorithm/<int:id>/review", methods=("POST",))
    def store_start_review(req: Request, id: int):
        return _store_forward(req, f"algorithm/{id}/review")

    @app.route("/api/store/review", methods=("GET",))
    def store_reviews(req: Request):
        params = {}
        if req.int_arg("algorithm_id") is not None:
            params["algorithm_id"] = req.int_arg("algorithm_id")
        return _store_forward(req, "review", params=params)

    @app.route("/api/store/review/<int:id>", methods=("GET", "PATCH"))
    def store_review_one(req: Request, id: int):
        return _store_forward(req, f"review/{id}")

    # --------------------------------------------------------------- events
    # untimed: the ?wait=S long-poll blocks by design and must not skew
    # the v6t_http_request_seconds histogram (see web.App.route)
    @app.route("/api/event", methods=("GET",), untimed=True)
    def events_fetch(req: Request):
        """Cursor catch-up (reference: socket reconnect re-sync) — now
        long-poll capable: `?wait=S` blocks up to S seconds (capped at 25)
        until an event lands in one of the caller's rooms, waking
        IMMEDIATELY on emit. `long_poll: true` in the response is how
        clients detect the capability (an old server ignores the unknown
        param and returns at once, without the flag — callers then keep
        their fixed-interval sleeps). `truncated: true` means the bounded
        replay buffer evicted events past the caller's cursor: the caller
        MUST resync from primary state (runs/kills/sessions), not trust
        the event stream alone."""
        kind, principal = _identity(srv, req)
        since = req.int_arg("since", 0)
        raw_wait = req.arg("wait")
        try:
            wait = min(25.0, max(0.0, float(raw_wait))) if raw_wait else 0.0
        except ValueError:
            raise HTTPError(400, "query param 'wait' must be a number") \
                from None
        # optional comma-separated name filter: narrows BOTH the returned
        # events and (crucially) the long-poll wake set — a daemon must
        # not wake on every status-update flooding its collaboration room
        raw_names = req.arg("names")
        names = (
            {n for n in raw_names.split(",") if n} if raw_names else None
        )
        rooms = _rooms_for(srv, kind, principal)
        if since < 0:
            # cursor probe: "where is the stream NOW?" — lets a client
            # start tailing without replaying the whole buffer first
            events: list = []
            cursor, truncated = srv.hub.cursor, False
        else:
            # collect() pairs the cursor with the event snapshot
            # ATOMICALLY — cursor read after a separate fetch could cover
            # an event emitted in the gap without delivering it
            events, cursor, truncated = srv.hub.collect(
                since, rooms, timeout=wait, names=names
            )
        if truncated:
            # the watchdog's event_cursor_lag signal: a consumer ACTUALLY
            # asked for history the ring already evicted (eviction alone
            # is steady-state on any busy server and proves nothing)
            from vantage6_tpu.common.telemetry import REGISTRY

            REGISTRY.counter("v6t_event_truncated_total").inc()
        return {
            "cursor": cursor,
            "data": [e.to_dict() for e in events],
            "long_poll": True,
            "truncated": truncated,
        }

    @app.route("/api/whoami", methods=("GET",))
    def whoami(req: Request):
        """Identity introspection (the algorithm store's trust handshake
        validates a caller's token by asking the caller's server)."""
        kind, principal = _identity(srv, req)
        if kind == "user":
            return {"type": "user", **principal.to_dict()}
        if kind == "node":
            return {"type": "node", **principal.to_dict()}
        return {"type": "container", **principal}

    @app.route("/api/ping", methods=("POST",))
    def ping(req: Request):
        node = _require_node(srv, req)
        _set_node_status(srv, node, "online", quiet=True)
        return {"pong": time.time()}


# ------------------------------------------------------------- task creation


def _create_task(srv: "ServerApp", req: Request) -> tuple[dict[str, Any], int]:
    kind, principal = _identity(srv, req)
    body = sch.load(sch.TaskInput(), req.json)
    collab = m.Collaboration.get(body["collaboration_id"])
    if collab is None:
        raise HTTPError(404, "collaboration not found")

    parent_id = None
    job_id = None
    if kind == "user":
        _check(
            srv.pm.allowed(
                principal, "task", Operation.CREATE, collaboration_id=collab.id
            )
        )
        init_org_id = principal.organization_id
        init_user_id = principal.id
    elif kind == "container":
        # a running algorithm creates subtasks within its own task tree
        parent = _container_task(principal)
        if parent is None or parent.collaboration_id != collab.id:
            raise HTTPError(403, "subtask outside parent collaboration")
        if parent.image != body["image"]:
            raise HTTPError(403, "subtask must use the parent's algorithm")
        parent_id = parent.id
        job_id = parent.job_id
        init_org_id = principal["organization_id"]
        init_user_id = parent.init_user_id
    else:
        raise HTTPError(403, "nodes cannot create tasks")

    if srv.algorithm_policy is not None and not srv.algorithm_policy(body["image"]):
        raise HTTPError(403, f"algorithm {body['image']!r} not allowed by store policy")

    member_ids = collab.organization_ids()
    study_id = body["study_id"]
    if study_id is not None:
        study = m.Study.get(study_id)
        if study is None or study.collaboration_id != collab.id:
            raise HTTPError(400, "study not in collaboration")
        member_ids = study.organization_ids()

    org_specs = body["organizations"]
    for spec in org_specs:
        if "id" not in spec:
            raise HTTPError(400, 'each organization entry needs an "id"')
        if int(spec["id"]) not in member_ids:
            raise HTTPError(
                400, f"organization {spec['id']} not in collaboration/study"
            )

    # sessions: validate the workspace and any dataframe references; the
    # server only bookkeeps handles — content stays at the nodes
    session_id = body["session_id"]
    session = None
    if session_id is not None:
        session = m.Session.get(session_id)
        if session is None or session.collaboration_id != collab.id:
            raise HTTPError(400, "session not in collaboration")
        if kind == "user" and (session.scope or "collaboration") == "own" \
                and session.owner_id != principal.id:
            raise HTTPError(403, "session is private to its owner")
    handles = {d.handle for d in session.dataframes()} if session else set()
    for db in body["databases"] or []:
        if db.get("type") == "session":
            if session is None:
                raise HTTPError(
                    400, "session dataframe reference without session_id"
                )
            if not db.get("dataframe"):
                raise HTTPError(
                    400, 'session database entries need a "dataframe" handle'
                )
            if db["dataframe"] not in handles:
                raise HTTPError(
                    400,
                    f"session has no dataframe {db['dataframe']!r} "
                    f"(known: {sorted(handles)})",
                )
    store_as = body["store_as"]
    if store_as is not None:
        if session is None:
            raise HTTPError(400, "store_as requires a session_id")
        if not store_as.replace("_", "").replace("-", "").isalnum():
            raise HTTPError(400, "store_as must be a simple identifier")

    engine = body["engine"]
    if engine == "device":
        # a device-engine run is ONE collective SPMD program: every process
        # of the global device mesh must enter it, or the collectives hang.
        # The server enforces the coarse proxy it can see — the task targets
        # every organization of the COLLABORATION (not a study subset: the
        # mesh spans all member daemons, and a daemon outside the study
        # would never receive a run yet its process must join the program).
        targeted = {int(s["id"]) for s in org_specs}
        collab_members = set(collab.organization_ids())
        if targeted != collab_members or len(org_specs) != len(targeted):
            raise HTTPError(
                400,
                "device-engine tasks must target every organization of the "
                f"collaboration exactly once (targeted "
                f"{sorted(int(s['id']) for s in org_specs)}, members "
                f"{sorted(collab_members)}): the SPMD program is collective "
                "and a duplicate or missing run would hang it",
            )

    # distributed tracing: persist the creating request's trace context on
    # the task. The current context here is the server's own http span
    # (child of the client's traceparent header), so daemon claim/exec/
    # report spans parented on it chain client → server → daemon in one
    # trace. No ambient trace (old client, tracing off) → NULLs.
    from vantage6_tpu.runtime.tracing import TRACER

    trace_ctx = TRACER.current_context()
    task = m.Task(
        name=body["name"],
        description=body["description"],
        image=body["image"],
        method=body["method"],
        collaboration_id=collab.id,
        study_id=study_id,
        parent_id=parent_id,
        init_org_id=init_org_id,
        init_user_id=init_user_id,
        databases=body["databases"] or [{"label": "default"}],
        session_id=session_id,
        store_as=store_as,
        engine=engine,
        trace_id=trace_ctx.trace_id if trace_ctx else None,
        traceparent=trace_ctx.to_traceparent() if trace_ctx else None,
    ).save()
    if store_as is not None:
        df = m.SessionDataframe.first(
            session_id=session_id, handle=store_as
        )
        if df is None:
            df = m.SessionDataframe(
                session_id=session_id, handle=store_as
            )
        df.last_task_id = task.id
        df.ready = False
        df.save()
    if job_id is None:
        job_id = task.id  # a root task starts its own job group
    task.job_id = job_id
    task.save()

    method = body["method"]
    # the run fan-out + event emits ARE "server dispatch" — one span so
    # the timeline separates dispatch cost from the surrounding request
    with TRACER.span(
        "server.dispatch", kind="dispatch", service="server",
        attrs={"task_id": task.id, "n_runs": len(org_specs)},
        require_parent=True,
    ):
        for spec in org_specs:
            org_id = int(spec["id"])
            node = _node_for_org(collab.id, org_id)
            run = m.TaskRun(
                task_id=task.id,
                organization_id=org_id,
                node_id=node.id if node else None,
                status=TaskStatus.PENDING.value,
                input=spec.get("input", ""),
                assigned_at=time.time(),
            ).save()
            if node:
                srv.hub.emit(
                    ev.TASK_CREATED,
                    {
                        "task_id": task.id,
                        "run_id": run.id,
                        "method": method,
                        "image": task.image,
                        "organization_id": org_id,
                    },
                    room=ev.node_room(node.id),
                )
        srv.hub.emit(
            ev.TASK_CREATED,
            {"task_id": task.id, "image": task.image},
            room=ev.collaboration_room(collab.id),
        )
    return task.to_dict(), 201


# ------------------------------------------------------------------- helpers


def _observe_dispatch(srv: "ServerApp", run: m.TaskRun) -> None:
    """Assigned->started dispatch latency of one run, observed at the
    activation CAS: into the process histogram (scrape-grade) AND as a
    per-event fleet sample (store-backed — the dispatch-latency SLO's
    burn windows read these rows, from whichever replica served the
    activation). Telemetry must never fail a dispatch."""
    try:
        assigned = float(run.assigned_at or 0.0)
        if assigned <= 0.0:
            return
        started = float(run.started_at or time.time())
        lat = max(0.0, started - assigned)
        from vantage6_tpu.common.telemetry import REGISTRY
        from vantage6_tpu.server import fleet

        REGISTRY.histogram("v6t_run_dispatch_seconds").observe(lat)
        fleet.record_sample(
            srv.db, srv.replica_id, "server",
            "v6t_run_dispatch_seconds", lat,
        )
    except Exception:
        pass


def _apply_run_patch(
    srv: "ServerApp",
    node: m.Node,
    run: m.TaskRun,
    task: m.Task | None,
    body: dict[str, Any],
) -> dict[str, Any]:
    """The one node-updates-a-run core, shared by `PATCH /api/run/<id>`
    and the batched `PATCH /api/run/batch` (per item). Raises HTTPError;
    the batch endpoint maps that to a per-item outcome."""
    if task is None:
        raise HTTPError(404, "run's task no longer exists")
    _check(
        run.organization_id == node.organization_id
        and task.collaboration_id == node.collaboration_id
    )
    new_status = body["status"]
    if new_status:
        # status transitions are compare-and-swap on the status we READ:
        # with N replicas over one store, the check and the write must be
        # one atomic statement or two replicas interleave (the
        # double-dispatch hole). One winner; losers get 409. The guards:
        # terminal states are immutable (a node finishing late must not
        # overwrite KILLED or re-open a completed run), and activating an
        # already-ACTIVE run is a lost activation race — the 409 is what
        # makes the daemon drop the run instead of executing it twice.
        # (The container token minted at claim time is a stateless JWT;
        # THIS activation CAS is the dispatch serialization point.)
        for _attempt in range(2):
            cur_status = run.status
            if cur_status and TaskStatus(cur_status).is_finished:
                raise HTTPError(
                    409, f"run {run.id} already {cur_status}; cannot change"
                )
            if (
                new_status == TaskStatus.ACTIVE.value
                and cur_status == TaskStatus.ACTIVE.value
            ):
                raise HTTPError(
                    409,
                    f"run {run.id} already active "
                    "(activation race lost to another claimant)",
                )
            sets: dict[str, Any] = {"status": new_status}
            for field in ("result", "log", "started_at", "finished_at"):
                if body[field] is not None:
                    sets[field] = body[field]
            if run.node_id is None:
                sets["node_id"] = node.id
            if m.TaskRun.compare_and_swap(
                run.id, sets, expect={"status": cur_status}
            ):
                for k, v in sets.items():
                    setattr(run, k, v)
                if new_status == TaskStatus.ACTIVE.value:
                    # the activation CAS winner IS the dispatch: record
                    # assigned->started latency, the dispatch SLO's series
                    _observe_dispatch(srv, run)
                break
            # lost the race: re-read and re-decide against the NEW state
            reread = m.TaskRun.get(run.id)
            if reread is None:
                raise HTTPError(404, "run deleted mid-update")
            run = reread
        else:  # two lost races in a row: punt to the caller
            raise HTTPError(
                409, f"run {run.id} status contended; re-fetch and retry"
            )
    else:
        for field in ("result", "log", "started_at", "finished_at"):
            if body[field] is not None:
                setattr(run, field, body[field])
        run.save()
    if body["status"]:
        srv.hub.emit(
            ev.STATUS_UPDATE,
            {
                "task_id": task.id,
                "run_id": run.id,
                "status": run.status,
                "organization_id": run.organization_id,
                "task_status": task.status(),
            },
            room=ev.collaboration_room(task.collaboration_id),
        )
    return run.to_dict()


def _rooms_for(
    srv: "ServerApp", kind: str, principal: Any
) -> list[str] | None:
    """Event rooms for a principal; None = every room (operator view)."""
    if kind == "user":
        if (
            srv.pm.user_scope(principal, "event", Operation.RECEIVE)
            == Scope.GLOBAL
        ):
            # a global event-receive holder (root/operators) watches the
            # whole stream — membership rooms would hide every
            # collaboration their org hasn't joined, which for root is
            # ALL of them
            return None
        return [
            ev.collaboration_room(cid)
            for cid in sorted(
                _visible_collab_ids(srv, principal.organization_id)
            )
        ]
    if kind == "node":
        return [
            ev.node_room(principal.id),
            ev.collaboration_room(principal.collaboration_id),
        ]
    # container: its node's collaboration room
    task = _container_task(principal)
    return [ev.collaboration_room(task.collaboration_id)]


def _set_node_status(
    srv: "ServerApp", node: m.Node, status: str, quiet: bool = False
) -> None:
    changed = node.status != status
    node.status = status
    node.last_seen_at = time.time()
    node.save()
    if changed and not quiet:
        srv.hub.emit(
            ev.NODE_ONLINE if status == "online" else ev.NODE_OFFLINE,
            {"node_id": node.id, "organization_id": node.organization_id},
            room=ev.collaboration_room(node.collaboration_id),
        )
