"""Lightweight sqlite3-backed persistence for the control plane.

Parity: the reference persists its control plane through SQLAlchemy ORM
models (SURVEY.md §2 items 2, 8). SQLAlchemy is not in this image, so this
module provides the small declarative core the server models need: typed
columns, foreign keys, many-to-many link tables, and schema migration by
additive DDL (the reference uses alembic; here `ensure_schema` creates
missing tables/columns on startup, which covers the same upgrade path for a
single-writer control plane).

Thread safety: one connection per thread (the WSGI server is threaded);
sqlite handles cross-process locking.

Storage backends: the server binds its models through `open_backend(uri)`,
which dispatches on the URI scheme via the `BACKENDS` registry:

- ``sqlite`` — the `Database` below: single replica, dev/test default
  (`:memory:` supported through one shared connection).
- ``sqlite+wal`` — `WalDatabase`: one WAL file SHARED by N server replica
  processes; every statement retries on SQLITE_BUSY with backoff, and the
  backend advertises ``SHARED = True`` so the app layer switches the event
  hub, cache invalidation and learning plane onto shared-store substrates.

A Postgres driver drops in by registering another class with the same
execute/query/close surface (rowcount-bearing cursors are the only
contract `Model.compare_and_swap` needs).
"""
from __future__ import annotations

import json
import sqlite3
import threading
import time
from pathlib import Path
from typing import Any, ClassVar, Iterable, TypeVar

T = TypeVar("T", bound="Model")

_TYPES = {
    "int": "INTEGER",
    "float": "REAL",
    "str": "TEXT",
    "bool": "INTEGER",
    "json": "TEXT",
    "blob": "BLOB",
}


class Database:
    """One sqlite database; thread-local connections."""

    # backend identity: the scheme this class serves in `BACKENDS`, and
    # whether N server processes may share one store (drives the app
    # layer's hub/cache/learning substrate selection)
    KIND: ClassVar[str] = "sqlite"
    SHARED: ClassVar[bool] = False

    def __init__(self, uri: str = "sqlite:///:memory:"):
        self.uri = uri
        self.path = uri.split(":///", 1)[1] if ":///" in uri else uri
        self._local = threading.local()
        self._memory_conn: sqlite3.Connection | None = None
        if self.path != ":memory:":
            Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        else:
            # :memory: is per-connection; share ONE connection (+lock) so all
            # threads see the same in-memory database (test mode).
            self._memory_conn = self._connect()
        self._memory_lock = threading.RLock()

    def _connect(self) -> sqlite3.Connection:
        # cached_statements: sqlite3 keeps per-connection PREPARED
        # statements keyed by SQL text; the generated CRUD SQL is highly
        # repetitive (one shape per model/filter combination), so a larger
        # cache keeps the whole hot set compiled across the federation's
        # polling/batch sweeps instead of re-parsing per request
        conn = sqlite3.connect(
            self.path, check_same_thread=False, cached_statements=256
        )
        conn.row_factory = sqlite3.Row
        conn.execute("PRAGMA foreign_keys = ON")
        conn.execute("PRAGMA journal_mode = WAL")
        # thread-per-request server (server/web.py): concurrent writers
        # queue on the sqlite write lock. sqlite3.connect's default
        # timeout already installs a 5 s busy handler; the pragma makes
        # that contract EXPLICIT so nobody "optimizes" connect(timeout=0)
        # without tripping over this line
        conn.execute("PRAGMA busy_timeout = 5000")
        # durable-enough with WAL (fsync at checkpoint, not per-commit);
        # the per-commit fsync of FULL is the single-writer bottleneck
        # under federation-scale polling
        conn.execute("PRAGMA synchronous = NORMAL")
        return conn

    @property
    def conn(self) -> sqlite3.Connection:
        if self._memory_conn is not None:
            return self._memory_conn
        c = getattr(self._local, "conn", None)
        if c is None:
            c = self._connect()
            self._local.conn = c
        return c

    def execute(self, sql: str, params: Iterable[Any] = ()) -> sqlite3.Cursor:
        if self._memory_conn is not None:
            with self._memory_lock:
                cur = self.conn.execute(sql, tuple(params))
                self.conn.commit()
                return cur
        cur = self.conn.execute(sql, tuple(params))
        self.conn.commit()
        return cur

    def query(self, sql: str, params: Iterable[Any] = ()) -> list[sqlite3.Row]:
        if self._memory_conn is not None:
            with self._memory_lock:
                return self.conn.execute(sql, tuple(params)).fetchall()
        return self.conn.execute(sql, tuple(params)).fetchall()

    def close(self) -> None:
        if self._memory_conn is not None:
            self._memory_conn.close()
            self._memory_conn = None
        c = getattr(self._local, "conn", None)
        if c is not None:
            c.close()
            self._local.conn = None


class WalDatabase(Database):
    """Shared-file WAL backend: N server replica PROCESSES over one store.

    The base class already opens every connection in WAL mode with a 5 s
    busy handler; what changes here is the failure contract. A single
    replica can treat SQLITE_BUSY as a bug (nothing else holds the file);
    with N replicas it is a normal collision on the single WAL writer
    slot, so every statement retries with exponential backoff before
    giving up. Statements that pass through here are safe to re-issue:
    the model layer's guarded updates (`Model.compare_and_swap`) carry
    their own `WHERE` state guards, and a retried INSERT only runs again
    when the first attempt's transaction rolled back.
    """

    KIND = "sqlite+wal"
    SHARED = True
    BUSY_RETRIES = 6

    def __init__(self, uri: str):
        super().__init__(uri)
        if self.path == ":memory:":
            raise ValueError(
                "sqlite+wal needs a file path shared between replicas; "
                ":memory: is per-process by construction"
            )

    def _retry(self, fn):
        delay = 0.005
        for attempt in range(self.BUSY_RETRIES):
            try:
                return fn()
            except sqlite3.OperationalError as e:
                msg = str(e).lower()
                if "locked" not in msg and "busy" not in msg:
                    raise
                try:  # drop any half-open transaction before re-issuing
                    self.conn.rollback()
                except sqlite3.Error:  # pragma: no cover - teardown race
                    pass
                if attempt == self.BUSY_RETRIES - 1:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 0.25)

    def execute(self, sql: str, params: Iterable[Any] = ()) -> sqlite3.Cursor:
        params = tuple(params)  # a generator must survive the re-issue
        return self._retry(lambda: Database.execute(self, sql, params))

    def query(self, sql: str, params: Iterable[Any] = ()) -> list[sqlite3.Row]:
        params = tuple(params)
        return self._retry(lambda: Database.query(self, sql, params))


# scheme -> backend class; `open_backend` dispatches on the URI scheme so a
# Postgres driver later is one registry entry, not an app-layer rewrite
BACKENDS: dict[str, type[Database]] = {
    Database.KIND: Database,
    WalDatabase.KIND: WalDatabase,
}


def open_backend(uri: str) -> Database:
    """Open the storage backend the URI scheme names (default: sqlite)."""
    scheme = uri.split(":///", 1)[0] if ":///" in uri else "sqlite"
    cls = BACKENDS.get(scheme)
    if cls is None:
        raise ValueError(
            f"unknown storage backend {scheme!r} "
            f"(registered: {sorted(BACKENDS)})"
        )
    return cls(uri)


class Model:
    """Declarative row: subclasses set TABLE and COLUMNS.

    COLUMNS maps field name -> type key in `_TYPES`; `"<name>_id"` columns
    ending in `_id` get an index. `id` (PK) and `created_at` are implicit.
    """

    TABLE: ClassVar[str] = ""
    COLUMNS: ClassVar[dict[str, str]] = {}

    # Bound per model *hierarchy*: `Model.db = ...` serves the server models;
    # a service with its own DB (algorithm store) subclasses Model with its
    # own `db = None` class attribute and binds that instead.
    db: ClassVar[Database | None] = None

    def __init__(self, **kw: Any):
        self.id: int | None = kw.pop("id", None)
        self.created_at: float = kw.pop("created_at", None) or time.time()
        for col in self.COLUMNS:
            setattr(self, col, kw.pop(col, None))
        if kw:
            raise TypeError(f"{type(self).__name__}: unknown fields {sorted(kw)}")

    # ------------------------------------------------------------------ class
    @classmethod
    def _db(cls) -> Database:
        db = cls.db  # class-attribute lookup: nearest hierarchy binding wins
        if db is None:
            raise RuntimeError("no database bound — call db.init(uri) first")
        return db

    @classmethod
    def _sql_columns(cls) -> frozenset[str]:
        """Column names that may appear in generated SQL (where/order).

        Derived from PRAGMA table_info on first use (covers legacy columns
        an old database may carry beyond COLUMNS) and cached per class.
        Defense-in-depth for the f-string SQL assembly in list/first/count:
        a bad kwarg fails HERE with a clear TypeError naming the field,
        before any SQL string is built.
        """
        cached = cls.__dict__.get("_SQL_COLUMNS")
        if cached is None:
            have = {
                r["name"]
                for r in cls._db().query(f"PRAGMA table_info({cls.TABLE})")
            }
            cached = frozenset(have | set(cls.COLUMNS) | {"id", "created_at"})
            cls._SQL_COLUMNS = cached  # per-class, not inherited
        return cached

    @classmethod
    def _check_columns(cls, names: Iterable[str], what: str) -> None:
        unknown = [n for n in names if n not in cls._sql_columns()]
        if unknown:
            raise TypeError(
                f"{cls.__name__}: unknown {what} column(s) {sorted(unknown)} "
                f"(known: {sorted(cls._sql_columns())})"
            )

    @classmethod
    def ensure_schema(cls) -> None:
        if "_SQL_COLUMNS" in cls.__dict__:
            delattr(cls, "_SQL_COLUMNS")  # re-derive after DDL
        cols = ", ".join(
            f'"{name}" {_TYPES[t]}' for name, t in cls.COLUMNS.items()
        )
        cls._db().execute(
            f"CREATE TABLE IF NOT EXISTS {cls.TABLE} "
            f"(id INTEGER PRIMARY KEY AUTOINCREMENT, created_at REAL"
            + (", " + cols if cols else "")
            + ")"
        )
        # additive migration: add any columns that an older schema lacks
        have = {
            r["name"]
            for r in cls._db().query(f"PRAGMA table_info({cls.TABLE})")
        }
        for name, t in cls.COLUMNS.items():
            if name not in have:
                cls._db().execute(
                    f'ALTER TABLE {cls.TABLE} ADD COLUMN "{name}" {_TYPES[t]}'
                )
        for name in cls.COLUMNS:
            if name.endswith("_id"):
                cls._db().execute(
                    f"CREATE INDEX IF NOT EXISTS idx_{cls.TABLE}_{name} "
                    f'ON {cls.TABLE}("{name}")'
                )

    # ------------------------------------------------------------- marshal
    @classmethod
    def _encode_value(cls, col: str, v: Any) -> Any:
        t = cls.COLUMNS.get(col)
        if v is None:
            return None
        if t == "json":
            return json.dumps(v)
        if t == "bool" or isinstance(v, bool):
            return int(v)
        return v

    def _encode(self, col: str) -> Any:
        return self._encode_value(col, getattr(self, col))

    @classmethod
    def _from_row(cls: type[T], row: sqlite3.Row) -> T:
        kw: dict[str, Any] = {"id": row["id"], "created_at": row["created_at"]}
        for col, t in cls.COLUMNS.items():
            v = row[col]
            if v is not None and t == "json":
                v = json.loads(v)
            elif v is not None and t == "bool":
                v = bool(v)
            kw[col] = v
        return cls(**kw)

    # ----------------------------------------------------------------- CRUD
    def save(self: T) -> T:
        cols = list(self.COLUMNS)
        vals = [self._encode(c) for c in cols]
        if self.id is None:
            placeholders = ", ".join("?" for _ in range(len(cols) + 1))
            cur = self._db().execute(
                f"INSERT INTO {self.TABLE} (created_at"
                + (", " + ", ".join(f'"{c}"' for c in cols) if cols else "")
                + f") VALUES ({placeholders})",
                [self.created_at, *vals],
            )
            self.id = cur.lastrowid
        else:
            sets = ", ".join(f'"{c}" = ?' for c in cols)
            self._db().execute(
                f"UPDATE {self.TABLE} SET {sets} WHERE id = ?",
                [*vals, self.id],
            )
        return self

    def delete(self) -> None:
        if self.id is not None:
            self._db().execute(
                f"DELETE FROM {self.TABLE} WHERE id = ?", [self.id]
            )

    @classmethod
    def get(cls: type[T], id_: int) -> T | None:
        rows = cls._db().query(
            f"SELECT * FROM {cls.TABLE} WHERE id = ?", [id_]
        )
        return cls._from_row(rows[0]) if rows else None

    @classmethod
    def list(
        cls: type[T],
        order: str = "id",
        limit: int | None = None,
        offset: int = 0,
        **where: Any,
    ) -> list[T]:
        cls._check_columns(where, "where")
        order_col, _, order_dir = order.partition(" ")
        cls._check_columns([order_col], "order")
        if order_dir and order_dir.lower() not in ("asc", "desc"):
            raise TypeError(f"{cls.__name__}: bad order direction {order!r}")
        sql = f"SELECT * FROM {cls.TABLE}"
        params: list[Any] = []
        if where:
            conds = []
            for k, v in where.items():
                if v is None:
                    conds.append(f'"{k}" IS NULL')
                else:
                    conds.append(f'"{k}" = ?')
                    params.append(int(v) if isinstance(v, bool) else v)
            sql += " WHERE " + " AND ".join(conds)
        sql += f" ORDER BY {order}"
        if limit is not None:
            sql += " LIMIT ? OFFSET ?"
            params += [limit, offset]
        return [cls._from_row(r) for r in cls._db().query(sql, params)]

    @classmethod
    def first(cls: type[T], **where: Any) -> T | None:
        rows = cls.list(limit=1, **where)
        return rows[0] if rows else None

    @classmethod
    def compare_and_swap(
        cls, id_: int, sets: dict[str, Any], expect: dict[str, Any]
    ) -> bool:
        """Atomic guarded update — the ONE primitive every cross-replica
        read-modify-write (run claim/activation, status transition, orphan
        reset) is built on: ``UPDATE ... SET <sets> WHERE id = ? AND
        <expect>`` in a single statement, so the state check and the write
        cannot interleave with another replica's. Returns True iff the row
        was in exactly the expected state and is now updated; False means
        the caller lost the race and must re-read before deciding."""
        if not sets:
            raise TypeError(f"{cls.__name__}.compare_and_swap: empty sets")
        cls._check_columns(sets, "set")
        cls._check_columns(expect, "where")
        set_sql = ", ".join(f'"{c}" = ?' for c in sets)
        params: list[Any] = [cls._encode_value(c, v) for c, v in sets.items()]
        conds = ["id = ?"]
        params.append(id_)
        for k, v in expect.items():
            if v is None:
                conds.append(f'"{k}" IS NULL')
            else:
                conds.append(f'"{k}" = ?')
                params.append(cls._encode_value(k, v))
        cur = cls._db().execute(
            f"UPDATE {cls.TABLE} SET {set_sql} WHERE " + " AND ".join(conds),
            params,
        )
        return cur.rowcount == 1

    @classmethod
    def count(cls, **where: Any) -> int:
        cls._check_columns(where, "where")
        sql = f"SELECT COUNT(*) AS n FROM {cls.TABLE}"
        params: list[Any] = []
        if where:
            conds = []
            for k, v in where.items():
                if v is None:
                    conds.append(f'"{k}" IS NULL')
                else:
                    conds.append(f'"{k}" = ?')
                    params.append(int(v) if isinstance(v, bool) else v)
            sql += " WHERE " + " AND ".join(conds)
        return int(cls._db().query(sql, params)[0]["n"])


class LinkTable:
    """Many-to-many link: two id columns, unique pairs."""

    def __init__(
        self, table: str, left: str, right: str, base: type[Model] = Model
    ):
        self.table, self.left, self.right = table, left, right
        self.base = base  # which model hierarchy's db binding to use

    def _db(self) -> Database:
        return self.base._db()

    def ensure_schema(self) -> None:
        self._db().execute(
            f"CREATE TABLE IF NOT EXISTS {self.table} ("
            f"{self.left} INTEGER NOT NULL, {self.right} INTEGER NOT NULL, "
            f"UNIQUE({self.left}, {self.right}))"
        )

    def add(self, left_id: int, right_id: int) -> None:
        self._db().execute(
            f"INSERT OR IGNORE INTO {self.table} ({self.left}, {self.right}) "
            "VALUES (?, ?)",
            [left_id, right_id],
        )

    def remove(self, left_id: int, right_id: int) -> None:
        self._db().execute(
            f"DELETE FROM {self.table} WHERE {self.left} = ? AND {self.right} = ?",
            [left_id, right_id],
        )

    def rights_for(self, left_id: int) -> list[int]:
        return [
            r[self.right]
            for r in self._db().query(
                f"SELECT {self.right} FROM {self.table} WHERE {self.left} = ?",
                [left_id],
            )
        ]

    def lefts_for(self, right_id: int) -> list[int]:
        return [
            r[self.left]
            for r in self._db().query(
                f"SELECT {self.left} FROM {self.table} WHERE {self.right} = ?",
                [right_id],
            )
        ]

    def exists(self, left_id: int, right_id: int) -> bool:
        return bool(
            self._db().query(
                f"SELECT 1 FROM {self.table} WHERE {self.left} = ? AND {self.right} = ?",
                [left_id, right_id],
            )
        )
