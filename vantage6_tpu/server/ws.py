"""WebSocket push bridge for the event hub.

Parity: the reference's SocketIO websocket (SURVEY.md §2 item 6) — nodes
and UIs get events PUSHED instead of polling the REST cursor. The cursor
endpoint remains the reconnect/catch-up path (exactly the reference's
`sync_task_queue_with_server` split: socket for liveness, sync for gaps).

Protocol (JSON messages over one websocket):

    client -> {"token": "<jwt>", "since": <cursor|0>}
    server -> {"connected": true, "cursor": N}
    server -> {"event": {seq, name, room, data, ts}}   (pushed, incl. any
               events after `since` replayed first)
    client -> {"ping": t}     server -> {"pong": t}
"""
from __future__ import annotations

import json
import queue
import threading
from typing import TYPE_CHECKING, Any

# `websockets` is OPTIONAL: the REST cursor remains the full-fidelity event
# path, so servers without the package simply run pull-only. Import errors
# surface on bridge construction, not module import.
try:
    from websockets.sync.server import serve
except ModuleNotFoundError as _e:  # pragma: no cover - exercised in CI env
    serve = None
    _WEBSOCKETS_ERROR: Exception | None = _e
else:
    _WEBSOCKETS_ERROR = None

from vantage6_tpu.common.log import setup_logging
from vantage6_tpu.server.resources import _rooms_for, identity_from_token
from vantage6_tpu.server.web import HTTPError

if TYPE_CHECKING:  # pragma: no cover
    from vantage6_tpu.server.app import ServerApp

log = setup_logging("vantage6_tpu/server.ws")


class WebSocketBridge:
    def __init__(self, srv: "ServerApp", host: str = "127.0.0.1", port: int = 0):
        if _WEBSOCKETS_ERROR is not None:
            raise RuntimeError(
                "the 'websockets' package is required for the event push "
                "bridge but is not installed; nodes fall back to the REST "
                "event cursor"
            ) from _WEBSOCKETS_ERROR
        self.srv = srv
        self._server = serve(self._handler, host, port)
        self.host, self.port = self._server.socket.getsockname()[:2]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"ws://{self.host}:{self.port}"

    def start_background(self) -> "WebSocketBridge":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        self.srv.ws_url = self.url
        log.info("event websocket on %s", self.url)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        if getattr(self.srv, "ws_url", None) == self.url:
            self.srv.ws_url = None

    # ---------------------------------------------------------------- serve
    def _handler(self, ws: Any) -> None:
        try:
            hello = json.loads(ws.recv(timeout=10))
        except Exception:
            ws.close(1002, "expected auth message")
            return
        try:
            kind, principal = identity_from_token(self.srv, hello.get("token"))
        except HTTPError as e:
            ws.send(json.dumps({"error": e.msg}))
            ws.close(1008, "unauthorized")
            return
        rooms = _rooms_for(self.srv, kind, principal)
        q: queue.Queue = queue.Queue(maxsize=1024)
        overflowed = threading.Event()

        def push(event: Any) -> None:
            try:
                q.put_nowait(event)
            except queue.Full:
                # a silently dropped event on a HEALTHY socket would never
                # be re-delivered — flag it so the handler closes the
                # connection, forcing the client onto its cursor catch-up
                overflowed.set()

        sid = self.srv.hub.subscribe(push, rooms)
        try:
            ws.send(
                json.dumps({"connected": True, "cursor": self.srv.hub.cursor})
            )
            # replay anything after the client's cursor BEFORE live events
            for ev in self.srv.hub.fetch(int(hello.get("since", 0)), rooms):
                ws.send(json.dumps({"event": ev.to_dict()}))
            while True:
                if overflowed.is_set():
                    ws.close(1013, "event overflow; re-sync via cursor")
                    break
                # interleave pushes with (optional) client pings
                try:
                    ev = q.get(timeout=0.25)
                    ws.send(json.dumps({"event": ev.to_dict()}))
                except queue.Empty:
                    pass
                try:
                    msg = ws.recv(timeout=0)
                    data = json.loads(msg)
                    if "ping" in data:
                        ws.send(json.dumps({"pong": data["ping"]}))
                except TimeoutError:
                    continue
                except Exception:
                    break  # closed / bad frame
        except Exception:
            pass  # connection ended
        finally:
            self.srv.hub.unsubscribe(sid)
