"""Built-in web UI.

Parity: the reference ships an Angular SPA (SURVEY.md §2 item 27) for
administration and task management. Here a dependency-free single-page app
(vanilla JS + the server's own REST API) is served by the control plane
itself at ``/`` — login/MFA, collaborations, node liveness, task submission
(freeform + store-metadata wizard), a full run-log/result viewer with
per-run timing, studies/sessions, admin CRUD (organizations, users, roles)
with rule-level role management and user role assignment, and the COMPLETE
store workflow in the browser: browse by status, submit an algorithm,
start a review, approve/reject with comment (same-origin proxy,
resources.py `_store_forward`). Deliberately buildless: one HTML document,
no bundler, no CDN (zero-egress deployments), trivially auditable.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

from vantage6_tpu.server.web import Request, Response

if TYPE_CHECKING:  # pragma: no cover
    from vantage6_tpu.server.app import ServerApp

PAGE = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>vantage6-tpu</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
:root { --bg:#10141a; --panel:#1a212b; --text:#e6e9ee; --dim:#8b97a6;
        --accent:#4fa3ff; --ok:#3fb97c; --bad:#e0635c; --warn:#d9a441; }
* { box-sizing:border-box; }
body { margin:0; background:var(--bg); color:var(--text);
       font:14px/1.5 system-ui, sans-serif; }
header { display:flex; align-items:center; gap:1rem; padding:.7rem 1.2rem;
         background:var(--panel); border-bottom:1px solid #2a3442; }
header h1 { font-size:1rem; margin:0; letter-spacing:.04em; }
header .who { margin-left:auto; color:var(--dim); }
main { max-width:1100px; margin:1.2rem auto; padding:0 1rem; }
.panel { background:var(--panel); border:1px solid #2a3442; border-radius:8px;
         padding:1rem 1.2rem; margin-bottom:1rem; }
h2 { font-size:.85rem; text-transform:uppercase; letter-spacing:.08em;
     color:var(--dim); margin:.2rem 0 .8rem; }
table { width:100%; border-collapse:collapse; }
th, td { text-align:left; padding:.35rem .5rem; border-bottom:1px solid #242e3b; }
th { color:var(--dim); font-weight:500; }
tr:hover td { background:#202a36; }
input, select, textarea, button {
  background:#0d1117; color:var(--text); border:1px solid #2a3442;
  border-radius:6px; padding:.45rem .6rem; font:inherit; }
button { background:var(--accent); color:#081018; border:none; cursor:pointer;
         font-weight:600; }
button.ghost { background:transparent; color:var(--accent);
               border:1px solid var(--accent); }
.badge { padding:.1rem .5rem; border-radius:10px; font-size:.75rem; }
.badge.online, .badge.completed { background:#15392a; color:var(--ok); }
.badge.offline, .badge.crashed, .badge.failed { background:#3d1f1d; color:var(--bad); }
.badge.pending, .badge.active { background:#3a2f16; color:var(--warn); }
.row { display:flex; gap:.6rem; flex-wrap:wrap; align-items:center; }
#login { max-width:360px; margin:14vh auto; }
.err { color:var(--bad); min-height:1.2em; }
pre { background:#0d1117; padding:.6rem; border-radius:6px; overflow:auto; }
a { color:var(--accent); cursor:pointer; }
.hidden { display:none; }
</style>
</head>
<body>
<header>
  <h1>vantage6-tpu</h1>
  <span id="version" class="who"></span>
  <span id="whoami" class="who"></span>
  <button id="logout" class="ghost hidden">log out</button>
</header>
<main>
  <div id="login" class="panel">
    <h2>Sign in</h2>
    <div class="row" style="flex-direction:column; align-items:stretch">
      <input id="username" placeholder="username" autocomplete="username">
      <input id="password" type="password" placeholder="password"
             autocomplete="current-password">
      <input id="mfa" placeholder="MFA code (if enabled)">
      <button id="signin">Sign in</button>
      <div id="loginerr" class="err"></div>
    </div>
  </div>

  <div id="appview" class="hidden">
    <nav class="row" style="margin-bottom:1rem">
      <button class="tabbtn" data-tab="overview">Overview</button>
      <button class="tabbtn ghost" data-tab="admin">Admin</button>
      <button class="tabbtn ghost" data-tab="store">Store</button>
    </nav>

    <div id="tab_overview">
    <div class="panel">
      <h2>Nodes</h2>
      <table id="nodes"><thead><tr>
        <th>name</th><th>organization</th><th>collaboration</th><th>status</th>
      </tr></thead><tbody></tbody></table>
    </div>
    <div class="panel">
      <h2>Collaborations</h2>
      <table id="collabs"><thead><tr>
        <th>id</th><th>name</th><th>encrypted</th><th>organizations</th>
      </tr></thead><tbody></tbody></table>
    </div>
    <div class="panel">
      <h2>New task</h2>
      <div class="row">
        <select id="t_collab"></select>
        <select id="t_study" title="target a study subset">
          <option value="">whole collaboration</option></select>
        <select id="t_algo" title="pick an approved store algorithm to get
a guided form, or stay freeform">
          <option value="">freeform algorithm</option></select>
      </div>
      <div class="row" id="t_freeform" style="margin-top:.5rem">
        <input id="t_image" placeholder="algorithm image" size="22">
        <input id="t_method" placeholder="method" size="16">
        <input id="t_kwargs" placeholder='kwargs JSON, e.g. {"column":"age"}'
               size="30">
      </div>
      <div id="t_wizard" class="hidden" style="margin-top:.5rem">
        <div class="row">
          <select id="w_function"></select>
          <span id="w_fndesc" class="who"></span>
        </div>
        <div id="w_args" class="row" style="margin-top:.4rem"></div>
      </div>
      <div class="row" style="margin-top:.5rem">
        <select id="t_session"><option value="">no session</option></select>
        <input id="t_store_as" size="18"
               placeholder="store as (session dataframe)">
        <button id="t_create">Create</button>
      </div>
      <div id="taskerr" class="err"></div>
    </div>
    <div class="panel">
      <h2>Studies</h2>
      <table id="studies"><thead><tr>
        <th>id</th><th>name</th><th>collaboration</th><th>organizations</th>
      </tr></thead><tbody></tbody></table>
      <div class="row" style="margin-top:.6rem">
        <input id="st_name" placeholder="study name" size="18">
        <select id="st_collab"></select>
        <select id="st_orgs" multiple size="3"
                title="member organizations (ctrl-click for several)"></select>
        <button id="st_create">Create study</button>
      </div>
      <div id="studyerr" class="err"></div>
    </div>
    <div class="panel">
      <h2>Sessions</h2>
      <table id="sessions"><thead><tr>
        <th>id</th><th>name</th><th>collaboration</th><th>scope</th>
        <th>dataframes</th><th></th>
      </tr></thead><tbody></tbody></table>
      <div class="row" style="margin-top:.6rem">
        <input id="se_name" placeholder="session name" size="18">
        <select id="se_collab"></select>
        <select id="se_scope">
          <option value="collaboration">collaboration</option>
          <option value="own">own</option>
        </select>
        <button id="se_create">Create session</button>
      </div>
      <div id="sesserr" class="err"></div>
    </div>
    <div class="panel">
      <h2>Tasks</h2>
      <table id="tasks"><thead><tr>
        <th>id</th><th>name</th><th>image</th><th>method</th><th>status</th>
        <th></th>
      </tr></thead><tbody></tbody></table>
    </div>
    <div class="panel hidden" id="detailpanel">
      <h2>Task <span id="d_id"></span></h2>
      <table id="runs"><thead><tr>
        <th>run</th><th>organization</th><th>node</th><th>status</th>
        <th>timing</th><th></th>
      </tr></thead><tbody></tbody></table>
    </div>
    <div class="panel hidden" id="runlogpanel">
      <h2>Run <span id="rl_id"></span> <span id="rl_meta" class="who"></span></h2>
      <h2>log</h2><pre id="rl_log"></pre>
      <h2>result (serialized)</h2><pre id="rl_result"></pre>
    </div>
    </div><!-- /tab_overview -->

    <div id="tab_admin" class="hidden">
    <div class="panel">
      <h2>Organizations</h2>
      <table id="a_orgs"><thead><tr>
        <th>id</th><th>name</th><th>country</th><th>public key</th>
      </tr></thead><tbody></tbody></table>
      <div class="row" style="margin-top:.6rem">
        <input id="o_name" placeholder="new organization name" size="24">
        <input id="o_country" placeholder="country" size="12">
        <button id="o_create">Create organization</button>
      </div>
      <div id="orgerr" class="err"></div>
    </div>
    <div class="panel">
      <h2>Users</h2>
      <table id="a_users"><thead><tr>
        <th>id</th><th>username</th><th>email</th><th>organization</th>
        <th>roles</th><th></th>
      </tr></thead><tbody></tbody></table>
      <div class="row" style="margin-top:.6rem">
        <input id="u_name" placeholder="username" size="14">
        <input id="u_pass" type="password" placeholder="password" size="14">
        <input id="u_email" placeholder="email" size="18">
        <select id="u_org"></select>
        <select id="u_roles" multiple size="3"
                title="roles (ctrl-click for several)"></select>
        <button id="u_create">Create user</button>
      </div>
      <div id="usererr" class="err"></div>
    </div>
    <div class="panel">
      <h2>Roles</h2>
      <table id="a_roles"><thead><tr>
        <th>id</th><th>name</th><th>organization</th><th>rules</th><th></th>
      </tr></thead><tbody></tbody></table>
      <div class="row" style="margin-top:.6rem">
        <input id="r_name" placeholder="role name" size="16">
        <select id="r_org"><option value="">global</option></select>
        <select id="r_rules" multiple size="4"
                title="rules (ctrl-click for several)"></select>
        <button id="r_create">Create role</button>
      </div>
      <div id="roleerr" class="err"></div>
    </div>
    <div class="panel hidden" id="roledetail">
      <h2>Role <span id="rd_name"></span></h2>
      <table id="rd_rules"><thead><tr>
        <th>rule</th><th>scope</th><th>operation</th>
      </tr></thead><tbody></tbody></table>
      <div class="row" style="margin-top:.6rem">
        <select id="rd_edit_rules" multiple size="5"
                title="replace this role's rules (ctrl-click)"></select>
        <button id="rd_save">Save rules</button>
        <button id="rd_delete" class="ghost">Delete role</button>
        <span id="rd_msg" class="who"></span>
      </div>
      <div id="rd_err" class="err"></div>
    </div>
    <div class="panel hidden" id="userdetail">
      <h2>User <span id="ud_name"></span></h2>
      <div class="row">
        <select id="ud_roles" multiple size="4"
                title="replace this user's roles (ctrl-click)"></select>
        <button id="ud_save">Save roles</button>
        <span id="ud_msg" class="who"></span>
      </div>
      <div id="ud_err" class="err"></div>
    </div>
    <div class="panel">
      <h2>My account</h2>
      <div class="row">
        <input id="pw_current" type="password" placeholder="current password"
               autocomplete="current-password" size="18">
        <input id="pw_new" type="password" placeholder="new password (min 8)"
               autocomplete="new-password" size="18">
        <button id="pw_change">Change password</button>
        <span id="pw_msg" class="who"></span>
      </div>
      <div id="pwerr" class="err"></div>
    </div>
    </div><!-- /tab_admin -->

    <div id="tab_store" class="hidden">
    <div class="panel">
      <h2>Algorithm store <span id="s_url" class="who"></span></h2>
      <div class="row" style="margin-bottom:.5rem">
        <select id="s_status" title="which submissions to list">
          <option value="">approved (public)</option>
          <option value="submitted">submitted</option>
          <option value="under review">under review</option>
          <option value="rejected">rejected</option>
        </select>
      </div>
      <table id="s_algos"><thead><tr>
        <th>id</th><th>name</th><th>image</th><th>status</th><th>functions</th>
      </tr></thead><tbody></tbody></table>
      <div id="storeerr" class="err"></div>
    </div>
    <div class="panel">
      <h2>Submit algorithm</h2>
      <div class="row">
        <input id="sa_name" placeholder="name" size="18">
        <input id="sa_image" size="32"
               placeholder="image ref, e.g. registry/algos/avg:1.0">
      </div>
      <div class="row" style="margin-top:.4rem">
        <input id="sa_desc" placeholder="description" size="52">
      </div>
      <div class="row" style="margin-top:.4rem">
        <textarea id="sa_functions" rows="4" cols="64" placeholder='functions JSON, e.g. [{"name":"partial_average","type":"federated","arguments":[{"name":"column","type":"column"}]}]'></textarea>
      </div>
      <div class="row" style="margin-top:.4rem">
        <button id="sa_submit">Submit for review</button>
        <span id="sa_msg" class="who"></span>
      </div>
      <div id="saerr" class="err"></div>
    </div>
    <div class="panel hidden" id="s_detailpanel">
      <h2>Algorithm <span id="s_d_name"></span></h2>
      <div id="s_d_desc" class="who"></div>
      <table id="s_d_functions"><thead><tr>
        <th>function</th><th>type</th><th>arguments</th><th>databases</th>
      </tr></thead><tbody></tbody></table>
      <h2 style="margin-top:.8rem">Reviews</h2>
      <table id="s_d_reviews"><thead><tr>
        <th>id</th><th>reviewer</th><th>status</th><th>comment</th><th></th>
      </tr></thead><tbody></tbody></table>
      <div class="row" style="margin-top:.5rem">
        <button id="s_d_startreview" class="ghost">Start review (assign me)</button>
        <input id="s_d_comment" placeholder="review comment" size="30">
        <span id="s_d_msg" class="who"></span>
      </div>
      <div id="s_d_err" class="err"></div>
    </div>
    </div><!-- /tab_store -->
  </div>
</main>
<script>
"use strict";
let token = sessionStorage.getItem("v6t_token") || null;
const $ = (id) => document.getElementById(id);

// every server-sourced string goes through esc() before innerHTML — task
// names/images/logs are collaborator-controlled input (stored-XSS vector)
function esc(v) {
  return String(v ?? "").replace(/[&<>"']/g, (c) => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  })[c]);
}

async function api(method, path, body) {
  const opts = { method, headers: {} };
  if (token) opts.headers["Authorization"] = "Bearer " + token;
  if (body !== undefined) {
    opts.headers["Content-Type"] = "application/json";
    opts.body = JSON.stringify(body);
  }
  const resp = await fetch("/api/" + path, opts);
  const data = resp.status === 204 ? {} : await resp.json();
  if (!resp.ok) throw new Error(data.msg || resp.statusText);
  return data;
}

function badge(status) {
  const cls = esc(String(status).split(" ")[0]);
  return `<span class="badge ${cls}">${esc(status)}</span>`;
}

function fill(tableId, rows, renderer) {
  $(tableId).querySelector("tbody").innerHTML = rows.map(renderer).join("");
}

let collabCache = [];

function keepSelection(sel, html) {
  // refresh() reruns every 3 s: rebuilding <option>s must not clobber what
  // the user picked mid-form (including ctrl-click MULTI-selections)
  const prev = new Set(
    [...sel.selectedOptions].map((o) => o.value));
  sel.innerHTML = html;
  let any = false;
  for (const o of sel.options) {
    if (prev.has(o.value)) { o.selected = true; any = true; }
    else if (sel.multiple) o.selected = false;
  }
  if (!any && !sel.multiple && sel.options.length) sel.selectedIndex = 0;
}

async function refresh() {
  const [nodes, collabs, tasks, studies, sessions] = await Promise.all([
    api("GET", "node"), api("GET", "collaboration"), api("GET", "task"),
    api("GET", "study").catch(() => ({ data: [] })),
    api("GET", "session").catch(() => ({ data: [] })),
  ]);
  collabCache = collabs.data;
  fill("nodes", nodes.data, (n) =>
    `<tr><td>${esc(n.name)}</td><td>${esc(n.organization.id)}</td>` +
    `<td>${esc(n.collaboration.id)}</td><td>${badge(n.status)}</td></tr>`);
  fill("collabs", collabs.data, (c) =>
    `<tr><td>${esc(c.id)}</td><td>${esc(c.name)}</td><td>${c.encrypted}</td>` +
    `<td>${esc(c.organizations.join(", "))}</td></tr>`);
  // encrypted collaborations need client-side key material the browser UI
  // does not hold — exclude them from task submission
  const collabOpts = collabs.data.filter((c) => !c.encrypted).map(
    (c) => `<option value="${Number(c.id)}">${esc(c.name)}</option>`).join("");
  keepSelection($("t_collab"), collabOpts);
  keepSelection($("st_collab"), collabOpts);
  keepSelection($("se_collab"), collabOpts);
  fillStudyOrgs();
  // only studies/sessions OF the selected collaboration: anything else
  // would 400 at submit ("study not in collaboration")
  const tc = parseInt($("t_collab").value, 10);
  keepSelection($("t_study"),
    `<option value="">whole collaboration</option>` +
    studies.data.filter((s) => s.collaboration === tc).map((s) =>
      `<option value="${Number(s.id)}">${esc(s.name)}</option>`).join(""));
  keepSelection($("t_session"),
    `<option value="">no session</option>` +
    sessions.data.filter((s) => s.collaboration.id === tc).map((s) =>
      `<option value="${Number(s.id)}">${esc(s.name)}</option>`).join(""));
  fill("studies", studies.data, (s) =>
    `<tr><td>${Number(s.id)}</td><td>${esc(s.name)}</td>` +
    `<td>${esc(s.collaboration)}</td>` +
    `<td>${esc((s.organizations || []).join(", "))}</td></tr>`);
  fill("sessions", sessions.data, (s) =>
    `<tr><td>${Number(s.id)}</td><td>${esc(s.name)}</td>` +
    `<td>${esc(s.collaboration.id)}</td><td>${esc(s.scope)}</td>` +
    `<td>${esc((s.dataframes || []).map((d) =>
        d.handle + (d.ready ? " ✓" : " …")).join(", "))}</td>` +
    `<td><button class="ghost" onclick="deleteSession(${Number(s.id)})">` +
    `delete</button></td></tr>`);
  fill("tasks", tasks.data.slice().reverse(), (t) =>
    `<tr><td><a onclick="showTask(${Number(t.id)})">${Number(t.id)}</a></td>` +
    `<td>${esc(t.name)}</td><td>${esc(t.image)}</td>` +
    `<td>${esc(t.method || "")}</td><td>${badge(t.status)}</td>` +
    // terminal-only states hide the button; a failed sibling run still
    // leaves OTHER runs consuming nodes, so failure states keep it
    `<td>${["completed", "killed by user"].includes(t.status) ? "" :
      `<button class="ghost" onclick="killTask(${Number(t.id)})">kill` +
      `</button>`}</td></tr>`);
}

window.killTask = async function (id) {
  try {
    $("taskerr").textContent = "";
    await api("POST", "kill/task", { task_id: id });
    await refresh();
  } catch (e) { $("taskerr").textContent = e.message; }
};

function fillStudyOrgs() {
  const collab = collabCache.find(
    (c) => c.id === parseInt($("st_collab").value, 10));
  keepSelection($("st_orgs"), (collab ? collab.organizations : []).map(
    (id) => `<option value="${Number(id)}">org ${Number(id)}</option>`
  ).join(""));
}
$("st_collab").onchange = fillStudyOrgs;
$("t_collab").onchange = () => {
  // org-typed wizard inputs and the study/session dropdowns are all scoped
  // to the selected collaboration — rebuild them on switch
  renderWizardArgs();
  refresh().catch(() => {});
};

let runCache = [];
window.showTask = async function (id) {
  const runs = await api("GET", `task/${id}/run`);
  runCache = runs.data;
  $("d_id").textContent = id;
  $("detailpanel").classList.remove("hidden");
  const dur = (a, b) => (a && b) ? `${(b - a).toFixed(2)}s` : "—";
  fill("runs", runs.data, (r) =>
    `<tr><td>${Number(r.id)}</td><td>${esc(r.organization.id)}</td>` +
    `<td>${esc(r.node && r.node.id ? r.node.id : "—")}</td>` +
    `<td>${badge(r.status)}</td>` +
    `<td>queued ${dur(r.assigned_at, r.started_at)}, ` +
    `ran ${dur(r.started_at, r.finished_at)}</td>` +
    `<td><a onclick="showRunLog(${Number(r.id)})">log / result</a></td></tr>`);
};

// full-content run viewer (the table truncates nothing — it links here)
window.showRunLog = function (id) {
  const r = runCache.find((x) => x.id === id);
  if (!r) return;
  $("rl_id").textContent = id;
  const ts = (t) => t ? new Date(t * 1000).toISOString() : "—";
  $("rl_meta").textContent =
    `org ${r.organization.id} · ${r.status} · assigned ${ts(r.assigned_at)}` +
    ` · started ${ts(r.started_at)} · finished ${ts(r.finished_at)}`;
  $("rl_log").textContent = r.log || "(empty)";
  $("rl_result").textContent = r.result || "(no result)";
  $("runlogpanel").classList.remove("hidden");
};

// ------------------------------------------------------------------- tabs
let activeTab = "overview";
document.querySelectorAll(".tabbtn").forEach((b) => {
  b.onclick = () => switchTab(b.dataset.tab);
});
function switchTab(tab) {
  activeTab = tab;
  for (const t of ["overview", "admin", "store"]) {
    $("tab_" + t).classList.toggle("hidden", t !== tab);
    document.querySelector(`.tabbtn[data-tab=${t}]`)
      .classList.toggle("ghost", t !== tab);
  }
  if (tab === "admin") refreshAdmin().catch(() => {});
  if (tab === "store") refreshStore().catch(() => {});
}

// ------------------------------------------------------------------ admin
let ruleCache = [], roleCache = [], userCache = [];

async function refreshAdmin() {
  const [orgs, users, roles, rules] = await Promise.all([
    api("GET", "organization"), api("GET", "user"),
    api("GET", "role"), api("GET", "rule?per_page=500"),
  ]);
  ruleCache = rules.data; roleCache = roles.data; userCache = users.data;
  fill("a_orgs", orgs.data, (o) =>
    `<tr><td>${Number(o.id)}</td><td>${esc(o.name)}</td>` +
    `<td>${esc(o.country || "")}</td>` +
    `<td>${o.public_key ? "yes" : "—"}</td></tr>`);
  const roleName = Object.fromEntries(roles.data.map((r) => [r.id, r.name]));
  fill("a_users", users.data, (u) =>
    `<tr><td>${Number(u.id)}</td>` +
    `<td><a onclick="showUser(${Number(u.id)})">${esc(u.username)}</a></td>` +
    `<td>${esc(u.email || "")}</td><td>${esc(u.organization.id)}</td>` +
    `<td>${esc((u.roles || []).map((r) => roleName[r] || r).join(", "))}</td>` +
    `<td><button class="ghost" onclick="deleteUser(${Number(u.id)})">` +
    `delete</button></td></tr>`);
  fill("a_roles", roles.data, (r) =>
    `<tr><td>${Number(r.id)}</td>` +
    `<td><a onclick="showRole(${Number(r.id)})">${esc(r.name)}</a></td>` +
    `<td>${esc(r.organization ? r.organization.id : "global")}</td>` +
    `<td>${Number((r.rules || []).length)}</td>` +
    `<td><a onclick="showRole(${Number(r.id)})">manage</a></td></tr>`);
  const orgOpts = orgs.data.map(
    (o) => `<option value="${Number(o.id)}">${esc(o.name)}</option>`).join("");
  $("u_org").innerHTML = orgOpts;
  $("r_org").innerHTML = `<option value="">global</option>` + orgOpts;
  $("u_roles").innerHTML = roles.data.map(
    (r) => `<option value="${Number(r.id)}">${esc(r.name)}</option>`).join("");
  $("r_rules").innerHTML = rules.data.map((r) =>
    `<option value="${Number(r.id)}">` +
    `${esc(r.name)}:${esc(r.scope)}:${esc(r.operation)}</option>`).join("");
}

window.deleteUser = async function (id) {
  try { await api("DELETE", `user/${id}`); await refreshAdmin(); }
  catch (e) { $("usererr").textContent = e.message; }
};

// ------------------------------------------------- role & user management
let shownRole = null, shownUser = null;

window.showRole = function (id) {
  const role = roleCache.find((r) => r.id === id);
  if (!role) return;
  shownRole = id;
  $("rd_name").textContent =
    `${role.name} (${role.organization ? "org " + role.organization.id
                                       : "global"})`;
  const ruleById = Object.fromEntries(ruleCache.map((r) => [r.id, r]));
  fill("rd_rules", role.rules || [], (rid) => {
    const r = ruleById[rid] || { name: rid, scope: "?", operation: "?" };
    return `<tr><td>${esc(r.name)}</td><td>${esc(r.scope)}</td>` +
      `<td>${esc(r.operation)}</td></tr>`;
  });
  const held = new Set(role.rules || []);
  $("rd_edit_rules").innerHTML = ruleCache.map((r) =>
    `<option value="${Number(r.id)}"${held.has(r.id) ? " selected" : ""}>` +
    `${esc(r.name)}:${esc(r.scope)}:${esc(r.operation)}</option>`).join("");
  $("rd_msg").textContent = ""; $("rd_err").textContent = "";
  $("roledetail").classList.remove("hidden");
};

$("rd_save").onclick = async () => {
  if (shownRole === null) return;
  try {
    $("rd_err").textContent = "";
    await api("PATCH", `role/${shownRole}`,
      { rules: selected("rd_edit_rules") });
    $("rd_msg").textContent = "rules updated";
    await refreshAdmin();
    showRole(shownRole);
  } catch (e) { $("rd_err").textContent = e.message; }
};

$("rd_delete").onclick = async () => {
  if (shownRole === null) return;
  try {
    $("rd_err").textContent = "";
    await api("DELETE", `role/${shownRole}`);
    $("roledetail").classList.add("hidden");
    shownRole = null;
    await refreshAdmin();
  } catch (e) { $("rd_err").textContent = e.message; }
};

window.showUser = function (id) {
  const u = userCache.find((x) => x.id === id);
  if (!u) return;
  shownUser = id;
  $("ud_name").textContent = `${u.username} (org ${u.organization.id})`;
  const held = new Set(u.roles || []);
  $("ud_roles").innerHTML = roleCache.map((r) =>
    `<option value="${Number(r.id)}"${held.has(r.id) ? " selected" : ""}>` +
    `${esc(r.name)}</option>`).join("");
  $("ud_msg").textContent = ""; $("ud_err").textContent = "";
  $("userdetail").classList.remove("hidden");
};

$("ud_save").onclick = async () => {
  if (shownUser === null) return;
  try {
    $("ud_err").textContent = "";
    await api("PATCH", `user/${shownUser}`,
      { roles: selected("ud_roles") });
    $("ud_msg").textContent = "roles updated";
    await refreshAdmin();
  } catch (e) { $("ud_err").textContent = e.message; }
};

const selected = (id) =>
  [...$(id).selectedOptions].map((o) => parseInt(o.value, 10));

$("o_create").onclick = async () => {
  try {
    $("orgerr").textContent = "";
    await api("POST", "organization",
      { name: $("o_name").value, country: $("o_country").value });
    $("o_name").value = "";
    await refreshAdmin();
  } catch (e) { $("orgerr").textContent = e.message; }
};

$("u_create").onclick = async () => {
  try {
    $("usererr").textContent = "";
    await api("POST", "user", {
      username: $("u_name").value, password: $("u_pass").value,
      email: $("u_email").value || null,
      organization_id: parseInt($("u_org").value, 10),
      roles: selected("u_roles"),
    });
    $("u_name").value = ""; $("u_pass").value = "";
    await refreshAdmin();
  } catch (e) { $("usererr").textContent = e.message; }
};

$("r_create").onclick = async () => {
  try {
    $("roleerr").textContent = "";
    await api("POST", "role", {
      name: $("r_name").value,
      organization_id: $("r_org").value ?
        parseInt($("r_org").value, 10) : null,
      rules: selected("r_rules"),
    });
    $("r_name").value = "";
    await refreshAdmin();
  } catch (e) { $("roleerr").textContent = e.message; }
};

$("pw_change").onclick = async () => {
  try {
    $("pwerr").textContent = ""; $("pw_msg").textContent = "";
    await api("POST", "password/change", {
      current_password: $("pw_current").value,
      new_password: $("pw_new").value,
    });
    $("pw_current").value = ""; $("pw_new").value = "";
    $("pw_msg").textContent = "password updated";
  } catch (e) { $("pwerr").textContent = e.message; }
};

// ------------------------------------------------------------------ store
async function refreshStore() {
  $("storeerr").textContent = "";
  const info = await api("GET", "store");
  if (!info.url) {
    $("s_url").textContent = "(no store linked)";
    fill("s_algos", [], () => ""); return;
  }
  $("s_url").textContent = info.url;
  try {
    const status = $("s_status").value;
    const algos = await api("GET", "store/algorithm" +
      (status ? `?status=${encodeURIComponent(status)}` : ""));
    storeAlgoCache = algos.data;
    fill("s_algos", algos.data, (a) =>
      `<tr><td><a onclick="showStoreAlgo(${Number(a.id)})">` +
      `${Number(a.id)}</a></td><td>${esc(a.name)}</td>` +
      `<td>${esc(a.image)}</td><td>${badge(a.status)}</td>` +
      `<td>${esc((a.functions || []).map((f) => f.name).join(", "))}</td>` +
      `</tr>`);
  } catch (e) { $("storeerr").textContent = e.message; }
}
$("s_status").onchange = () => refreshStore().catch(() => {});

$("sa_submit").onclick = async () => {
  try {
    $("saerr").textContent = ""; $("sa_msg").textContent = "";
    const fns = $("sa_functions").value.trim();
    await api("POST", "store/algorithm", {
      name: $("sa_name").value,
      image: $("sa_image").value,
      description: $("sa_desc").value,
      functions: fns ? JSON.parse(fns) : [],
    });
    $("sa_msg").textContent = "submitted — awaiting review";
    $("sa_name").value = ""; $("sa_image").value = "";
    await refreshStore();
  } catch (e) { $("saerr").textContent = e.message; }
};

let storeAlgoCache = [], shownStoreAlgo = null;
window.showStoreAlgo = async function (id) {
  const a = storeAlgoCache.find((x) => x.id === id);
  if (!a) return;
  shownStoreAlgo = id;
  $("s_d_name").textContent = `${a.name} (${a.image})`;
  $("s_d_desc").textContent = a.description || "";
  $("s_d_msg").textContent = ""; $("s_d_err").textContent = "";
  $("s_detailpanel").classList.remove("hidden");
  fill("s_d_functions", a.functions || [], (f) =>
    `<tr><td>${esc(f.display_name || f.name)}</td><td>${esc(f.type)}</td>` +
    `<td>${esc((f.arguments || []).map((x) =>
        `${x.name}:${x.type}${x.has_default ? "?" : ""}`).join(", "))}</td>` +
    `<td>${esc((f.databases || []).map((d) => d.name).join(", "))}</td>` +
    `</tr>`);
  await refreshStoreReviews(id);
};

async function refreshStoreReviews(algoId) {
  try {
    const reviews = await api("GET", `store/review?algorithm_id=${algoId}`);
    fill("s_d_reviews", reviews.data, (r) =>
      `<tr><td>${Number(r.id)}</td><td>${esc(r.reviewer)}</td>` +
      `<td>${badge(r.status)}</td><td>${esc(r.comment || "")}</td>` +
      `<td>${r.status === "under review" ?
        `<button onclick="decideReview(${Number(r.id)},'approved')">` +
        `approve</button> ` +
        `<button class="ghost" ` +
        `onclick="decideReview(${Number(r.id)},'rejected')">reject</button>`
        : ""}</td></tr>`);
  } catch (e) {
    // the review ledger needs a trusted-server token; browsing the public
    // registry must keep working without it
    fill("s_d_reviews", [], () => "");
    $("s_d_err").textContent = e.message;
  }
}

$("s_d_startreview").onclick = async () => {
  if (shownStoreAlgo === null) return;
  try {
    $("s_d_err").textContent = "";
    await api("POST", `store/algorithm/${shownStoreAlgo}/review`);
    $("s_d_msg").textContent = "review opened — decide below";
    await refreshStoreReviews(shownStoreAlgo);
    await refreshStore();
  } catch (e) { $("s_d_err").textContent = e.message; }
};

window.decideReview = async function (reviewId, verdict) {
  try {
    $("s_d_err").textContent = "";
    await api("PATCH", `store/review/${reviewId}`, {
      status: verdict, comment: $("s_d_comment").value,
    });
    $("s_d_msg").textContent = `review ${verdict}`;
    await refreshStoreReviews(shownStoreAlgo);
    await refreshStore();
  } catch (e) { $("s_d_err").textContent = e.message; }
};

async function enter() {
  $("login").classList.add("hidden");
  $("appview").classList.remove("hidden");
  $("logout").classList.remove("hidden");
  await refresh();
  loadWizardAlgos();  // once per session; the 3 s poll must not hit the store
}

$("signin").onclick = async () => {
  try {
    const data = await api("POST", "token/user", {
      username: $("username").value,
      password: $("password").value,
      mfa_code: $("mfa").value || null,
    });
    token = data.access_token;
    sessionStorage.setItem("v6t_token", token);
    $("whoami").textContent = data.user.username;
    await enter();
  } catch (e) { $("loginerr").textContent = e.message; }
};

$("logout").onclick = () => {
  sessionStorage.removeItem("v6t_token"); location.reload();
};

// --------------------------------------------------- task wizard (store)
// Approved store algorithms carry full function/argument metadata
// (reference: the Angular UI's "task wizard" builds its form from exactly
// this); picking one swaps the freeform inputs for a typed form.
let wizardAlgos = [];

async function loadWizardAlgos() {
  try {
    const info = await api("GET", "store");
    if (!info.url) return;
    const algos = await api("GET", "store/algorithm");
    wizardAlgos = algos.data.filter((a) => a.status === "approved");
    $("t_algo").innerHTML = `<option value="">freeform algorithm</option>` +
      wizardAlgos.map((a) =>
        `<option value="${Number(a.id)}">${esc(a.name)} (${esc(a.image)})` +
        `</option>`).join("");
  } catch (e) { /* store unreachable: freeform still works */ }
}

function wizardAlgo() {
  return wizardAlgos.find((a) => a.id === parseInt($("t_algo").value, 10));
}

$("t_algo").onchange = () => {
  const algo = wizardAlgo();
  $("t_freeform").classList.toggle("hidden", !!algo);
  $("t_wizard").classList.toggle("hidden", !algo);
  if (!algo) return;
  $("w_function").innerHTML = (algo.functions || []).map((f) =>
    `<option value="${esc(f.name)}">${esc(f.display_name || f.name)}` +
    ` [${esc(f.type)}]</option>`).join("");
  renderWizardArgs();
};
$("w_function").onchange = () => renderWizardArgs();

function wizardFunction() {
  const algo = wizardAlgo();
  return algo && (algo.functions || []).find(
    (f) => f.name === $("w_function").value);
}

function argInput(a) {
  const id = `wa_${esc(a.name)}`;
  const ph = esc(a.display_name || a.name) +
    (a.has_default ? ` (default ${esc(JSON.stringify(a.default))})` : "");
  const title = esc(a.description || a.name);
  if (a.type === "boolean")
    return `<label title="${title}"><input type="checkbox" id="${id}"` +
      `${a.default ? " checked" : ""}> ${esc(a.name)}</label>`;
  if (a.type === "organization" || a.type === "organization_list") {
    const collab = collabCache.find(
      (c) => c.id === parseInt($("t_collab").value, 10));
    const opts = (collab ? collab.organizations : []).map(
      (o) => `<option value="${Number(o)}">org ${Number(o)}</option>`).join("");
    const multi = a.type === "organization_list" ? " multiple size=3" : "";
    return `<select id="${id}" title="${title}"${multi}>${opts}</select>`;
  }
  // "string" and "column" are free text; "integer"/"float" parse at submit
  const size = a.type === "json" ? 28 :
    (a.type === "string" || a.type === "column") ? 16 :
    (a.type === "integer" || a.type === "float") ? 8 : 14;
  return `<input id="${id}" placeholder="${ph}" title="${title}"` +
    ` size="${size}">`;
}

function renderWizardArgs() {
  const fn = wizardFunction();
  $("w_fndesc").textContent = fn ? (fn.description || "") : "";
  $("w_args").innerHTML =
    (fn ? fn.arguments || [] : []).map(argInput).join(" ");
}

function wizardKwargs() {
  const fn = wizardFunction();
  const kwargs = {};
  for (const a of fn.arguments || []) {
    const el = $(`wa_${a.name}`);
    if (!el) continue;
    if (a.type === "boolean") { kwargs[a.name] = el.checked; continue; }
    if (a.type === "organization_list") {
      const ids = [...el.selectedOptions].map((o) => parseInt(o.value, 10));
      if (ids.length || !a.has_default) kwargs[a.name] = ids;
      continue;
    }
    const raw = el.value.trim();
    if (!raw) {
      if (!a.has_default)
        throw new Error(`argument "${a.name}" is required`);
      continue;  // omitted: the algorithm applies its default
    }
    if (a.type === "integer" || a.type === "organization")
      kwargs[a.name] = parseInt(raw, 10);
    else if (a.type === "float") kwargs[a.name] = parseFloat(raw);
    else if (a.type === "json") kwargs[a.name] = JSON.parse(raw);
    else kwargs[a.name] = raw;  // string | column
  }
  return kwargs;
}

$("t_create").onclick = async () => {
  try {
    $("taskerr").textContent = "";
    const algo = wizardAlgo();
    let image, method, kwargs;
    if (algo) {
      image = algo.image;
      method = $("w_function").value;
      kwargs = wizardKwargs();
    } else {
      image = $("t_image").value;
      method = $("t_method").value;
      kwargs = $("t_kwargs").value.trim() ?
        JSON.parse($("t_kwargs").value) : {};
    }
    const collab = parseInt($("t_collab").value, 10);
    const studyId = $("t_study").value ?
      parseInt($("t_study").value, 10) : null;
    let orgs;
    if (studyId) {
      orgs = (await api("GET", `study/${studyId}`)).organizations;
    } else {
      orgs = (await api("GET", `collaboration/${collab}`)).organizations;
    }
    const input = { method, kwargs };
    // unencrypted collaborations: plain base64 payload per org
    const blob = btoa(JSON.stringify(input));
    const body = {
      name: "ui task", image, method, collaboration_id: collab,
      organizations: orgs.map((id) => ({ id, input: blob })),
    };
    if (studyId) body.study_id = studyId;
    if ($("t_session").value) {
      body.session_id = parseInt($("t_session").value, 10);
      if ($("t_store_as").value.trim())
        body.store_as = $("t_store_as").value.trim();
    }
    await api("POST", "task", body);
    await refresh();
  } catch (e) { $("taskerr").textContent = e.message; }
};

// ----------------------------------------------------- studies & sessions
$("st_create").onclick = async () => {
  try {
    $("studyerr").textContent = "";
    await api("POST", "study", {
      name: $("st_name").value,
      collaboration_id: parseInt($("st_collab").value, 10),
      organization_ids: selected("st_orgs"),
    });
    $("st_name").value = "";
    await refresh();
  } catch (e) { $("studyerr").textContent = e.message; }
};

$("se_create").onclick = async () => {
  try {
    $("sesserr").textContent = "";
    await api("POST", "session", {
      name: $("se_name").value,
      collaboration_id: parseInt($("se_collab").value, 10),
      scope: $("se_scope").value,
    });
    $("se_name").value = "";
    await refresh();
  } catch (e) { $("sesserr").textContent = e.message; }
};

window.deleteSession = async function (id) {
  try { await api("DELETE", `session/${id}`); await refresh(); }
  catch (e) { $("sesserr").textContent = e.message; }
};

api("GET", "version").then((v) => $("version").textContent = "v" + v.version);
if (token) {
  api("GET", "whoami").then((w) => {
    $("whoami").textContent = w.username; enter();  // textContent: no XSS
  }).catch(() => { token = null; sessionStorage.removeItem("v6t_token"); });
}
setInterval(() => { if (token && !$("appview").classList.contains("hidden"))
  refresh().catch(() => {}); }, 3000);
</script>
</body>
</html>
"""


def register_ui(srv: "ServerApp") -> None:
    app = srv.app

    @app.route("/")
    @app.route("/ui")
    def ui(req: Request):
        return Response(
            PAGE.encode(), headers={"Content-Type": "text/html; charset=utf-8"}
        )
