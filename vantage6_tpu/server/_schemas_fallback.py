"""Minimal marshmallow-compatible validation fallback.

`server.schemas` prefers real marshmallow (reference parity). This module
implements the EXACT subset those schemas use — Str/Int/Bool/Float/Email/
List/Dict/Nested fields, Length/OneOf/Range validators, required /
load_default / partial / Meta.unknown=EXCLUDE semantics — so the control
plane keeps validating request bodies (and keeps returning the same 400s)
in environments where marshmallow is not installed. It is NOT a general
marshmallow replacement; anything outside that subset raises loudly.

Matched marshmallow behaviors relied on by the resources/tests:
- missing required field  -> {"field": ["Missing data for required field."]}
- load_default used when the key is absent (callables are called)
- a field whose load_default is None implicitly allows null payloads
- unknown keys are EXCLUDEd
- Schema(partial=True) demotes required fields (collaboration PATCH)
"""
from __future__ import annotations

import re
from typing import Any, Callable

EXCLUDE = "exclude"

_MISSING = object()


class ValidationError(Exception):
    def __init__(self, messages: Any):
        super().__init__(str(messages))
        self.messages = messages


class validate:  # noqa: N801 - namespace mirrors `marshmallow.validate`
    class Length:
        def __init__(self, min: int | None = None, max: int | None = None):
            self.min, self.max = min, max

        def __call__(self, value: Any) -> None:
            n = len(value)
            if self.min is not None and n < self.min:
                raise ValidationError(f"Shorter than minimum length {self.min}.")
            if self.max is not None and n > self.max:
                raise ValidationError(f"Longer than maximum length {self.max}.")

    class Range:
        def __init__(self, min: Any = None, max: Any = None):
            self.min, self.max = min, max

        def __call__(self, value: Any) -> None:
            if self.min is not None and value < self.min:
                raise ValidationError(
                    f"Must be greater than or equal to {self.min}."
                )
            if self.max is not None and value > self.max:
                raise ValidationError(
                    f"Must be less than or equal to {self.max}."
                )

    class OneOf:
        def __init__(self, choices: Any):
            self.choices = list(choices)

        def __call__(self, value: Any) -> None:
            if value not in self.choices:
                raise ValidationError(
                    f"Must be one of: {', '.join(map(str, self.choices))}."
                )


class Field:
    def __init__(
        self,
        required: bool = False,
        load_default: Any = _MISSING,
        validate: Callable[[Any], Any] | None = None,
    ):
        self.required = required
        self.load_default = load_default
        self.validators = [validate] if validate is not None else []
        # marshmallow: load_default=None implicitly sets allow_none=True
        self.allow_none = load_default is None

    def deserialize(self, value: Any) -> Any:
        if value is None:
            if self.allow_none:
                return None
            raise ValidationError("Field may not be null.")
        value = self._coerce(value)
        for v in self.validators:
            v(value)
        return value

    def _coerce(self, value: Any) -> Any:  # pragma: no cover - abstract
        return value


class Str(Field):
    def _coerce(self, value: Any) -> str:
        if not isinstance(value, str):
            raise ValidationError("Not a valid string.")
        return value


class Email(Str):
    _RE = re.compile(r"^[^@\s]+@[^@\s]+\.[^@\s]+$")

    def _coerce(self, value: Any) -> str:
        value = super()._coerce(value)
        if not self._RE.match(value):
            raise ValidationError("Not a valid email address.")
        return value


class Int(Field):
    def _coerce(self, value: Any) -> int:
        if isinstance(value, bool):
            raise ValidationError("Not a valid integer.")
        if isinstance(value, int):
            return value
        if isinstance(value, float) and value.is_integer():
            return int(value)
        if isinstance(value, str):
            try:
                return int(value)
            except ValueError:
                pass
        raise ValidationError("Not a valid integer.")


class Float(Field):
    def _coerce(self, value: Any) -> float:
        if isinstance(value, bool):
            raise ValidationError("Not a valid number.")
        if isinstance(value, (int, float)):
            return float(value)
        if isinstance(value, str):
            try:
                return float(value)
            except ValueError:
                pass
        raise ValidationError("Not a valid number.")


class Bool(Field):
    _TRUTHY = {"true", "True", "1", "on", "yes"}
    _FALSY = {"false", "False", "0", "off", "no"}

    def _coerce(self, value: Any) -> bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, int) and value in (0, 1):
            return bool(value)
        if isinstance(value, str):
            if value in self._TRUTHY:
                return True
            if value in self._FALSY:
                return False
        raise ValidationError("Not a valid boolean.")


class List(Field):
    def __init__(self, inner: Field, **kw: Any):
        super().__init__(**kw)
        self.inner = inner

    def _coerce(self, value: Any) -> list:
        if not isinstance(value, list):
            raise ValidationError("Not a valid list.")
        return [self.inner.deserialize(v) for v in value]


class Dict(Field):
    def __init__(self, keys: Field | None = None, values: Field | None = None,
                 **kw: Any):
        super().__init__(**kw)
        self.keys, self.values = keys, values

    def _coerce(self, value: Any) -> dict:
        if not isinstance(value, dict):
            raise ValidationError("Not a valid mapping type.")
        out = {}
        for k, v in value.items():
            if self.keys is not None:
                k = self.keys.deserialize(k)
            if self.values is not None:
                v = self.values.deserialize(v)
            out[k] = v
        return out


class Nested(Field):
    def __init__(self, nested: Any, **kw: Any):
        super().__init__(**kw)
        self.nested = nested

    def _coerce(self, value: Any) -> Any:
        schema = self.nested() if isinstance(self.nested, type) else self.nested
        return schema.load(value)


class fields:  # noqa: N801 - namespace mirrors `marshmallow.fields`
    Str = Str
    Int = Int
    Bool = Bool
    Float = Float
    Email = Email
    List = List
    Dict = Dict
    Nested = Nested


class Schema:
    class Meta:
        unknown = EXCLUDE

    def __init__(self, partial: bool = False):
        self.partial = partial

    @classmethod
    def _declared_fields(cls) -> dict[str, Field]:
        out: dict[str, Field] = {}
        for klass in reversed(cls.__mro__):
            for name, value in vars(klass).items():
                if isinstance(value, Field):
                    out[name] = value
        return out

    def load(self, data: Any) -> dict[str, Any]:
        if not isinstance(data, dict):
            raise ValidationError({"_schema": ["Invalid input type."]})
        errors: dict[str, list[str]] = {}
        out: dict[str, Any] = {}
        for name, field in self._declared_fields().items():
            if name in data:
                try:
                    out[name] = field.deserialize(data[name])
                except ValidationError as e:
                    msgs = e.messages
                    errors[name] = msgs if isinstance(msgs, list) else [msgs]
            elif field.required and not self.partial:
                errors[name] = ["Missing data for required field."]
            elif field.load_default is not _MISSING:
                d = field.load_default
                out[name] = d() if callable(d) else d
        if errors:
            raise ValidationError(errors)
        return out
