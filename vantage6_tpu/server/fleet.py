"""Fleet store: cross-host telemetry aggregation over the storage backend.

The ingest half of the fleet fabric (`common/fleet.py` is the push
half): snapshots POSTed to `/api/telemetry` — and the server's own
self-ingested snapshots — land here as CAS-free appends in the
`fleet_metric` / `fleet_event` tables (migration v8), so N replicas
over one `sqlite+wal` store serve ONE coherent fleet view. All helpers
are module-level functions taking the `db` handle (the `pubsub.py`
idiom): no per-replica state beyond what the store itself holds.

Reads:

- :func:`fleet_view` — `GET /api/fleet`'s body: per-source freshness,
  the merged counter/gauge census (latest row per source+series;
  counters sum across sources, gauges too — capacity-shaped gauges add,
  and per-source values stay inspectable under ``sources``), and the
  top-k counter deltas over the fast window ("what is the fleet doing
  right now").
- :func:`metric_series` — the SLO engine's windowed sample scan.
- :func:`liveness` — fresh/total daemon sources, the daemon-liveness
  SLO's subject ratio.

Retention: :func:`prune` deletes samples older than the retention
floor (``V6T_FLEET_RETENTION_S``, default 2 h) but always keeps the
newest row per (source, series) — a quiet source ages toward *stale*,
it never silently vanishes from the census. Called on an ingest
cadence (every ``PRUNE_EVERY`` ingests), the `DbPubSub._prune` stance:
pruning must never fail a push.
"""
from __future__ import annotations

import json
import time
from typing import Any

from vantage6_tpu.common.env import env_float
from vantage6_tpu.common.telemetry import REGISTRY

# retention floor for samples/events; the newest row per series survives
RETENTION_S = env_float("V6T_FLEET_RETENTION_S", 7200.0)
# a source whose newest snapshot is older than this is stale (3x the
# default push interval: one missed push is jitter, three is a lapse)
STALE_AFTER_S = env_float("V6T_FLEET_STALE_S", 45.0)
PRUNE_EVERY = 32
TOP_K_DELTAS = 8

# replica-local: ingest cadence counter for the pruner (approximate by
# design — each replica prunes on its own 1/PRUNE_EVERY of ingests)
_INGESTS = 0

# sqlite's default variable cap is 999; 6 columns/row -> stay well under
_ROWS_PER_INSERT = 120


def ingest(db: Any, payload: dict[str, Any]) -> dict[str, int]:
    """Append one decoded push payload (see `common.fleet.build_snapshot`)
    to the store. CAS-free: rows are only ever inserted, never updated —
    two replicas ingesting concurrently cannot conflict. Returns the
    appended row counts."""
    global _INGESTS
    from vantage6_tpu.common.fleet import sample_kind

    now = time.time()
    source = str(payload["source"])
    service = str(payload.get("service") or "")
    seq = int(payload.get("seq") or 0)
    # clamp the sample timestamp into sane wall-clock: a pusher with a
    # skewed clock must not land samples in the far future (they would
    # pin the census) or before the retention floor (instantly pruned)
    ts = float(payload.get("ts") or now)
    ts = min(max(ts, now - RETENTION_S), now + 60.0)

    metrics = payload.get("metrics") or {}
    rows = [
        (source, service, seq, str(name), sample_kind(str(name)),
         float(value), ts)
        for name, value in metrics.items()
        if isinstance(value, (int, float)) and not isinstance(value, bool)
    ]
    for i in range(0, len(rows), _ROWS_PER_INSERT):
        chunk = rows[i:i + _ROWS_PER_INSERT]
        sql = (
            "INSERT INTO fleet_metric "
            "(source, service, seq, name, kind, value, ts) VALUES "
            + ", ".join(["(?, ?, ?, ?, ?, ?, ?)"] * len(chunk))
        )
        db.execute(sql, [v for row in chunk for v in row])

    events = 0
    for note in payload.get("notes") or []:
        if not isinstance(note, dict) or not note.get("kind"):
            continue
        db.execute(
            "INSERT INTO fleet_event (source, service, kind, ts, data) "
            "VALUES (?, ?, ?, ?, ?)",
            [source, service, str(note["kind"]),
             float(note.get("ts") or ts),
             json.dumps({k: v for k, v in note.items()
                         if k not in ("kind", "ts")}, default=str)],
        )
        events += 1

    REGISTRY.counter("v6t_fleet_ingests_total").inc()
    REGISTRY.counter("v6t_fleet_ingest_rows_total").inc(len(rows))
    _INGESTS += 1
    if _INGESTS % PRUNE_EVERY == 0:
        try:
            prune(db, now)
        except Exception:  # pruning must never fail a push
            pass
    return {"metrics": len(rows), "events": events}


def record_sample(
    db: Any,
    source: str,
    service: str,
    name: str,
    value: float,
    ts: float | None = None,
) -> None:
    """Append one per-event sample (e.g. a run's dispatch latency at its
    start transition) — the SLO engine's event-grade series, finer than
    the snapshot cadence."""
    from vantage6_tpu.common.fleet import sample_kind

    db.execute(
        "INSERT INTO fleet_metric "
        "(source, service, seq, name, kind, value, ts) "
        "VALUES (?, ?, ?, ?, ?, ?, ?)",
        [source, service, 0, name, sample_kind(name), float(value),
         ts if ts is not None else time.time()],
    )


def prune(db: Any, now: float | None = None) -> int:
    """Delete samples/events past the retention floor, keeping the
    newest row per (source, series) so quiet sources stay visible as
    stale instead of vanishing. Returns rows deleted."""
    now = now if now is not None else time.time()
    floor = now - RETENTION_S
    cur = db.execute(
        "DELETE FROM fleet_metric WHERE ts < ? AND id NOT IN "
        "(SELECT MAX(id) FROM fleet_metric GROUP BY source, name)",
        [floor],
    )
    deleted = cur.rowcount or 0
    cur = db.execute("DELETE FROM fleet_event WHERE ts < ?", [floor])
    deleted += cur.rowcount or 0
    if deleted:
        REGISTRY.counter("v6t_fleet_pruned_rows_total").inc(deleted)
    return deleted


def sources(db: Any, now: float | None = None) -> list[dict[str, Any]]:
    """Per-source freshness: newest snapshot age, push seq, series count.
    Also refreshes the fleet census gauges — every caller of the fleet
    view or the watchdog feed keeps them current."""
    now = now if now is not None else time.time()
    out = []
    for r in db.query(
        "SELECT source, MAX(service) AS service, MAX(ts) AS last_ts, "
        "MAX(seq) AS seq, COUNT(DISTINCT name) AS series "
        "FROM fleet_metric GROUP BY source ORDER BY source"
    ):
        age = now - float(r["last_ts"])
        out.append({
            "source": r["source"],
            "service": r["service"] or "",
            "last_seen_at": float(r["last_ts"]),
            "age_s": round(age, 3),
            "stale": age > STALE_AFTER_S,
            "seq": int(r["seq"] or 0),
            "series": int(r["series"]),
        })
    REGISTRY.gauge("v6t_fleet_sources").set(len(out))
    REGISTRY.gauge("v6t_fleet_stale_sources").set(
        sum(1 for s in out if s["stale"])
    )
    return out


def _latest_rows(db: Any) -> list[dict[str, Any]]:
    return db.query(
        "SELECT m.source, m.name, m.kind, m.value, m.ts FROM fleet_metric m "
        "JOIN (SELECT source, name, MAX(id) AS mid FROM fleet_metric "
        "GROUP BY source, name) x ON m.id = x.mid"
    )


def census(db: Any) -> dict[str, dict[str, float]]:
    """The merged fleet census: latest value per (source, series),
    summed across sources per series. Counters sum into fleet totals by
    construction; gauges sum into fleet capacity/occupancy (per-source
    values remain readable through the raw samples)."""
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    for r in _latest_rows(db):
        bucket = counters if r["kind"] == "counter" else gauges
        bucket[r["name"]] = bucket.get(r["name"], 0.0) + float(r["value"] or 0)
    return {"counters": counters, "gauges": gauges}


def top_deltas(
    db: Any,
    window_s: float,
    now: float | None = None,
    k: int = TOP_K_DELTAS,
) -> list[dict[str, Any]]:
    """The k counter series that moved most over the trailing window —
    newest minus oldest in-window sample per (source, series), summed
    per series. The "what is the fleet doing right now" read."""
    now = now if now is not None else time.time()
    rows = db.query(
        "SELECT source, name, value, ts FROM fleet_metric "
        "WHERE kind = 'counter' AND ts >= ? ORDER BY id",
        [now - window_s],
    )
    first: dict[tuple[str, str], float] = {}
    last: dict[tuple[str, str], float] = {}
    for r in rows:
        key = (r["source"], r["name"])
        first.setdefault(key, float(r["value"] or 0))
        last[key] = float(r["value"] or 0)
    deltas: dict[str, float] = {}
    for key, end in last.items():
        d = end - first[key]
        if d > 0:
            deltas[key[1]] = deltas.get(key[1], 0.0) + d
    ranked = sorted(deltas.items(), key=lambda kv: -kv[1])[:k]
    return [
        {"name": name, "delta": round(delta, 6), "window_s": window_s}
        for name, delta in ranked
    ]


def metric_series(
    db: Any, name: str, since: float
) -> list[dict[str, Any]]:
    """All samples of one series since ``since``, oldest first, across
    every source — the SLO engine's windowed history."""
    return [
        {"metric": name, "source": r["source"], "ts": float(r["ts"]),
         "value": float(r["value"] or 0)}
        for r in db.query(
            "SELECT source, value, ts FROM fleet_metric "
            "WHERE name = ? AND ts >= ? ORDER BY ts",
            [name, since],
        )
    ]


def recent_events(
    db: Any, since: float, limit: int = 100
) -> list[dict[str, Any]]:
    out = []
    for r in db.query(
        "SELECT source, service, kind, ts, data FROM fleet_event "
        "WHERE ts >= ? ORDER BY id DESC LIMIT ?",
        [since, limit],
    ):
        try:
            data = json.loads(r["data"]) if r["data"] else {}
        except (TypeError, ValueError):
            data = {}
        out.append({
            "source": r["source"], "service": r["service"] or "",
            "kind": r["kind"], "ts": float(r["ts"]), **data,
        })
    out.reverse()
    return out


def liveness(
    db: Any, now: float | None = None
) -> tuple[int, int, list[dict[str, Any]]]:
    """(fresh daemon sources, total daemon sources, all sources) — the
    daemon-liveness SLO's subject. Only daemon-service sources count:
    a finished bench Federation going quiet is expected, a daemon is
    not."""
    rows = sources(db, now)
    daemons = [s for s in rows if s["service"].startswith("daemon")]
    fresh = sum(1 for s in daemons if not s["stale"])
    return fresh, len(daemons), rows


def fleet_view(db: Any, now: float | None = None) -> dict[str, Any]:
    """`GET /api/fleet`'s body (also doctor --live's raw material)."""
    from vantage6_tpu.runtime.watchdog import WATCHDOG

    now = now if now is not None else time.time()
    fast_window = float(WATCHDOG.config.get("slo_fast_window_s", 300.0))
    fresh, daemons, rows = liveness(db, now)
    return {
        "ts": now,
        "sources": rows,
        "census": census(db),
        "top_deltas": top_deltas(db, fast_window, now),
        "events": recent_events(db, now - fast_window),
        "liveness": {
            "fresh_daemons": fresh,
            "daemons": daemons,
            "ratio": (fresh / daemons) if daemons else 1.0,
            "stale_after_s": STALE_AFTER_S,
        },
        "retention_s": RETENTION_S,
    }


def health_block(db: Any, now: float | None = None) -> dict[str, Any]:
    """The compact fleet section folded into `GET /api/health`."""
    now = now if now is not None else time.time()
    fresh, daemons, rows = liveness(db, now)
    return {
        "sources": len(rows),
        "stale_sources": sum(1 for s in rows if s["stale"]),
        "fresh_daemons": fresh,
        "daemons": daemons,
    }
