"""Multi-host / multi-slice federation bootstrap (DCN scale-out).

The reference scales out with more node daemons over HTTPS (SURVEY.md
§2.4); the TPU-native data plane scales out with more PROCESSES over DCN:
each host (one process per TPU slice, or per machine on CPU) initializes
the JAX coordination service, after which ``jax.devices()`` is the GLOBAL
device list and one ``FederationMesh`` spans every slice — XLA routes
collectives over ICI within a slice and DCN across slices, exactly the
"mesh axes ride the fastest fabric" recipe of the scaling playbook.

Deployment contract (mirrors how real vantage6 stations hold only their own
data): every process loads ONLY the shards of the stations it hosts;
``stack_local_shards`` assembles the global station-stacked array from the
per-process pieces without any host ever holding another host's rows.

Works identically on a laptop: ``initialize()`` with no configuration is a
no-op single-process setup, and the same code runs on the in-process mesh.
Tested with real multi-process CPU collectives (Gloo) in
tests/test_distributed.py.
"""
from __future__ import annotations

import os
from typing import Any, Mapping, Sequence

import jax
import numpy as np

from vantage6_tpu.core.mesh import FederationMesh

_initialized = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids: Sequence[int] | None = None,
    auto: bool = False,
) -> bool:
    """Join (or skip) the multi-process coordination service. Idempotent.

    Resolution order per field: explicit argument > environment
    (``V6T_COORDINATOR``, ``V6T_NUM_PROCESSES``, ``V6T_PROCESS_ID``).
    With NO configuration found, the default is plain single-process local
    mode (returns False, no side effects) — pass ``auto=True`` on managed
    clusters (TPU pods, slurm, GKE) to hand detection to
    ``jax.distributed.initialize()``'s cluster plugins instead; auto mode
    raises if no cluster is detected rather than silently running
    single-process (each host training a disjoint federation is exactly
    the failure this guards against).

    Returns True when running multi-process, False for single-process.
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get(
        "V6T_COORDINATOR"
    )
    if num_processes is None and os.environ.get("V6T_NUM_PROCESSES"):
        num_processes = int(os.environ["V6T_NUM_PROCESSES"])
    if process_id is None and os.environ.get("V6T_PROCESS_ID"):
        process_id = int(os.environ["V6T_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        if not auto:
            # single-process mode: nothing to join
            return False
        jax.distributed.initialize()  # cluster plugins; raises if none
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    _initialized = True
    return jax.process_count() > 1


def global_mesh(
    n_stations: int, devices_per_station: int = 1
) -> FederationMesh:
    """A FederationMesh over the GLOBAL device list (all processes).

    Call after ``initialize()``. Single-process, this is exactly
    ``FederationMesh(n_stations, ...)``.
    """
    return FederationMesh(
        n_stations,
        devices=jax.devices(),
        devices_per_station=devices_per_station,
    )


def local_stations(mesh: FederationMesh) -> list[int]:
    """The station indices THIS process hosts (owns the devices of).

    Station i lives in station-axis slot ``i // stations_per_slot``
    (contiguous blocks — the fed_map packing contract); a slot belongs to
    the process owning its first device.
    """
    me = jax.process_index()
    spp = mesh.stations_per_slot
    out = []
    for i in range(mesh.n_stations):
        slot = i // spp
        if mesh.mesh.devices[slot, 0].process_index == me:
            out.append(i)
    return out


def stack_local_shards(
    mesh: FederationMesh,
    shards: Mapping[int, np.ndarray] | Sequence[np.ndarray],
    dtype: Any = None,
) -> jax.Array:
    """Build the global ``[S, ...]`` station-stacked array from THIS
    process's shards only.

    ``shards`` maps station index -> that station's (padded) array, and
    must cover exactly ``local_stations(mesh)`` — each host contributes its
    own stations; no host ever materializes another host's rows. (A plain
    sequence is accepted single-process, where local == all.)
    """
    mine = local_stations(mesh)
    if not mine:
        raise ValueError(
            f"process {jax.process_index()} hosts NO stations: the mesh "
            f"uses {mesh.station_axis_size * mesh.devices_per_station} of "
            "the global devices and none of this process's devices made "
            "the cut — size n_stations/devices_per_station so every "
            "process owns at least one station slot"
        )
    if not isinstance(shards, Mapping):
        shards = dict(enumerate(shards))
    missing = [i for i in mine if i not in shards]
    extra = [i for i in shards if i not in mine]
    if missing or extra:
        raise ValueError(
            f"process {jax.process_index()} hosts stations {mine}; shards "
            f"missing {missing}, not-local {extra} — every process passes "
            "exactly its own stations' data"
        )
    local = np.stack([np.asarray(shards[i], dtype=dtype) for i in mine])
    return jax.make_array_from_process_local_data(
        mesh.station_sharding(), local
    )
