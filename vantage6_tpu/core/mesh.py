"""Federation mesh: maps N data stations onto the available JAX devices.

This is the TPU-native replacement for the reference's data plane
(vantage6-node daemons + Docker containers + HTTPS transport; SURVEY.md §1/§3).
Each *data station* owns a slice of a `jax.sharding.Mesh`; a federated round is
one jitted SPMD program in which "partial" functions run per-station under
`shard_map` and "central" aggregation lowers to XLA collectives over ICI.

Design (scales 1 chip -> full pod with one code path):

- All per-station state is *stacked* on a leading station axis: an array of
  shape ``[S, ...]`` holds every station's shard.
- The mesh has axes ``('station', 'device')``. The station mesh-axis size D is
  the largest divisor of S that fits the available devices; each of the D mesh
  slots simulates ``S/D`` stations via an inner ``vmap``. With D == S every
  station owns real devices; with D == 1 the same program runs on a laptop.
- ``fed_map(fn, ...)`` = ``shard_map(vmap(fn))`` over the station axis.
- Aggregation is expressed at the jnp level on station-sharded arrays
  (``jnp.sum(x, axis=0)``) so GSPMD inserts the all-reduce/reduce-scatter —
  the idiomatic XLA path — with explicit-collective variants in
  ``vantage6_tpu.fed`` where masking/secure-sum needs per-station RNG.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 top-level; older: experimental
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

# fed_map requires variance checking OFF (see the comment at the call site:
# with it on, jax auto-psums gradients of replicated inputs across the mesh,
# silently breaking per-station gradient isolation). Resolve the flag name
# once here; if a future jax renames it again, fail LOUDLY — running with
# the check enabled would corrupt federated semantics without any error.
import inspect as _inspect

_SHARD_MAP_PARAMS = _inspect.signature(shard_map).parameters
if "check_vma" in _SHARD_MAP_PARAMS:
    _NO_VMA_KW = {"check_vma": False}
elif "check_rep" in _SHARD_MAP_PARAMS:  # pragma: no cover - older jax
    _NO_VMA_KW = {"check_rep": False}
else:  # pragma: no cover
    raise RuntimeError(
        "cannot disable shard_map variance checking (no check_vma/check_rep "
        "parameter in this jax version) — fed_map's per-station gradient "
        "isolation would silently break; pin a compatible jax or update "
        "vantage6_tpu.core.mesh"
    )

STATION_AXIS = "station"
DEVICE_AXIS = "device"


def station_shard_map(mesh: "FederationMesh", fn: Callable[..., Any],
                      in_specs: Any, out_specs: Any) -> Callable[..., Any]:
    """``shard_map`` over a FederationMesh with variance checking disabled
    (same rationale as ``fed_map``) — the entry point for explicit-collective
    code (``fed.collectives`` scattered primitives) that needs
    ``psum_scatter``/``all_gather`` with named-axis control instead of
    leaving the reduction to GSPMD."""
    return shard_map(
        fn, mesh=mesh.mesh, in_specs=in_specs, out_specs=out_specs,
        **_NO_VMA_KW,
    )


@dataclasses.dataclass(frozen=True)
class Station:
    """One data station (reference: a vantage6 node at an organization).

    In the reference a station is a daemon next to private data; here it is an
    index into the station axis of the federation mesh plus metadata. The
    privacy boundary is preserved *semantically* by the API (partials only see
    their own shard; only aggregates cross stations), not by physical network
    isolation — see docs/THREAT_MODEL.md for the honest mapping.
    """

    index: int
    name: str
    organization: str = ""
    databases: dict[str, Any] = dataclasses.field(default_factory=dict)


def _largest_divisor_leq(n: int, cap: int) -> int:
    for d in range(min(n, cap), 0, -1):
        if n % d == 0:
            return d
    return 1


class FederationMesh:
    """Owns the device mesh and the station-axis execution primitives.

    Parameters
    ----------
    n_stations:
        Number of data stations S in the federation.
    devices:
        Flat list of JAX devices (default: ``jax.devices()``).
    devices_per_station:
        Devices forming each station's sub-mesh (tensor/model parallelism
        *within* a station rides the ``device`` mesh axis).
    """

    def __init__(
        self,
        n_stations: int,
        devices: Sequence[jax.Device] | None = None,
        devices_per_station: int = 1,
    ):
        if n_stations < 1:
            raise ValueError("n_stations must be >= 1")
        devices = list(devices if devices is not None else jax.devices())
        if devices_per_station < 1 or devices_per_station > len(devices):
            raise ValueError("invalid devices_per_station")
        self.n_stations = n_stations
        self.devices_per_station = devices_per_station
        usable = len(devices) // devices_per_station
        # Station mesh-axis size: largest divisor of S fitting the hardware.
        self.station_axis_size = _largest_divisor_leq(n_stations, usable)
        self.stations_per_slot = n_stations // self.station_axis_size
        n_used = self.station_axis_size * devices_per_station
        dev_array = np.array(devices[:n_used]).reshape(
            self.station_axis_size, devices_per_station
        )
        self.mesh = Mesh(dev_array, (STATION_AXIS, DEVICE_AXIS))

    # ------------------------------------------------------------------ specs
    def station_spec(self, *trailing: Any) -> P:
        """PartitionSpec sharding the leading (station) axis."""
        return P(STATION_AXIS, *trailing)

    def station_sharding(self, *trailing: Any) -> NamedSharding:
        return NamedSharding(self.mesh, self.station_spec(*trailing))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_stacked(self, tree: Any) -> Any:
        """Place a pytree of stacked ``[S, ...]`` arrays onto the mesh,
        station axis sharded. Works for numpy or jax inputs."""
        sh = self.station_sharding()
        return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sh), tree)

    def replicate(self, tree: Any) -> Any:
        sh = self.replicated_sharding()
        return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sh), tree)

    # ------------------------------------------------------------- execution
    def fed_map(
        self,
        fn: Callable[..., Any],
        *stacked_args: Any,
        replicated_args: tuple[Any, ...] = (),
    ) -> Any:
        """Run ``fn`` once per station; return stacked ``[S, ...]`` outputs.

        ``stacked_args`` are pytrees whose leaves carry a leading station axis
        of size S (sharded over the mesh's station axis). ``replicated_args``
        are broadcast to every station (e.g. the global model). This is the
        TPU-native analogue of the reference's "create one subtask per
        organization" fan-out (SURVEY.md §3.1) — but it is a single SPMD
        program, not N containers.
        """
        n_s = len(stacked_args)

        def block_fn(*args):
            s_args, r_args = args[:n_s], args[n_s:]
            # Each mesh slot holds a [S/D, ...] block of stations; the inner
            # vmap walks the stations within the block.
            return jax.vmap(lambda *sa: fn(*sa, *r_args))(*s_args)

        in_specs = tuple(self.station_spec() for _ in stacked_args) + tuple(
            P() for _ in replicated_args
        )
        # Variance checking OFF: station blocks are PURELY LOCAL programs.
        # With it on, replicated (P()) inputs are "unvarying" and jax
        # auto-inserts a psum over the mesh on any gradient taken w.r.t. them
        # inside the body — silently turning each station's local gradient
        # into the cross-station sum (breaking the federated privacy/
        # isolation contract, not just numerics). All cross-station reduction
        # happens explicitly, outside fed_map, via fed.collectives.
        return shard_map(
            block_fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=self.station_spec(),
            **_NO_VMA_KW,
        )(*stacked_args, *replicated_args)

    def fingerprint(self) -> tuple:
        """Hashable identity of everything a compiled runner depends on:
        station count, mesh factorization, and the exact device placement.
        Two meshes with equal fingerprints produce identical shardings, so
        jitted programs traced against one are reusable with the other —
        the key workload runner caches (glm/quantiles) use instead of mesh
        OBJECT identity, which would recompile (and leak a cache entry) for
        every fresh FederationMesh over the same devices."""
        return (
            self.n_stations,
            self.station_axis_size,
            self.devices_per_station,
            tuple(d.id for d in self.mesh.devices.flat),
        )

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"FederationMesh(S={self.n_stations}, "
            f"station_axis={self.station_axis_size}, "
            f"per_slot={self.stations_per_slot}, "
            f"dps={self.devices_per_station})"
        )
