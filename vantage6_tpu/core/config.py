"""Federation configuration: YAML configs + contexts.

Parity: vantage6-common context/configuration_manager (SURVEY.md §2 item 22) —
the reference locates YAML node/server configs in well-known dirs, validates
them against a schema, and exposes them through ``NodeContext``/
``ServerContext``. Here one *federation* YAML describes the whole simulated
network (server-side entities + every station), because stations are mesh
slices of one pod rather than daemons on separate machines.

Example::

    federation:
      name: demo
      encrypted: false
      devices_per_station: 1
    stations:
      - name: station_a
        organization: org_a
        api_key: "..."           # optional; parity with node api_key auth
        databases:
          - label: default
            type: csv
            uri: data/a.csv
        policies:
          allowed_algorithms: ["*"]
"""
from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Any

import yaml


class ConfigurationError(Exception):
    pass


@dataclasses.dataclass
class DatabaseConfig:
    """One data source at a station (reference: node config `databases:`)."""

    label: str
    type: str = "csv"  # csv | parquet | excel | sql | sparql | omop | array | session
    uri: str = ""
    options: dict[str, Any] = dataclasses.field(default_factory=dict)

    _KNOWN_TYPES = {
        "csv", "parquet", "excel", "sql", "sparql", "omop", "array",
        "session",  # a session-store dataframe (node-resolved local path)
    }

    def validate(self) -> None:
        if not self.label:
            raise ConfigurationError("database needs a label")
        if self.type not in self._KNOWN_TYPES:
            raise ConfigurationError(
                f"unknown database type {self.type!r}; expected one of "
                f"{sorted(self._KNOWN_TYPES)}"
            )


@dataclasses.dataclass
class StationConfig:
    """Config of one data station (reference: one node YAML)."""

    name: str
    organization: str = ""
    api_key: str = ""
    databases: list[DatabaseConfig] = dataclasses.field(default_factory=list)
    policies: dict[str, Any] = dataclasses.field(default_factory=dict)

    def validate(self) -> None:
        if not self.name:
            raise ConfigurationError("station needs a name")
        labels = [d.label for d in self.databases]
        if len(labels) != len(set(labels)):
            raise ConfigurationError(f"duplicate database labels in {self.name}")
        for d in self.databases:
            d.validate()

    def database(self, label: str = "default") -> DatabaseConfig:
        for d in self.databases:
            if d.label == label:
                return d
        raise KeyError(f"station {self.name} has no database {label!r}")


@dataclasses.dataclass
class FederationConfig:
    """The whole federation: global options + all stations."""

    name: str = "federation"
    encrypted: bool = False
    devices_per_station: int = 1
    # Host-path station executor pool (runtime.executor.StationExecutor):
    #   None -> default min(n_stations, os.cpu_count()) worker threads;
    #   0    -> fully synchronous dispatch (the deterministic-debug escape
    #           hatch — today's sequential semantics, no threads at all);
    #   N>0  -> exactly N worker threads (per-station serialization holds
    #           at any size).
    executor_workers: int | None = None
    # Gradient compression of host-plane delta exchanges (a
    # fed.compression.CompressorSpec, or None): when set, algorithm code
    # can route update payloads through client.compress_update /
    # client.decompress_update and the federation keeps per-station
    # error-feedback accumulators between rounds (docs/compression.md).
    # Typed Any so core stays import-light; validate() duck-checks it.
    compressor: Any = None
    # Learning-plane recording of device-mode aggregations
    # (docs/observability.md "learning plane"): every aggregate_stacked
    # records per-station update stats into the process LEARNING
    # registry. The stats pass pulls the [S, N] stacked result to host
    # once per aggregation — set False where that transfer matters
    # (large models on a real pod), same stance as
    # FedAvgSpec.learning_stats.
    learning_stats: bool = True
    # Autopilot remediation engine (runtime.autopilot —
    # docs/OPERATOR_GUIDE.md "autopilot"): when `enabled`, the Federation
    # attaches an Autopilot to the process watchdog with itself as the
    # actuator (mask / selection-weight / admission capabilities). Keys:
    #   enabled: bool (default False — opt in per federation)
    #   dry_run: bool (log + count decisions, touch nothing)
    #   disable: [rule names] (turn individual policies off)
    #   straggler_weight: float (shrunk selection weight, default 0.25)
    autopilot: dict[str, Any] | None = None
    stations: list[StationConfig] = dataclasses.field(default_factory=list)
    server: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def n_stations(self) -> int:
        return len(self.stations)

    def resolved_executor_workers(self) -> int:
        """The effective pool size (0 = synchronous)."""
        if self.executor_workers is not None:
            return self.executor_workers
        return min(self.n_stations, os.cpu_count() or 1)

    def validate(self) -> None:
        if not self.stations:
            raise ConfigurationError("federation needs at least one station")
        if self.executor_workers is not None and self.executor_workers < 0:
            raise ConfigurationError(
                "executor_workers must be >= 0 (0 = synchronous dispatch)"
            )
        if self.compressor is not None:
            validate = getattr(self.compressor, "validate", None)
            if not callable(validate):
                raise ConfigurationError(
                    "compressor must be a CompressorSpec "
                    "(vantage6_tpu.fed.compression) or None"
                )
            try:
                validate()
            except ValueError as e:
                raise ConfigurationError(f"bad compressor: {e}") from e
        if self.autopilot is not None:
            if not isinstance(self.autopilot, dict):
                raise ConfigurationError(
                    "federation.autopilot must be a mapping "
                    "(enabled/dry_run/disable/straggler_weight), got "
                    f"{self.autopilot!r}"
                )
            allowed = {"enabled", "dry_run", "disable", "straggler_weight"}
            unknown = set(self.autopilot) - allowed
            if unknown:
                raise ConfigurationError(
                    "federation.autopilot: unknown key(s) "
                    f"{sorted(unknown)} (expected {sorted(allowed)})"
                )
        names = [s.name for s in self.stations]
        if len(names) != len(set(names)):
            raise ConfigurationError("duplicate station names")
        for s in self.stations:
            s.validate()

    # ---------------------------------------------------------------- yaml io
    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "FederationConfig":
        fed = raw.get("federation", {}) or {}
        stations = []
        for s in raw.get("stations", []) or []:
            dbs = [
                DatabaseConfig(
                    label=d.get("label", "default"),
                    type=d.get("type", "csv"),
                    uri=_interp_env(str(d.get("uri", ""))),
                    options=d.get("options", {}) or {},
                )
                for d in (s.get("databases", []) or [])
            ]
            stations.append(
                StationConfig(
                    name=s.get("name", ""),
                    organization=s.get("organization", ""),
                    api_key=s.get("api_key", ""),
                    databases=dbs,
                    policies=s.get("policies", {}) or {},
                )
            )
        workers = fed.get("executor_workers")
        compressor = None
        comp_raw = fed.get("compression")
        if comp_raw:
            if not isinstance(comp_raw, dict):
                raise ConfigurationError(
                    "federation.compression must be a mapping "
                    "(topk_ratio/int8/chunk/error_feedback), got "
                    f"{comp_raw!r}"
                )
            # unknown keys fail LOUD: a typo ('topk:' — the V6T_COMPRESS
            # spelling — instead of 'topk_ratio:') would otherwise build
            # an identity spec and silently disable compression
            allowed = {"topk_ratio", "int8", "chunk", "error_feedback"}
            unknown = set(comp_raw) - allowed
            if unknown:
                raise ConfigurationError(
                    "federation.compression: unknown key(s) "
                    f"{sorted(unknown)} (expected {sorted(allowed)})"
                )
            # lazy import: core stays free of the fed/jax dependency unless
            # a config actually turns compression on
            from vantage6_tpu.fed.compression import CompressorSpec

            ratio = comp_raw.get("topk_ratio")
            compressor = CompressorSpec(
                topk_ratio=None if ratio is None else float(ratio),
                int8=bool(comp_raw.get("int8", False)),
                chunk=int(comp_raw.get("chunk", 256)),
                error_feedback=bool(comp_raw.get("error_feedback", True)),
            )
        cfg = cls(
            name=fed.get("name", "federation"),
            encrypted=bool(fed.get("encrypted", False)),
            devices_per_station=int(fed.get("devices_per_station", 1)),
            executor_workers=None if workers is None else int(workers),
            compressor=compressor,
            autopilot=fed.get("autopilot"),
            stations=stations,
            server=raw.get("server", {}) or {},
        )
        cfg.validate()
        return cfg

    @classmethod
    def load(cls, path: str | Path) -> "FederationConfig":
        with open(path) as f:
            raw = yaml.safe_load(f)
        if not isinstance(raw, dict):
            raise ConfigurationError(f"{path}: not a mapping")
        return cls.from_dict(raw)

    def to_dict(self) -> dict[str, Any]:
        return {
            "federation": {
                "name": self.name,
                "encrypted": self.encrypted,
                "devices_per_station": self.devices_per_station,
                "executor_workers": self.executor_workers,
                **(
                    {"autopilot": self.autopilot}
                    if self.autopilot is not None else {}
                ),
            },
            "server": self.server,
            "stations": [
                {
                    "name": s.name,
                    "organization": s.organization,
                    "api_key": s.api_key,
                    "databases": [
                        {
                            "label": d.label,
                            "type": d.type,
                            "uri": d.uri,
                            "options": d.options,
                        }
                        for d in s.databases
                    ],
                    "policies": s.policies,
                }
                for s in self.stations
            ],
        }

    def save(self, path: str | Path) -> None:
        with open(path, "w") as f:
            yaml.safe_dump(self.to_dict(), f, sort_keys=False)


def _interp_env(value: str) -> str:
    """`${VAR}` env interpolation in URIs (reference config does the same)."""
    return os.path.expandvars(value)


def default_config_dir() -> Path:
    """Well-known per-user config dir (reference uses appdirs)."""
    from vantage6_tpu.common.context import config_root

    return config_root()


def demo_federation(n_stations: int = 2, name: str = "dev") -> FederationConfig:
    """Generate a demo federation config (reference: `v6 dev create-demo-network`)."""
    return FederationConfig(
        name=name,
        stations=[
            StationConfig(
                name=f"station_{i}",
                organization=f"org_{i}",
                databases=[DatabaseConfig(label="default", type="array")],
            )
            for i in range(n_stations)
        ],
    )
