"""Station executor pool — parallel host-path execution.

The reference network runs one daemon per node, all concurrently: a round's
wall-clock is max-over-stations, not sum-over-stations. This module gives the
in-process Federation the same semantics for host-mode runs:

- A shared ``ThreadPoolExecutor`` of ``workers`` threads executes queued run
  items.
- **Per-station serialization**: each station has a FIFO queue and at most
  ONE thread ever executes that station's items at a time (matching the
  one-daemon-per-node reality, and keeping per-station session stores safe
  without fine-grained locking inside algorithms).
- **Re-entrant help while waiting** (the deadlock-avoidance rule for nested
  subtasks): a thread that is executing a run and blocks waiting for other
  runs (a central partial inside ``wait_for_results`` / a nested
  ``create_task(wait=True)``) lends itself to the queue via
  :meth:`help_or_wait` — it may claim items of any idle station AND of
  stations it itself holds (its own run is suspended in the wait, so the
  one-thread-per-station invariant is preserved). This is why a pool of ANY
  size, even 1, cannot deadlock on central→partial fan-out, including a
  central whose subtask lands on its own station.

Threads that are NOT executing a run (e.g. the user's main thread polling
``wait_for_results``) never steal work — they sleep on the condition variable
so an explicit ``timeout`` keeps its polling semantics.
"""
from __future__ import annotations

import threading
import weakref
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

# telemetry: queue-depth visibility across every live pool in the process
# (the unified registry's executor series — docs/observability.md; the
# collector itself is registered by common.telemetry, which imports this
# set lazily so the series exists even before any pool does). A WeakSet
# so abandoned pools vanish from the gauge with their GC, not at an
# explicit close.
_LIVE_POOLS: "weakref.WeakSet[StationExecutor]" = weakref.WeakSet()


class StationExecutor:
    """FIFO-per-station work queue on top of a bounded thread pool."""

    def __init__(self, n_stations: int, workers: int):
        if n_stations < 1:
            raise ValueError("n_stations must be >= 1")
        if workers < 1:
            raise ValueError(
                "workers must be >= 1 (use no executor at all for the "
                "synchronous escape hatch)"
            )
        self.n_stations = n_stations
        self.workers = workers
        self._cond = threading.Condition()
        # guarded-by: _cond
        self._queues: list[deque[Callable[[], Any]]] = [
            deque() for _ in range(n_stations)
        ]
        # thread currently executing (or holding, while blocked in a nested
        # wait) each station; None = idle
        self._executing: list[threading.Thread | None] = [None] * n_stations  # guarded-by: _cond
        self._inflight = 0  # guarded-by: _cond
        self._rr = 0  # guarded-by: _cond (round-robin claim start)
        self._tls = threading.local()
        self._shutdown = False  # guarded-by: _cond
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="v6t-station"
        )
        _LIVE_POOLS.add(self)

    # ----------------------------------------------------------------- submit
    def submit(self, station: int, item: Callable[[], Any]) -> None:
        """Queue ``item`` on ``station``'s FIFO; a pool thread (or a helping
        waiter) will execute it, never concurrently with another item of the
        same station."""
        if not 0 <= station < self.n_stations:
            raise ValueError(f"unknown station {station}")
        with self._cond:
            if self._shutdown:
                raise RuntimeError("executor is shut down")
            self._queues[station].append(item)
            self._inflight += 1
            self._cond.notify_all()
        self._pool.submit(self._pump)

    # ------------------------------------------------------------------ claim
    def _held(self) -> list[int]:
        """Stations the CURRENT thread is executing items for, innermost
        last (a stack: re-entrant helping nests)."""
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _claim(self, steal_held: bool) -> tuple[int, Callable[[], Any]] | None:
        """Pop the next item of a claimable station (idle, or — when
        ``steal_held`` — held by this very thread, whose run is suspended in
        a wait). Returns None when nothing is claimable right now."""
        me = threading.current_thread()
        held = self._held()
        with self._cond:
            n = self.n_stations
            start = self._rr
            self._rr = (self._rr + 1) % n
            for off in range(n):
                s = (start + off) % n
                if not self._queues[s]:
                    continue
                owner = self._executing[s]
                if owner is None or (steal_held and owner is me and s in held):
                    item = self._queues[s].popleft()
                    self._executing[s] = me
                    return s, item
        return None

    def _run_item(self, station: int, item: Callable[[], Any]) -> None:
        held = self._held()
        held.append(station)
        try:
            item()
        finally:
            held.pop()
            with self._cond:
                self._inflight -= 1
                if station not in held:
                    self._executing[station] = None
                more = bool(self._queues[station]) and not self._shutdown
                self._cond.notify_all()
            if more:
                # whoever ran this item may stop draining (a helper returning
                # to its wait loop): make sure a pool thread comes back for
                # the rest of this station's queue
                self._pool.submit(self._pump)

    def _pump(self) -> None:
        """Pool-thread drain loop: claim and run items until none are
        claimable. One pump is submitted per item, so queued work can never
        be orphaned — extra pumps find nothing and exit."""
        while True:
            claimed = self._claim(steal_held=False)
            if claimed is None:
                return
            self._run_item(*claimed)

    # ------------------------------------------------------------------- wait
    def help_or_wait(self, timeout: float) -> bool:
        """One iteration of a wait loop.

        A thread currently executing a run (``held`` non-empty) lends itself
        to the queue — claiming any idle station's item or, re-entrantly, an
        item of a station it holds. Other threads (and helpers that find
        nothing claimable) sleep up to ``timeout`` on the condition variable,
        which is notified on every submit and completion. Returns True if an
        item was executed inline.
        """
        if self._held():
            claimed = self._claim(steal_held=True)
            if claimed is not None:
                self._run_item(*claimed)
                return True
        with self._cond:
            if self._inflight:
                self._cond.wait(timeout)
        return False

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every submitted item has executed (or ``timeout``
        elapsed). Returns True when the queue is empty."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._inflight:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining if remaining is not None else 1.0)
        return True

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    def stats(self) -> dict[str, Any]:
        """Queue-depth view for the ops plane (watchdog queue_buildup feed
        + /api/alerts context): total inflight, worker capacity, and the
        per-station queue lengths that tell a uniformly-loaded pool from
        one station's FIFO wedged behind a long run."""
        with self._cond:
            return {
                "inflight": self._inflight,
                "workers": self.workers,
                "n_stations": self.n_stations,
                "queued_per_station": [len(q) for q in self._queues],
                "executing_stations": [
                    i for i, t in enumerate(self._executing) if t is not None
                ],
            }

    # ------------------------------------------------------------- lifecycle
    def close(self) -> None:
        """Tear down the pool. Queued-but-unstarted items are dropped —
        only for Federation teardown, never mid-protocol."""
        _LIVE_POOLS.discard(self)  # dropped items would pin the gauge
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
        self._pool.shutdown(wait=False, cancel_futures=True)
