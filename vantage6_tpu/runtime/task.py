"""Task and Run records — the control-plane vocabulary of the reference.

Parity: vantage6-server ORM `Task` / `Run` entities (SURVEY.md §2 item 2) and
the status lifecycle of §2 item 23. A *task* is one request ("run `method` of
`image` on these organizations"); it fans out into one *run* per target
organization. The reference persists these in SQLAlchemy and moves them via
REST+SocketIO; here they are in-memory records moved by the orchestrator, with
identical states so client code observing them ports unchanged.
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Any

from vantage6_tpu.common.enums import TaskStatus

_task_ids = itertools.count(1)
_run_ids = itertools.count(1)


@dataclasses.dataclass
class Run:
    """One organization's execution of a task (reference: `Run`, né `Result`).

    Status transitions are thread-safe and terminal-sticky: with the station
    executor pool a run may be started by a worker thread while `kill_task`
    flips it to KILLED from another — whoever reaches a terminal state first
    wins, and a late `finish`/`crash` must NOT overwrite a kill (parity: the
    server rejects status patches on terminal runs with 409). Each mutator
    returns whether it applied.
    """

    id: int
    task_id: int
    organization: str
    station_index: int
    status: TaskStatus = TaskStatus.PENDING
    result: Any = None
    log: str = ""
    assigned_at: float = dataclasses.field(default_factory=time.time)
    # set when the run is queued onto the station executor pool; together
    # with started_at/finished_at this gives the queued→started→finished
    # lifecycle runtime.metrics.run_lifecycle decomposes (straggler view)
    queued_at: float | None = None
    started_at: float | None = None
    finished_at: float | None = None
    # on-wire payload accounting (common.serialization.wire_nbytes): what
    # this run's input/result WOULD cost on the v2 binary wire — lets the
    # straggler view tell compute-bound from transfer-bound stations even
    # in the in-process host path, which never actually serializes. None =
    # not measured or not wire-serializable.
    input_wire_bytes: int | None = None
    result_wire_bytes: int | None = None
    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def start(self) -> bool:
        with self._lock:
            if self.status.is_finished:
                return False  # killed while queued: never goes ACTIVE
            self.status = TaskStatus.ACTIVE
            self.started_at = time.time()
            return True

    def finish(self, result: Any) -> bool:
        with self._lock:
            if self.status.is_finished:
                return False  # killed mid-execution: drop the result
            self.result = result
            self.status = TaskStatus.COMPLETED
            self.finished_at = time.time()
            return True

    def crash(self, log: str) -> bool:
        with self._lock:
            if self.status.is_finished:
                return False
            self.log = log
            self.status = TaskStatus.CRASHED
            self.finished_at = time.time()
            return True

    def kill(self) -> bool:
        """Parity: the server's kill event. Queued (not-yet-started) and
        ACTIVE runs flip to KILLED; finished runs are immutable."""
        with self._lock:
            if self.status.is_finished:
                return False
            self.status = TaskStatus.KILLED
            self.finished_at = time.time()
            return True

    def mark_queued(self) -> None:
        self.queued_at = time.time()


@dataclasses.dataclass
class Task:
    """A federated task: method + input fanned out to organizations.

    `image` survives as the algorithm identifier (the reference addresses
    algorithms by Docker image name; here it names a registered algorithm
    module — same role, no container).
    """

    id: int
    name: str
    method: str
    image: str
    organizations: list[str]
    input_: dict[str, Any] = dataclasses.field(default_factory=dict)
    databases: list[dict[str, Any]] = dataclasses.field(default_factory=list)
    parent_id: int | None = None  # subtasks created by a central fn
    init_org: str = ""
    init_user: str = ""
    collaboration: str = ""
    # sessions: the workspace this task runs in + the handle its returned
    # dataframe is persisted under at each station
    session_id: int | None = None
    store_as: str | None = None
    # estimated v2 on-wire size of input_ (shared by every run — a
    # broadcast sends ONE ciphertext; see encrypt_bytes_broadcast)
    input_wire_bytes: int | None = None
    runs: list[Run] = dataclasses.field(default_factory=list)
    created_at: float = dataclasses.field(default_factory=time.time)
    # Device-mode only: the stacked [S, ...] on-device result pytree (full
    # station axis) plus the [S] participation mask — 1.0 where the station
    # was targeted by this task AND completed. Central aggregation consumes
    # both without a host round-trip.
    stacked_result: Any = None
    participation: Any = None

    @property
    def status(self) -> TaskStatus:
        """Aggregate status over runs (reference computes the same rollup)."""
        if not self.runs:
            return TaskStatus.PENDING
        statuses = {r.status for r in self.runs}
        for bad in (TaskStatus.KILLED, TaskStatus.NOT_ALLOWED, TaskStatus.NO_IMAGE,
                    TaskStatus.CRASHED, TaskStatus.FAILED):
            if bad in statuses:
                return bad
        if statuses == {TaskStatus.COMPLETED}:
            return TaskStatus.COMPLETED
        if TaskStatus.ACTIVE in statuses or TaskStatus.INITIALIZING in statuses:
            return TaskStatus.ACTIVE
        return TaskStatus.PENDING

    @property
    def is_finished(self) -> bool:
        return self.status.is_finished

    def results(self) -> list[Any]:
        return [r.result for r in self.runs]

    def to_dict(self) -> dict[str, Any]:
        """Wire shape compatible with the reference's /api/task JSON."""
        return {
            "id": self.id,
            "name": self.name,
            "image": self.image,
            "method": self.method,
            "status": self.status.value,
            "parent": {"id": self.parent_id} if self.parent_id else None,
            "collaboration": {"name": self.collaboration},
            "runs": [
                {
                    "id": r.id,
                    "organization": r.organization,
                    "status": r.status.value,
                }
                for r in self.runs
            ],
        }


def new_task(**kw: Any) -> Task:
    return Task(id=next(_task_ids), **kw)


def new_run(**kw: Any) -> Run:
    return Run(id=next(_run_ids), **kw)
