"""Live health watchdog: declarative alert rules over telemetry + run state.

PR 5 built the *attribution* substrate (tracing + the unified telemetry
registry); this module is the *detection* layer on top of it — the ops
plane that tells an operator something is wrong while it is still wrong,
instead of leaving a stuck round to be discovered in `trace_view` after
the fact.

Design:

- **Rules** (:class:`AlertRule`) are declarative: a snake_case name, a
  severity, a human summary + runbook line, the telemetry series they
  read (audited against ``KNOWN_METRICS`` by ``tools/check_collect.py``
  — a rule referencing an undeclared metric fails CI), and a pure
  ``check(ctx)`` returning findings.
- **Context** (:class:`RuleContext`) is everything a rule may look at:
  the current unified-telemetry snapshot, a bounded per-metric history
  (for trend rules: queue buildup, EF mass growth, eviction deltas), and
  the run/node/round **feeds** registered by live components — the
  server registers its DB view (ACTIVE runs, node ping freshness), an
  in-process Federation registers its executor/round view. Feeds are
  keyed (replacement semantics, like telemetry collectors) and fail-soft.
- **Alerts** are stateful raise/clear transitions, deduplicated on
  ``(rule, labels)``. A raise emits: a WARNING log line (trace-correlated
  when the subject has a trace), telemetry counters/gauges
  (``v6t_alerts_*``), a flight-recorder note, and a trace span — parented
  on the affected task's own trace when the feed supplies its
  ``traceparent``, so the alert lands **inside the stuck round's
  timeline** for `tools/doctor.py` to merge.
- **Health** — components (event hub, tracer sink, the watchdog's own
  evaluation loop) register self-checks; :meth:`Watchdog.health` folds
  them with active critical alerts into the ``ok``/``degraded`` verdict
  behind the server's upgraded ``GET /api/health``.

The process-wide singleton is :data:`WATCHDOG` (same stance as
``TRACER``/``REGISTRY``): the server starts its evaluation thread and
serves its state at ``GET /api/alerts``; simulators and tests register
feeds and call :meth:`Watchdog.evaluate` directly for determinism.

Env knobs (read at construction; ``configure()`` overrides live):
``V6T_WATCHDOG_INTERVAL`` (seconds between evaluations, default 5),
``V6T_RUN_DEADLINE_S`` (stuck-run threshold, default 300),
``V6T_PING_WINDOW_S`` (daemon lapse threshold, default 60).
"""
from __future__ import annotations

import dataclasses
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable

from vantage6_tpu.common.env import env_float
from vantage6_tpu.common.log import setup_logging
from vantage6_tpu.common.telemetry import REGISTRY, metric_kind as _metric_kind
from vantage6_tpu.runtime.tracing import TRACER

log = setup_logging("vantage6_tpu/watchdog")

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

SEVERITIES = ("info", "warning", "critical")


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative detection: name, severity, what it means, what to
    do, which telemetry series it reads, and the check itself.

    ``metrics`` is the audited contract: every name listed here must be
    declared in ``common.telemetry.KNOWN_METRICS`` (check_collect gate) —
    a rule silently reading a renamed/undeclared series is exactly the
    drift the audit exists to catch. Feed-only rules declare ``()``.
    """

    name: str
    severity: str
    summary: str
    runbook: str
    metrics: tuple[str, ...]
    check: Callable[["RuleContext"], list[dict[str, Any]]]

    def validate(self) -> None:
        if not _NAME_RE.match(self.name):
            raise ValueError(
                f"alert rule name {self.name!r} must be snake_case"
            )
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"alert rule {self.name}: severity {self.severity!r} not in "
                f"{SEVERITIES}"
            )
        if not self.summary or not self.runbook:
            raise ValueError(
                f"alert rule {self.name}: summary and runbook are required"
            )


class RuleContext:
    """What one evaluation pass shows a rule: current metric values, short
    per-metric history, and every registered feed's state."""

    def __init__(
        self,
        snapshot: dict[str, Any],
        history: dict[str, deque],
        feeds: dict[str, Any],
        config: dict[str, Any],
        now: float,
    ):
        self.snapshot = snapshot
        self._history = history
        self.feeds = feeds
        self.config = config
        self.now = now

    def metric(self, name: str, default: float | None = None) -> float | None:
        v = self.snapshot.get(name, default)
        return v if isinstance(v, (int, float)) else default

    def history(self, name: str) -> list[tuple[float, float]]:
        """Oldest-first (ts, value) samples, one per evaluation."""
        return list(self._history.get(name, ()))

    def feed_items(self, key: str) -> list[dict[str, Any]]:
        """Concatenate list-valued entries named ``key`` across every
        feed — rules stay topology-agnostic (a server feed and a
        simulator Federation feed both publish "runs")."""
        out: list[dict[str, Any]] = []
        for state in self.feeds.values():
            if isinstance(state, dict):
                items = state.get(key)
                if isinstance(items, (list, tuple)):
                    out.extend(i for i in items if isinstance(i, dict))
        return out


# ------------------------------------------------------------ default rules


def _check_stuck_run(ctx: RuleContext) -> list[dict[str, Any]]:
    deadline = float(ctx.config["run_deadline_s"])
    findings = []
    for run in ctx.feed_items("runs"):
        if run.get("status") != "active":
            continue
        base = run.get("started_at") or run.get("assigned_at")
        if base is None:
            continue
        # a run whose status events are still flowing is slow, not stuck —
        # feeds that track event freshness override the start timestamp
        last_event = run.get("last_event_ts")
        if last_event is not None:
            base = max(base, last_event)
        age = ctx.now - float(base)
        if age > deadline:
            findings.append({
                "message": (
                    f"run {run.get('run_id')} of task {run.get('task_id')} "
                    f"ACTIVE for {age:.1f}s with no status events "
                    f"(deadline {deadline:g}s)"
                ),
                "labels": {
                    "run_id": run.get("run_id"),
                    "task_id": run.get("task_id"),
                },
                "traceparent": run.get("traceparent"),
            })
    return findings


def _check_daemon_lapsed(ctx: RuleContext) -> list[dict[str, Any]]:
    window = float(ctx.config["ping_window_s"])
    findings = []
    for node in ctx.feed_items("nodes"):
        if node.get("status") != "online":
            continue
        last = node.get("last_seen_at")
        if last is None:
            continue
        age = ctx.now - float(last)
        if age > window:
            findings.append({
                "message": (
                    f"node {node.get('node_id')} "
                    f"({node.get('name') or 'unnamed'}) claims online but "
                    f"last ping was {age:.1f}s ago (window {window:g}s)"
                ),
                "labels": {"node_id": node.get("node_id")},
            })
    return findings


def _check_replica_lapsed(ctx: RuleContext) -> list[dict[str, Any]]:
    findings = []
    for rep in ctx.feed_items("replicas"):
        if rep.get("alive"):
            continue
        last = rep.get("last_seen_at")
        age = (ctx.now - float(last)) if last is not None else None
        findings.append({
            "message": (
                f"server replica {rep.get('replica_id')} "
                f"(pid {rep.get('pid')}) stopped heartbeating"
                + (f" {age:.1f}s ago" if age is not None else "")
                + " — crashed or partitioned from the shared store"
            ),
            "labels": {"replica_id": rep.get("replica_id")},
        })
    return findings


def station_window_flags(
    rounds: list[dict[str, Any]],
    window: int,
    flag_fn: Callable[[dict[str, Any]], Any],
) -> tuple[dict[Any, int], dict[Any, tuple[float, str]], int]:
    """The ONE per-station rolling-window census the station-shaped rules
    (``straggler_station``, ``anomalous_station``) share: scan the last
    ``window`` round dicts, let ``flag_fn(round)`` yield zero or more
    ``(key, score, detail)`` flags (a round may flag several stations),
    and return ``(flag counts per key, worst (score, detail) per key,
    rounds considered)``. "Worst" keeps the highest-score flag's
    preformatted detail so each rule's message can name the offending
    stat without re-deriving it."""
    recent = rounds[-window:]
    counts: dict[Any, int] = {}
    worst: dict[Any, tuple[float, str]] = {}
    for r in recent:
        for key, score, detail in flag_fn(r) or ():
            counts[key] = counts.get(key, 0) + 1
            if key not in worst or score > worst[key][0]:
                worst[key] = (float(score), str(detail))
    return counts, worst, len(recent)


def _check_straggler_station(ctx: RuleContext) -> list[dict[str, Any]]:
    need = int(ctx.config["straggler_rounds"])
    ratio = float(ctx.config["straggler_ratio"])
    window = int(ctx.config["straggler_window"])

    def flag(r: dict[str, Any]):
        station = r.get("straggler_station")
        mx = r.get("max_exec_s")
        mean = r.get("mean_exec_s")
        if station is None or not mx or not mean or r.get("n", 0) < 2:
            return ()
        if mx / mean >= ratio:
            return ((station, mx / mean, f"{mx / mean:.1f}x the round mean"),)
        return ()

    counts, worst, n_rounds = station_window_flags(
        ctx.feed_items("rounds"), window, flag
    )
    return [
        {
            "message": (
                f"station {station} was the straggler in {n} of the last "
                f"{n_rounds} rounds (worst {worst[station][1]})"
            ),
            "labels": {"station": station},
        }
        for station, n in counts.items()
        if n >= need
    ]


def _check_anomalous_station(ctx: RuleContext) -> list[dict[str, Any]]:
    cos_thr = float(ctx.config["anomaly_cos_threshold"])
    factor = float(ctx.config["anomaly_norm_factor"])
    need = int(ctx.config["anomaly_rounds"])
    window = int(ctx.config["anomaly_window"])

    def flag(r: dict[str, Any]):
        # keys are per-(task, station) already; the WINDOW below is
        # applied per task too (see the grouping loop) — slicing the
        # merged cross-task feed would let concurrent tasks dilute each
        # other's evidence and a poisoned station would never reach the
        # repeat threshold on a busy server
        median = r.get("median_norm") or 0.0
        pooled = r.get("update_norm") or 0.0
        flags = []
        for st in r.get("stations") or ():
            station = st.get("station")
            if station is None:
                continue
            # a masked-out station's stats are fictional (SPMD computes
            # them, the pooled update excludes them) AND the documented
            # remediation for this very alert is "mask the station" —
            # flagging non-participants would make the alert impossible
            # to clear by its own runbook
            if st.get("participating") is False:
                continue
            key = (r.get("task"), station)
            cos = st.get("cos")
            norm = st.get("norm")
            # cosine is only evidence when there is an update on BOTH
            # sides: a zero-norm station (sent nothing this round) and a
            # zero pooled update both degenerate to cos == 0, which is
            # absence of signal, not a contrarian update
            if (
                isinstance(cos, (int, float))
                and cos < cos_thr
                and isinstance(norm, (int, float)) and norm > 0
                and pooled > 0
            ):
                # score by how far below the threshold: the most
                # contrarian round's cosine names the stat
                flags.append((
                    key, cos_thr - cos,
                    f"cosine to the pooled update {cos:.3f} "
                    f"(threshold {cos_thr:g})",
                ))
            elif (
                isinstance(norm, (int, float))
                and median > 0
                and norm >= factor * median
            ):
                flags.append((
                    key, norm / median,
                    f"update norm {norm / median:.1f}x the station median "
                    f"(threshold {factor:g}x)",
                ))
        return flags

    by_task: dict[Any, list[dict[str, Any]]] = {}
    for r in ctx.feed_items("learning_rounds"):
        by_task.setdefault(r.get("task"), []).append(r)
    findings = []
    for rounds in by_task.values():
        counts, worst, n_rounds = station_window_flags(rounds, window, flag)
        for key, n in counts.items():
            if n < need:
                continue
            task, station = key
            findings.append({
                "message": (
                    f"station {station} (task {task}) sent anomalous "
                    f"updates in {n} of the last {n_rounds} recorded "
                    f"rounds — worst: {worst[key][1]}"
                ),
                "labels": {"task": task, "station": station},
            })
    return findings


def _check_model_divergence(ctx: RuleContext) -> list[dict[str, Any]]:
    need = int(ctx.config["divergence_rounds"])
    min_growth = float(ctx.config["divergence_min_growth_pct"])
    findings = []
    for item in ctx.feed_items("learning_tasks"):
        norms = [
            v for v in (item.get("recent_norms") or ())
            if isinstance(v, (int, float))
        ][-(need + 1):]
        if len(norms) < need + 1 or norms[0] <= 0:
            continue
        # strictly increasing over the window AND real growth overall —
        # round-to-round wobble is normal, a monotone climb is not
        if not all(b > a for a, b in zip(norms, norms[1:])):
            continue
        growth_pct = 100.0 * (norms[-1] - norms[0]) / norms[0]
        if growth_pct < min_growth:
            continue
        findings.append({
            "message": (
                f"task {item.get('task')}: global update norm grew "
                f"monotonically over the last {need} recorded rounds "
                f"({norms[0]:.3g} -> {norms[-1]:.3g}, "
                f"+{growth_pct:.1f}%) — the model is diverging"
            ),
            "labels": {"task": item.get("task")},
        })
    return findings


def _check_non_convergence(ctx: RuleContext) -> list[dict[str, Any]]:
    budget = int(ctx.config["non_convergence_rounds"])
    window = int(ctx.config["non_convergence_window"])
    min_decay = float(ctx.config["non_convergence_decay_pct"])
    converged = float(ctx.config["non_convergence_converged_ratio"])
    findings = []
    for item in ctx.feed_items("learning_tasks"):
        rounds = item.get("rounds") or 0
        if rounds < budget:
            continue
        norms = [
            v for v in (item.get("recent_norms") or ())
            if isinstance(v, (int, float))
        ][-window:]
        if len(norms) < 2 or norms[0] <= 0:
            continue
        peak = item.get("peak_norm") or 0.0
        # a CONVERGED run plateaus near zero relative to its peak —
        # plateau-at-the-bottom is success, not a stall
        if peak > 0 and norms[-1] <= converged * peak:
            continue
        decay_pct = 100.0 * (norms[0] - norms[-1]) / norms[0]
        if decay_pct >= min_decay:
            continue
        # a NEGATIVE decay is the norm growing non-monotonically —
        # model_divergence's strictly-monotone check stays quiet, but
        # telling the operator "decay stalled, fell only -80%" would
        # misdiagnose a blow-up as a stall and point at the wrong runbook
        if decay_pct < 0:
            trend = (
                f"the global update norm ROSE {-decay_pct:.1f}% (non-"
                "monotonically — check model_divergence and the lr)"
            )
        else:
            trend = (
                "norm decay stalled — the global update norm fell only "
                f"{decay_pct:.1f}%"
            )
        findings.append({
            "message": (
                f"task {item.get('task')}: {trend} over the "
                f"last {len(norms)} recorded rounds "
                f"({norms[0]:.3g} -> {norms[-1]:.3g}) after {rounds} "
                f"rounds (budget {budget})"
            ),
            "labels": {"task": item.get("task")},
        })
    return findings


def _check_queue_buildup(ctx: RuleContext) -> list[dict[str, Any]]:
    factor = float(ctx.config["queue_factor"])
    sustain = int(ctx.config["queue_sustain_evals"])
    hist = ctx.history("v6t_executor_inflight_items")[-sustain:]
    if len(hist) < sustain:
        return []
    # "sustained" means sustained in WALL CLOCK, not in sample count:
    # ad-hoc evaluate() calls (close()'s reconcile pass, tests) can land
    # samples milliseconds apart and would promote a momentary spike to a
    # sustained backlog. Half the nominal spacing tolerates loop jitter.
    min_span = 0.5 * (sustain - 1) * float(
        ctx.config.get("eval_interval_s", 0.0)
    )
    if hist[-1][0] - hist[0][0] < min_span:
        return []
    capacity = max(1.0, ctx.metric("v6t_executor_capacity", 0.0) or 0.0)
    threshold = factor * capacity
    if all(v > threshold for _, v in hist):
        inflight = hist[-1][1]
        return [{
            "message": (
                f"executor backlog: {inflight:g} items in flight vs "
                f"{capacity:g} worker slots ({factor:g}x threshold) for "
                f"{sustain} consecutive evaluations"
            ),
            "labels": {},
        }]
    return []


def _check_event_cursor_lag(ctx: RuleContext) -> list[dict[str, Any]]:
    # key on ACTUAL truncated fetches (a consumer asked for history the
    # ring already evicted), not on eviction itself — a busy server's full
    # ring evicts on every emit as steady state, which proves nothing
    # strictly consecutive samples: the engine zero-fills this counter's
    # history while it is still absent from the snapshot, so the first
    # truncation of a process lifetime shows as a 0 -> 1 step — and a
    # count predating THIS watchdog's start never reads as a fresh jump
    hist = ctx.history("v6t_event_truncated_total")
    if len(hist) < 2:
        return []
    prev, cur = hist[-2][1], hist[-1][1]
    if cur > prev:
        evicted = ctx.metric("v6t_event_hub_evicted_through", 0.0)
        cursor = ctx.metric("v6t_event_hub_cursor", 0.0)
        return [{
            "message": (
                f"{cur - prev:g} event fetch(es) answered truncated since "
                f"the last evaluation (evicted_through {evicted:g}, cursor "
                f"{cursor:g}): lagging consumers are missing events and "
                "paying full resyncs"
            ),
            "labels": {},
        }]
    return []


def _check_ef_mass_growth(ctx: RuleContext) -> list[dict[str, Any]]:
    need = int(ctx.config["ef_growth_evals"])
    hist = ctx.history("v6t_compress_ef_norm")[-(need + 1):]
    if len(hist) < need + 1:
        return []
    values = [v for _, v in hist]
    if values[-1] > 0 and all(b > a for a, b in zip(values, values[1:])):
        return [{
            "message": (
                "compression error-feedback mass grew for "
                f"{need} consecutive evaluations "
                f"(ef_norm {values[0]:.3g} -> {values[-1]:.3g}): residual "
                "error is accumulating instead of shipping"
            ),
            "labels": {},
        }]
    return []


def _check_recompile_storm(ctx: RuleContext) -> list[dict[str, Any]]:
    # a retrace or two is normal warm-up (new batch shape, first donated
    # round); a STORM is the counter stepping every evaluation — the
    # signature key is unstable and every dispatch recompiles
    need = int(ctx.config["recompile_storm_retraces"])
    window = int(ctx.config["recompile_storm_window"])
    hist = ctx.history("v6t_jit_retraces_total")[-(window + 1):]
    if len(hist) < 2:
        return []
    delta = hist[-1][1] - hist[0][1]
    if delta < need:
        return []
    # name the culprit: the device-plane feed carries recent retrace
    # events with the function and the leaf that changed. Scope to THIS
    # window (the first in-window snapshot's timestamp) — the feed deque
    # is all-time, and a warm-up burst hours ago must not out-vote the
    # function actually storming now.
    window_start = hist[0][0]
    retraces = [
        r for r in ctx.feed_items("retraces")
        if not isinstance(r.get("ts"), (int, float))
        or r["ts"] >= window_start
    ]
    by_fn: dict[str, int] = {}
    last_changed: dict[str, str] = {}
    for r in retraces:
        fn = str(r.get("function") or "?")
        by_fn[fn] = by_fn.get(fn, 0) + 1
        if r.get("changed"):
            last_changed[fn] = str(r["changed"])
    if by_fn:
        worst = max(by_fn, key=by_fn.get)
        culprit = (
            f"; worst offender {worst} ({by_fn[worst]} recent retraces"
            + (f", last change {last_changed[worst]}" if worst in
               last_changed else "")
            + ")"
        )
        labels = {"function": worst}
    else:
        culprit = ""
        labels = {}
    return [{
        "message": (
            f"{delta:g} retrace(s) across the last {len(hist) - 1} "
            f"evaluation(s) (threshold {need}): same function, new "
            f"abstract signature — every one pays a full XLA "
            f"compile{culprit}"
        ),
        "labels": labels,
    }]


def _check_device_mem_growth(ctx: RuleContext) -> list[dict[str, Any]]:
    need = int(ctx.config["device_mem_growth_evals"])
    min_pct = float(ctx.config["device_mem_growth_pct"])
    hist = ctx.history("v6t_device_mem_bytes_in_use")[-(need + 1):]
    if len(hist) < need + 1:
        return []
    values = [v for _, v in hist]
    if values[0] <= 0:
        return []
    if not all(b > a for a, b in zip(values, values[1:])):
        return []
    growth_pct = 100.0 * (values[-1] - values[0]) / values[0]
    if growth_pct < min_pct:
        return []
    return [{
        "message": (
            f"device memory in use grew {growth_pct:.1f}% over "
            f"{need} consecutive evaluations "
            f"({values[0]:.3g} -> {values[-1]:.3g} bytes): buffers are "
            "accumulating instead of being freed (leaked executable "
            "cache entry, un-donated carry, or host references pinning "
            "device arrays)"
        ),
        "labels": {},
    }]


# --------------------------------------------------------------- SLO engine
# Declarative service-level objectives over the STORE-BACKED fleet
# history (server/fleet.py): the server's watchdog feed publishes each
# objective's sample stream ("slo_dispatch", "slo_rounds") and the
# per-source freshness census ("fleet_sources") read straight off the
# shared fleet_metric table, so burn rates aggregate every daemon and
# every replica — not one process's memory — and survive restarts.
# Multi-window burn-rate alerting (SRE-workbook shape): an SLO alerts
# only when the error budget is burning past threshold in BOTH the fast
# window (catches an acute burn within one evaluation) and the slow
# window (keeps sporadic noise quiet: a blip inflates the fast burn but
# never the slow one). A process with no fleet feed (a daemon-side
# watchdog) proposes nothing — the SLO rules are server-evaluated by
# construction.


@dataclasses.dataclass(frozen=True)
class SloRule:
    """One declarative objective: a human-readable goal, the fleet-feed
    sample stream it reads, and the evaluation mode. Targets, windows
    and burn thresholds live in ``Watchdog.config`` (``slo_*`` keys) so
    operators — and tests — tune them live via ``configure()``.

    Modes:

    - ``threshold`` — event samples vs a latency/size target; the bad
      fraction over each window, divided by ``slo_error_budget``, is
      the burn rate.
    - ``throughput`` — cumulative counter samples; the fast-window rate
      must hold ``slo_throughput_floor_pct`` of the trailing
      slow-window baseline rate.
    - ``liveness`` — the per-source freshness census; the stale
      fraction of daemon sources, divided by the liveness budget
      (1 - ``slo_liveness_ratio``), is the burn rate.
    """

    name: str
    objective: str
    feed_key: str
    mode: str
    severity: str = "warning"
    metrics: tuple[str, ...] = ()
    runbook: str = ""

    def to_alert_rule(self) -> AlertRule:
        check = {
            "threshold": _slo_threshold_check,
            "throughput": _slo_throughput_check,
            "liveness": _slo_liveness_check,
        }[self.mode](self)
        return AlertRule(
            name=self.name,
            severity=self.severity,
            summary=(
                f"SLO burn: {self.objective} — the error budget is "
                "burning past threshold in both the fast and the slow "
                "window (store-backed fleet history, not one process's "
                "view)."
            ),
            runbook=self.runbook or (
                "GET /api/fleet for per-source freshness and the counter "
                "deltas; doctor --live names the burning SLO and the "
                "lagging source — docs/observability.md 'SLO burn-rate "
                "alerting'."
            ),
            metrics=self.metrics,
            check=check,
        )


def _slo_samples(
    ctx: RuleContext, key: str
) -> list[tuple[float, float, str]]:
    """(ts, value, source) samples from the fleet feed, deduplicated —
    two in-process replicas both feed the same shared store, and a
    double-counted sample would double the burn rate."""
    seen: set[tuple[Any, float, float]] = set()
    out: list[tuple[float, float, str]] = []
    for s in ctx.feed_items(key):
        ts, v = s.get("ts"), s.get("value")
        if not isinstance(ts, (int, float)) or not isinstance(v, (int, float)):
            continue
        k = (s.get("source"), round(float(ts), 6), float(v))
        if k in seen:
            continue
        seen.add(k)
        out.append((float(ts), float(v), str(s.get("source") or "?")))
    out.sort(key=lambda t: t[0])
    return out


def _slo_windows(ctx: RuleContext) -> tuple[float, float]:
    return (
        float(ctx.config["slo_fast_window_s"]),
        float(ctx.config["slo_slow_window_s"]),
    )


def _slo_threshold_check(slo: SloRule):
    def check(ctx: RuleContext) -> list[dict[str, Any]]:
        REGISTRY.counter("v6t_slo_evaluations_total").inc()
        samples = _slo_samples(ctx, slo.feed_key)
        if not samples:
            return []
        target = float(ctx.config["slo_dispatch_target_s"])
        budget = max(1e-9, float(ctx.config["slo_error_budget"]))
        thr = float(ctx.config["slo_burn_threshold"])
        min_n = int(ctx.config["slo_min_samples"])
        fast, slow = _slo_windows(ctx)

        def burn(window: float) -> tuple[float | None, int]:
            w = [v for ts, v, _ in samples if ctx.now - ts <= window]
            if len(w) < min_n:
                return None, len(w)
            return (sum(1 for v in w if v > target) / len(w)) / budget, len(w)

        burn_fast, n_fast = burn(fast)
        burn_slow, _ = burn(slow)
        if (
            burn_fast is None or burn_slow is None
            or burn_fast < thr or burn_slow < thr
        ):
            return []
        # name the worst offender: most over-target samples in the fast
        # window — "the lagging source" doctor --live calls out
        by_src: dict[str, int] = {}
        for ts, v, src in samples:
            if ctx.now - ts <= fast and v > target:
                by_src[src] = by_src.get(src, 0) + 1
        worst = max(by_src, key=by_src.get) if by_src else None
        return [{
            "message": (
                f"SLO '{slo.objective}' (target {target:g}s): error "
                f"budget burning at {burn_fast:.1f}x over the fast "
                f"{fast:g}s window ({n_fast} samples) and "
                f"{burn_slow:.1f}x over the slow {slow:g}s window "
                f"(threshold {thr:g}x)"
                + (f"; worst source {worst} "
                   f"({by_src[worst]} over-target)" if worst else "")
            ),
            "labels": {"slo": slo.name},
        }]

    return check


def _slo_throughput_check(slo: SloRule):
    def check(ctx: RuleContext) -> list[dict[str, Any]]:
        REGISTRY.counter("v6t_slo_evaluations_total").inc()
        samples = _slo_samples(ctx, slo.feed_key)
        if not samples:
            return []
        floor_pct = float(ctx.config["slo_throughput_floor_pct"])
        min_n = int(ctx.config["slo_min_samples"])
        fast, slow = _slo_windows(ctx)

        def rate(window: float) -> tuple[float, int]:
            # counters are per-source cumulative: delta per source, then
            # sum — one source restarting must not read as negative fleet
            # throughput
            first: dict[str, float] = {}
            last: dict[str, float] = {}
            n = 0
            for ts, v, src in samples:
                if ctx.now - ts > window:
                    continue
                n += 1
                first.setdefault(src, v)
                last[src] = v
            total = sum(
                max(0.0, last[s] - first[s]) for s in last
            )
            return total / max(window, 1e-9), n

        slow_rate, n_slow = rate(slow)
        fast_rate, n_fast = rate(fast)
        if n_slow < min_n or n_fast < 2 or slow_rate <= 0:
            return []  # no established baseline -> nothing to burn
        floor_rate = (floor_pct / 100.0) * slow_rate
        if fast_rate >= floor_rate:
            return []
        return [{
            "message": (
                f"SLO '{slo.objective}': round throughput "
                f"{fast_rate:.4g}/s over the fast {fast:g}s window is "
                f"below {floor_pct:g}% of the trailing {slow:g}s-window "
                f"baseline ({slow_rate:.4g}/s)"
            ),
            "labels": {"slo": slo.name},
        }]

    return check


def _slo_liveness_check(slo: SloRule):
    def check(ctx: RuleContext) -> list[dict[str, Any]]:
        REGISTRY.counter("v6t_slo_evaluations_total").inc()
        daemons: dict[str, dict[str, Any]] = {}
        for s in ctx.feed_items(slo.feed_key):
            name = s.get("source")
            if name and str(s.get("service") or "").startswith("daemon"):
                daemons[str(name)] = s
        if not daemons:
            return []
        budget = max(1e-9, 1.0 - float(ctx.config["slo_liveness_ratio"]))
        thr = float(ctx.config["slo_burn_threshold"])
        grace = float(ctx.config["slo_liveness_slow_grace_s"])
        fast, slow = _slo_windows(ctx)
        ages = {
            src: float(s.get("age_s") or 0.0) for src, s in daemons.items()
        }
        # fast window: the freshness census's own stale verdict; slow
        # window: stale PAST the grace — a daemon mid-restart inflates
        # the fast burn only, and the AND keeps the alert quiet
        stale_fast = [s for s, d in daemons.items() if d.get("stale")]
        stale_slow = [s for s in stale_fast if ages[s] > grace]
        burn_fast = (len(stale_fast) / len(daemons)) / budget
        burn_slow = (len(stale_slow) / len(daemons)) / budget
        if burn_fast < thr or burn_slow < thr:
            return []
        worst = max(ages, key=ages.get)
        return [{
            "message": (
                f"SLO '{slo.objective}': {len(stale_fast)} of "
                f"{len(daemons)} daemon sources are stale (burn "
                f"{burn_fast:.1f}x fast {fast:g}s window / "
                f"{burn_slow:.1f}x slow {slow:g}s window, threshold "
                f"{thr:g}x); most lagging: {worst} "
                f"({ages[worst]:.1f}s since last push)"
            ),
            "labels": {"slo": slo.name},
        }]

    return check


def default_slos() -> list[SloRule]:
    return [
        SloRule(
            name="slo_dispatch_latency",
            objective=(
                "99% of run dispatches start within the target latency"
            ),
            feed_key="slo_dispatch",
            mode="threshold",
            severity="critical",
            metrics=("v6t_run_dispatch_seconds",),
            runbook=(
                "GET /api/fleet: check per-source freshness (a lagging "
                "daemon claims late) and v6t_rest_* deltas (a slow "
                "transport dispatches late); doctor --live names the "
                "worst source. Tune slo_dispatch_target_s / "
                "slo_error_budget via Watchdog.configure."
            ),
        ),
        SloRule(
            name="slo_round_throughput",
            objective=(
                "round throughput holds the floor fraction of its "
                "trailing baseline"
            ),
            feed_key="slo_rounds",
            mode="throughput",
            severity="warning",
            metrics=("v6t_round_updates_total",),
            runbook=(
                "compare straggler_station / queue_buildup alerts and "
                "/api/fleet top_deltas: a collapsed round rate with busy "
                "REST counters is a wedged aggregation, with quiet "
                "counters a stalled submitter. Floor: "
                "slo_throughput_floor_pct of the slow-window rate."
            ),
        ),
        SloRule(
            name="slo_daemon_liveness",
            objective=(
                "the fleet's daemon sources keep pushing fresh telemetry"
            ),
            feed_key="fleet_sources",
            mode="liveness",
            severity="warning",
            metrics=(),
            runbook=(
                "GET /api/fleet liveness block for who went quiet; a "
                "single daemon also raises daemon_lapsed (per-node, "
                "critical) — this SLO is the aggregate budget. Restart "
                "the lagging daemons; pushes resume on their next sync "
                "tick."
            ),
        ),
    ]


def default_rules() -> list[AlertRule]:
    return [
        AlertRule(
            name="stuck_run",
            severity="critical",
            summary=(
                "A run has been ACTIVE past the deadline with no status "
                "events — its daemon crashed mid-execution, the terminal "
                "status patch was lost, or the algorithm is wedged."
            ),
            runbook=(
                "doctor the flight dump for the run's trace_id, check the "
                "owning node's daemon log, then kill_task to release the "
                "round (the anti-entropy sweep re-claims orphans)."
            ),
            metrics=(),
            check=_check_stuck_run,
        ),
        AlertRule(
            name="daemon_lapsed",
            severity="critical",
            summary=(
                "A node is marked online but missed its ping window — the "
                "daemon process died or lost its network path without an "
                "offline handshake."
            ),
            runbook=(
                "restart the node daemon; its startup resync re-claims "
                "pending runs. Runs it held past the deadline raise "
                "stuck_run separately. Automated: the autopilot requeues "
                "the node's ACTIVE runs (CAS-guarded, one-shot) — see "
                "docs/OPERATOR_GUIDE.md 'autopilot'."
            ),
            metrics=(),
            check=_check_daemon_lapsed,
        ),
        AlertRule(
            name="replica_lapsed",
            severity="warning",
            summary=(
                "A server replica sharing this store stopped heartbeating "
                "— its process died or lost the store without a clean "
                "shutdown. The surviving replicas keep serving; runs the "
                "dead replica had in flight re-queue via the orphan sweep."
            ),
            runbook=(
                "check /api/health `replicas` on a survivor; restart or "
                "remove the dead replica. Attribute its in-flight work "
                "with trace_view (spans carry replica_id). Warning, not "
                "critical: N-1 replicas is degraded capacity, not an "
                "outage (see docs/control_plane.md). Automated: the "
                "autopilot requeues runs the dead replica's lost reports "
                "stranded ACTIVE (CAS-guarded, one-shot) — see "
                "docs/OPERATOR_GUIDE.md 'autopilot'."
            ),
            metrics=(),
            check=_check_replica_lapsed,
        ),
        AlertRule(
            name="straggler_station",
            severity="warning",
            summary=(
                "The same station dominated round wall-clock in several "
                "recent rounds — persistent slow hardware/data-size skew, "
                "not a one-off."
            ),
            runbook=(
                "compare the station's exec spans (trace_view straggler "
                "call-out) against its wire bytes; consider async "
                "aggregation (run_buffered) or re-balancing its shard. "
                "Automated: the autopilot shrinks the station's selection "
                "weight while this alert is active and restores it on "
                "clear — see docs/OPERATOR_GUIDE.md 'autopilot'."
            ),
            metrics=(),
            check=_check_straggler_station,
        ),
        AlertRule(
            name="anomalous_station",
            severity="warning",
            summary=(
                "A station's updates are statistical outliers in several "
                "recent rounds — cosine to the pooled update below "
                "threshold (label flip / poisoning / diverging local "
                "training) or update norm a multiple of the station "
                "median (scaling / exploding gradients)."
            ),
            runbook=(
                "GET /api/rounds/<task_id> for the per-station "
                "trajectory (doctor's learning digest renders the same "
                "table from a dump); inspect the station's data/labels, "
                "then drop it from the next task's organizations or mask "
                "it — the pooled update already nan-isolates zero-weight "
                "stations. Automated: the autopilot masks the station out "
                "of the aggregate while this alert is active and unmasks "
                "it on clear — see docs/OPERATOR_GUIDE.md 'autopilot'."
            ),
            metrics=(),
            check=_check_anomalous_station,
        ),
        AlertRule(
            name="model_divergence",
            severity="critical",
            summary=(
                "The global update norm is growing monotonically across "
                "recorded rounds — the model is diverging (learning rate "
                "too high, poisoned aggregate, or numerical blow-up), "
                "and every further round makes it worse."
            ),
            runbook=(
                "stop the run (kill_task), check /api/rounds for which "
                "round the norm took off and whether anomalous_station "
                "names a culprit; resume from the last good checkpoint "
                "with a lower local_lr/server lr."
            ),
            metrics=(),
            check=_check_model_divergence,
        ),
        AlertRule(
            name="non_convergence",
            severity="warning",
            summary=(
                "The global update norm stopped decaying past the round "
                "budget — training is burning rounds without progress "
                "(lr too low/high, compression too aggressive, or the "
                "task is mis-specified)."
            ),
            runbook=(
                "read the trajectory at /api/rounds/<task_id> (trend "
                "first: is it flat or oscillating?), check ef_mass_growth "
                "and anomalous_station beside it, then adjust lr / "
                "topk_ratio or re-examine the data split — "
                "docs/OPERATOR_GUIDE.md 'the model isn't converging'."
            ),
            metrics=(),
            check=_check_non_convergence,
        ),
        AlertRule(
            name="queue_buildup",
            severity="warning",
            summary=(
                "Executor backlog is sustained at a multiple of worker "
                "capacity — submission outpaces execution and task latency "
                "is compounding."
            ),
            runbook=(
                "raise executor_workers, throttle task creation, or check "
                "for a station whose FIFO is blocked by a long run "
                "(queue_wait_s in run_lifecycle). Automated: the autopilot "
                "applies admission control (new host runs queue instead of "
                "dispatching) while this alert is active and drains on "
                "clear — see docs/OPERATOR_GUIDE.md 'autopilot'."
            ),
            metrics=(
                "v6t_executor_inflight_items",
                "v6t_executor_capacity",
            ),
            check=_check_queue_buildup,
        ),
        AlertRule(
            name="event_cursor_lag",
            severity="warning",
            summary=(
                "Consumers are fetching event history the bounded hub "
                "buffer already evicted (truncated responses) — lagging "
                "daemons are missing events and paying full resyncs."
            ),
            runbook=(
                "check daemon backoff counters (a flapping network keeps "
                "pollers behind) and raise the hub buffer_size if "
                "truncations persist."
            ),
            metrics=(
                "v6t_event_truncated_total",
                "v6t_event_hub_cursor",
                "v6t_event_hub_evicted_through",
            ),
            check=_check_event_cursor_lag,
        ),
        AlertRule(
            name="ef_mass_growth",
            severity="warning",
            summary=(
                "The compression error-feedback accumulator is growing "
                "monotonically — compression is too aggressive for this "
                "workload and residual error is piling up instead of "
                "shipping."
            ),
            runbook=(
                "raise topk_ratio (ship more coordinates) or disable int8 "
                "for this workload; compression_stats() shows the per-round "
                "trajectory."
            ),
            metrics=("v6t_compress_ef_norm",),
            check=_check_ef_mass_growth,
        ),
        AlertRule(
            name="recompile_storm",
            severity="warning",
            summary=(
                "Observed jit functions are retracing every evaluation — "
                "an unstable abstract signature (wobbling batch shape, "
                "fresh weak-typed scalar, new dtype) is paying a full "
                "XLA compile per dispatch instead of reusing the cache."
            ),
            runbook=(
                "the alert and the doctor perf digest name the function "
                "and the leaf that changed; pad/bucket that input to a "
                "static shape (or mark the wobbling scalar static). "
                "trace_view's device call-out shows the compile cost."
            ),
            metrics=("v6t_jit_retraces_total",),
            check=_check_recompile_storm,
        ),
        AlertRule(
            name="device_mem_growth",
            severity="warning",
            summary=(
                "Device memory in use is growing monotonically across "
                "evaluations — buffers are accumulating instead of being "
                "freed (leaked executable-cache entry, un-donated scan "
                "carry, host references pinning device arrays)."
            ),
            runbook=(
                "open a profile window (POST /api/debug/profile) around "
                "a round and compare v6t_jit_signatures / "
                "v6t_engine_cache_entries growth; clear or bound the "
                "offending cache, or donate the round's carry buffers."
            ),
            metrics=("v6t_device_mem_bytes_in_use",),
            check=_check_device_mem_growth,
        ),
    ] + [slo.to_alert_rule() for slo in default_slos()]


DEFAULT_RULES = default_rules()

# name -> catalog row: what tools/doctor.py explains alerts against and
# docs/observability.md documents
RULE_CATALOG: dict[str, dict[str, str]] = {
    r.name: {
        "severity": r.severity,
        "summary": r.summary,
        "runbook": r.runbook,
    }
    for r in DEFAULT_RULES
}


@dataclasses.dataclass
class Alert:
    rule: str
    severity: str
    message: str
    labels: dict[str, Any]
    traceparent: str | None
    raised_at: float
    last_seen_at: float
    count: int = 1
    resolved_at: float | None = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "labels": self.labels,
            "traceparent": self.traceparent,
            "raised_at": self.raised_at,
            "last_seen_at": self.last_seen_at,
            "count": self.count,
            "resolved_at": self.resolved_at,
        }


class Watchdog:
    """Rule engine + evaluation loop + health verdict (module docstring)."""

    def __init__(
        self,
        rules: list[AlertRule] | None = None,
        interval: float | None = None,
        history: int = 128,
    ):
        self._lock = threading.Lock()
        self.rules: list[AlertRule] = []
        for rule in rules if rules is not None else default_rules():
            self.add_rule(rule)
        self.interval = (
            interval
            if interval is not None
            else max(0.1, env_float("V6T_WATCHDOG_INTERVAL", 5.0))
        )
        self.config: dict[str, Any] = {
            "run_deadline_s": env_float("V6T_RUN_DEADLINE_S", 300.0),
            "ping_window_s": env_float("V6T_PING_WINDOW_S", 60.0),
            "queue_factor": 4.0,
            "queue_sustain_evals": 2,
            "straggler_rounds": 3,
            "straggler_ratio": 3.0,
            "straggler_window": 8,
            # learning plane (runtime.learning feed)
            "anomaly_cos_threshold": 0.2,
            "anomaly_norm_factor": 4.0,
            "anomaly_rounds": 3,
            "anomaly_window": 8,
            "divergence_rounds": 4,
            "divergence_min_growth_pct": 10.0,
            "non_convergence_rounds": 30,
            "non_convergence_window": 16,
            "non_convergence_decay_pct": 5.0,
            "non_convergence_converged_ratio": 0.05,
            "ef_growth_evals": 4,
            "recompile_storm_retraces": 3,
            "recompile_storm_window": 4,
            "device_mem_growth_evals": 4,
            "device_mem_growth_pct": 10.0,
            # SLO engine (store-backed fleet history; see default_slos)
            "slo_dispatch_target_s": 2.0,
            "slo_error_budget": 0.01,
            "slo_burn_threshold": 6.0,
            "slo_fast_window_s": 300.0,
            "slo_slow_window_s": 3600.0,
            "slo_min_samples": 4,
            "slo_throughput_floor_pct": 50.0,
            "slo_liveness_ratio": 0.9,
            "slo_liveness_slow_grace_s": 120.0,
        }
        self._history_len = max(8, history)
        self._feeds: dict[str, Callable[[], Any]] = {}  # guarded-by: _lock
        self._components: dict[str, Callable[[], Any]] = {}  # guarded-by: _lock
        self._metric_history: dict[str, deque] = {}  # guarded-by: _lock
        self._active: dict[Any, Alert] = {}  # guarded-by: _lock
        self._recent: deque[Alert] = deque(maxlen=256)  # guarded-by: _lock
        self._feed_error_keys: set[str] = set()  # guarded-by: _lock
        self._listeners: dict[str, Callable[[str, Alert], Any]] = {}  # guarded-by: _lock
        self.last_eval_at: float | None = None
        self._users = 0  # guarded-by: _lock (refcounted start/stop)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # own freshness as a first-class component: a wedged evaluation
        # loop must itself flip health to degraded
        self.register_component("watchdog", self.self_check)

    # ------------------------------------------------------------- registry
    def add_rule(self, rule: AlertRule) -> None:
        rule.validate()
        with self._lock:
            if any(r.name == rule.name for r in self.rules):
                raise ValueError(f"duplicate alert rule {rule.name!r}")
            self.rules.append(rule)

    def configure(self, interval: float | None = None, **config: Any) -> "Watchdog":
        if interval is not None:
            self.interval = max(0.05, float(interval))
        for key, value in config.items():
            if key not in self.config:
                raise ValueError(f"unknown watchdog config key {key!r}")
            self.config[key] = value
        return self

    def register_feed(self, key: str, fn: Callable[[], Any]) -> None:
        """Register (or replace — same key) a state source: ``fn()``
        returns a dict of list-valued entries ("runs", "nodes", "rounds")
        or None. Same keyed-replacement story as telemetry collectors."""
        with self._lock:
            self._feeds[key] = fn

    def unregister_feed(
        self, key: str, fn: Callable[[], Any] | None = None
    ) -> None:
        """Remove a feed; with ``fn``, only if it is still the registered
        one (a replaced source must not evict its replacement — same
        contract as telemetry's unregister_collector)."""
        with self._lock:
            if fn is None or self._feeds.get(key) == fn:
                self._feeds.pop(key, None)
                self._feed_error_keys.discard(key)

    def has_feed(self, key: str) -> bool:
        with self._lock:
            return key in self._feeds

    def add_listener(self, key: str, fn: Callable[[str, Alert], Any]) -> None:
        """Register (or replace — same key) a transition listener:
        ``fn(event, alert)`` with event ``"raised"`` or ``"cleared"``,
        called synchronously after the transition's own emits (span, log,
        flight note) so anything the listener does — the autopilot's
        remediation spans in particular — nests correctly after the
        alert's. Listeners are fail-soft: one raising never blocks the
        others or the evaluation."""
        with self._lock:
            self._listeners[key] = fn

    def remove_listener(
        self, key: str, fn: Callable[[str, Alert], Any] | None = None
    ) -> None:
        """Remove a listener; with ``fn``, only if it is still the
        registered one (same contract as unregister_feed)."""
        with self._lock:
            if fn is None or self._listeners.get(key) == fn:
                self._listeners.pop(key, None)

    def register_component(self, name: str, fn: Callable[[], Any]) -> None:
        """Register a health self-check: ``fn()`` returns ``(ok, detail)``
        or a bare bool. A raising check counts as failed (the component
        cannot even answer)."""
        with self._lock:
            self._components[name] = fn

    def unregister_component(self, name: str) -> None:
        with self._lock:
            self._components.pop(name, None)

    # ------------------------------------------------------------ evaluation
    def _rule_metric_names(self) -> set[str]:
        return {name for rule in self.rules for name in rule.metrics}

    def evaluate(self) -> list[dict[str, Any]]:
        """One full pass: snapshot telemetry, pull feeds, run every rule,
        transition alert state, emit. Returns the active alerts."""
        now = time.time()
        snapshot = REGISTRY.snapshot()
        with self._lock:
            feeds_fns = dict(self._feeds)
            tracked = self._rule_metric_names()
            for name in tracked:
                value = snapshot.get(name)
                if value is None and _metric_kind(name) == "counter":
                    # counters materialize in the snapshot on first inc();
                    # an absent counter IS 0, and recording that baseline
                    # is what lets a trend rule see the first increment of
                    # a process lifetime as growth — without ever
                    # mistaking a pre-existing count at watchdog start for
                    # a fresh jump
                    value = 0.0
                if isinstance(value, (int, float)):
                    hist = self._metric_history.get(name)
                    if hist is None:
                        hist = self._metric_history[name] = deque(
                            maxlen=self._history_len
                        )
                    hist.append((now, float(value)))
            history = {
                k: deque(v) for k, v in self._metric_history.items()
            }
        feeds: dict[str, Any] = {}
        any_feed_failed = False
        for key, fn in feeds_fns.items():
            try:
                state = fn()
            except Exception as e:
                REGISTRY.counter("v6t_watchdog_feed_errors_total").inc()
                any_feed_failed = True
                with self._lock:
                    fresh = key not in self._feed_error_keys
                    self._feed_error_keys.add(key)
                if fresh:  # once per failure streak, not per eval
                    log.warning("watchdog feed %s failed: %s", key, e)
                continue
            with self._lock:
                self._feed_error_keys.discard(key)
            if state is not None:
                feeds[key] = state
        # eval_interval_s rides along (NOT a configure() key): trend rules
        # need the nominal sample spacing to turn "N consecutive samples"
        # into a wall-clock claim
        ctx = RuleContext(
            snapshot, history, feeds,
            {**self.config, "eval_interval_s": self.interval}, now,
        )

        proposed: dict[Any, tuple[AlertRule, dict[str, Any]]] = {}
        crashed_rules: set[str] = set()
        for rule in list(self.rules):
            try:
                findings = rule.check(ctx) or []
            except Exception as e:
                REGISTRY.counter("v6t_watchdog_feed_errors_total").inc()
                crashed_rules.add(rule.name)
                log.warning("alert rule %s crashed: %s", rule.name, e)
                continue
            for finding in findings:
                labels = finding.get("labels") or {}
                key = (
                    rule.name,
                    tuple(sorted((k, str(v)) for k, v in labels.items())),
                )
                proposed[key] = (rule, finding)

        raised: list[Alert] = []
        cleared: list[Alert] = []
        with self._lock:
            for key, (rule, finding) in proposed.items():
                alert = self._active.get(key)
                if alert is None:
                    alert = Alert(
                        rule=rule.name,
                        severity=rule.severity,
                        message=finding["message"],
                        labels=finding.get("labels") or {},
                        traceparent=finding.get("traceparent"),
                        raised_at=now,
                        last_seen_at=now,
                    )
                    self._active[key] = alert
                    raised.append(alert)
                else:
                    alert.message = finding["message"]
                    alert.last_seen_at = now
                    alert.count += 1
            for key in [k for k in self._active if k not in proposed]:
                # Fail-soft HOLDS, never clears: when a feed raised or the
                # alert's own rule crashed, the finding's absence is loss
                # of evidence, not recovery — resolving would flap
                # /api/health and reset raised_at/count on the next clean
                # pass. Hold the alert until a clean evaluation stops
                # proposing it.
                if any_feed_failed or key[0] in crashed_rules:
                    continue
                alert = self._active.pop(key)
                alert.resolved_at = now
                self._recent.append(alert)
                cleared.append(alert)
            n_active = len(self._active)
            n_slo = sum(
                1 for a in self._active.values()
                if a.rule.startswith("slo_")
            )
            active = [a.to_dict() for a in self._active.values()]
            self.last_eval_at = now

        for alert in raised:
            self._emit_raise(alert)
            self._notify_listeners("raised", alert)
        for alert in cleared:
            self._emit_clear(alert)
            self._notify_listeners("cleared", alert)

        REGISTRY.counter("v6t_watchdog_evaluations_total").inc()
        if raised:
            REGISTRY.counter("v6t_alerts_raised_total").inc(len(raised))
        if cleared:
            REGISTRY.counter("v6t_alerts_cleared_total").inc(len(cleared))
        REGISTRY.gauge("v6t_alerts_active").set(n_active)
        REGISTRY.gauge("v6t_slo_burning").set(n_slo)
        REGISTRY.gauge("v6t_watchdog_last_eval_unixtime").set(now)
        # fold the verdict into telemetry + the flight recorder's metric
        # history every pass — a dump carries the health trajectory
        verdict = self.health()
        REGISTRY.gauge("v6t_health_degraded").set(
            1.0 if verdict["status"] == "degraded" else 0.0
        )
        try:
            from vantage6_tpu.common.flight import FLIGHT

            # reuse THIS evaluation's snapshot — taking another would run
            # every collector (hub/executor/cache stats, each under its
            # component's lock) twice per tick
            FLIGHT.snapshot_metrics(snapshot)
        except Exception:  # pragma: no cover
            pass
        return active

    def _emit_raise(self, alert: Alert) -> None:
        attrs = {
            "severity": alert.severity,
            "message": alert.message,
            "transition": "raised",
            **{f"label_{k}": v for k, v in alert.labels.items()},
        }
        # the span is ACTIVE around the warning log so the log record is
        # stamped with the trace ids (TraceContextFilter): when the alert
        # carries the affected task's traceparent, both the span AND the
        # log line land inside the stuck round's own trace — the
        # correlation tools/doctor.py merges on
        with TRACER.span(
            f"alert.{alert.rule}", kind="alert", service="watchdog",
            parent=alert.traceparent,  # None -> fresh root trace
            attrs=attrs,
        ) as sp:
            sp.add_event("alert_raised", rule=alert.rule,
                         severity=alert.severity)
            log.warning(
                "ALERT raised [%s/%s]: %s", alert.severity, alert.rule,
                alert.message,
            )
        try:
            from vantage6_tpu.common.flight import FLIGHT

            FLIGHT.note(
                "alert_raised", rule=alert.rule, severity=alert.severity,
                message=alert.message, labels=alert.labels,
                traceparent=alert.traceparent,
            )
        except Exception:  # pragma: no cover
            pass

    def _emit_clear(self, alert: Alert) -> None:
        # symmetric with _emit_raise: the clear gets its own span on the
        # SAME trace (alert.traceparent), so a remediation revert — which
        # the autopilot hangs off this transition — is as visible in
        # doctor timelines as the raise that triggered the action
        duration_s = (alert.resolved_at or 0) - alert.raised_at
        attrs = {
            "severity": alert.severity,
            "message": alert.message,
            "transition": "cleared",
            "duration_s": duration_s,
            **{f"label_{k}": v for k, v in alert.labels.items()},
        }
        with TRACER.span(
            f"alert.{alert.rule}", kind="alert", service="watchdog",
            parent=alert.traceparent,  # None -> fresh root trace
            attrs=attrs,
        ) as sp:
            sp.add_event("alert_cleared", rule=alert.rule,
                         severity=alert.severity)
            log.info(
                "alert cleared [%s/%s] after %.1fs: %s", alert.severity,
                alert.rule, duration_s, alert.message,
            )
        try:
            from vantage6_tpu.common.flight import FLIGHT

            FLIGHT.note(
                "alert_cleared", rule=alert.rule, severity=alert.severity,
                message=alert.message, labels=alert.labels,
                traceparent=alert.traceparent, duration_s=duration_s,
            )
        except Exception:  # pragma: no cover
            pass

    def _notify_listeners(self, event: str, alert: Alert) -> None:
        with self._lock:
            listeners = list(self._listeners.items())
        for key, fn in listeners:
            try:
                fn(event, alert)
            except Exception as e:
                REGISTRY.counter("v6t_watchdog_feed_errors_total").inc()
                log.warning(
                    "watchdog listener %s failed on %s %s: %s",
                    key, alert.rule, event, e,
                )

    # -------------------------------------------------------------- queries
    def active_alerts(self) -> list[dict[str, Any]]:
        with self._lock:
            return [a.to_dict() for a in self._active.values()]

    def recent_alerts(self, limit: int = 50) -> list[dict[str, Any]]:
        with self._lock:
            recent = list(self._recent)[-limit:]
        return [a.to_dict() for a in reversed(recent)]

    def health(self) -> dict[str, Any]:
        """ok/degraded verdict: every registered component's self-check
        plus the active alert census. Degraded = any component failing OR
        any critical alert active."""
        with self._lock:
            components = dict(self._components)
            active = list(self._active.values())
        comp_out: dict[str, dict[str, Any]] = {}
        degraded = False
        for name, fn in components.items():
            try:
                result = fn()
            except Exception as e:
                result = (False, f"self-check raised: {e}")
            if isinstance(result, tuple):
                ok, detail = bool(result[0]), str(result[1])
            else:
                ok, detail = bool(result), ""
            comp_out[name] = {"ok": ok, "detail": detail}
            degraded |= not ok
        n_critical = sum(1 for a in active if a.severity == "critical")
        degraded |= n_critical > 0
        return {
            "status": "degraded" if degraded else "ok",
            "components": comp_out,
            "alerts": {
                "active": len(active),
                "critical": n_critical,
            },
        }

    def self_check(self) -> tuple[bool, str]:
        """The watchdog's own freshness, registered as component
        "watchdog": started-but-stale (or started-but-dead-thread) fails."""
        with self._lock:
            users = self._users
            thread = self._thread
            last = self.last_eval_at
        if users <= 0:
            return True, "not running (on-demand evaluation)"
        if thread is None or not thread.is_alive():
            return False, "evaluation thread is not alive"
        if last is None:
            return True, "starting"
        lag = time.time() - last
        if lag > max(3.0 * self.interval, 1.0):
            return False, f"last evaluation {lag:.1f}s ago (interval {self.interval:g}s)"
        return True, f"last evaluation {lag:.1f}s ago"

    # ------------------------------------------------------------- lifecycle
    def start(self, interval: float | None = None) -> "Watchdog":
        """Refcounted: each server/daemon embedding calls start() once and
        stop() on close; the loop runs while any user remains."""
        if interval is not None:
            self.configure(interval=interval)
        with self._lock:
            self._users += 1
            if self._thread is not None and self._thread.is_alive():
                return self
            # a FRESH loop: the previous loop's timestamp must not count
            # against the new one's freshness check (a server starting
            # minutes after the last one stopped would otherwise report
            # a degraded watchdog until the first tick)
            self.last_eval_at = None
            self._stop = threading.Event()
            # the loop gets ITS OWN stop event as an argument: reading
            # self._stop lazily inside _loop races a stop()+start() pair
            # swapping the attribute before the old thread's first read —
            # the old loop would bind the NEW (unset) event and run
            # forever beside its replacement
            self._thread = threading.Thread(
                target=self._loop, args=(self._stop,),
                daemon=True, name="v6t-watchdog",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._users = max(0, self._users - 1)
            if self._users > 0:
                return
            thread, self._thread = self._thread, None
            self._stop.set()
        if thread is not None:
            thread.join(timeout=5)

    def _loop(self, stop: threading.Event) -> None:
        # evaluate IMMEDIATELY, then on the interval: a freshly started
        # server gets a real health verdict (and stale alerts from feeds
        # that died with a previous embedder get cleared) on its first
        # request, not after one full interval
        while True:
            try:
                self.evaluate()
            except Exception:
                # the loop must survive anything an eval throws; the next
                # tick tries again and self_check reports staleness if it
                # keeps failing
                log.exception("watchdog evaluation crashed")
            if stop.wait(self.interval):
                return


WATCHDOG = Watchdog()
