"""Device performance observatory: compile/retrace telemetry, XLA
memory & cost introspection, and on-demand profiling windows.

The task plane became observable in two layers (PR-5 tracing + telemetry,
PR-8 watchdog + flight recorder); the DEVICE plane stayed a black box — a
silent retrace storm or creeping executable-cache leak showed up only as
"rounds got slower", with nothing naming the cause. This module is the
attribution layer for everything below `jax.jit`:

- **Observed jit** — :func:`observed_jit` wraps a function the way
  ``jax.jit`` does, but owns the signature→executable cache so every
  lowering+compile is an EVENT it can measure: each one is recorded as a
  ``device.compile`` span (parented on the active trace when there is
  one) carrying lowering and compile wall time plus the compiled
  program's ``memory_analysis()`` (temp/argument/output bytes) and
  ``cost_analysis()`` (flops, bytes accessed), and counted in the
  ``v6t_jit_*`` telemetry series.
- **Retrace registry** — a *retrace* is the same function name compiling
  against an abstract signature it has NEVER seen. The observatory names
  the differing leaf (shape/dtype before → after) in the compile span, a
  flight-recorder note (kind ``retrace``), and the watchdog feed the
  ``recompile_storm`` rule reads — the storm is detected *and attributed*
  in one place. Recompiling a signature the bounded executable cache
  evicted is marked ``evicted_recompile`` on the span instead: real cost,
  but cache churn, not a storm.
- **Engine-cache counters** — the ``mesh.fingerprint()``-keyed runner
  caches (glm/quantile/device_engine) report hits/misses/entries through
  :func:`engine_cache_event`, emitted here as the ``v6t_engine_cache_*``
  series, so executable-cache effectiveness is a number, not a hope.
- **Per-device memory** — a telemetry collector publishes bytes-in-use /
  peak across ALL local devices (``v6t_device_mem_*``), the series the
  ``device_mem_growth`` watchdog rule trends.
- **Profile windows** — :func:`profile_window` runs a bounded
  ``jax.profiler`` session on demand (``POST /api/debug/profile``),
  registers the artifact path in the flight recorder, and records a
  ``device.profile`` span linked to the requesting trace.

Dispatch semantics: an observed function behaves exactly like its
``jax.jit`` twin. Called under an outer trace (leaves are tracers) it
inlines like any jitted function; called with a known signature it
dispatches straight to the cached executable; anything the AOT path
cannot express (sharding mismatch, exotic pytree) falls back to the
plain jitted callable — counted, never fatal. Disable the whole layer
with ``V6T_DEVICE_OBS=0`` (calls forward to ``jax.jit`` untouched).
"""
from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Callable

import jax

from vantage6_tpu.common.env import env_int
from vantage6_tpu.common.telemetry import REGISTRY
from vantage6_tpu.runtime.tracing import TRACER

__all__ = [
    "DEVICE_OBS",
    "ObservedFunction",
    "ProfileBusyError",
    "RunnerCache",
    "engine_cache_event",
    "observed_jit",
    "profile_window",
]


def _abstractify(leaf: Any) -> Any:
    """Hashable abstract signature of one leaf — jax's own retrace key
    (shape, dtype, weak_type) when the leaf is array-like, a type tag
    otherwise (an exotic leaf must not crash the observatory)."""
    try:
        from jax.api_util import shaped_abstractify

        return shaped_abstractify(leaf)
    except Exception:
        return ("opaque", type(leaf).__name__)


def _leaf_str(aval: Any) -> str:
    try:
        return aval.str_short()
    except Exception:
        return str(aval)


def _signature_diff(
    old_paths: list[str], old_avals: tuple, new_paths: list[str],
    new_avals: tuple, old_statics: tuple = (), new_statics: tuple = (),
) -> str:
    """Name what changed between two abstract signatures — the one string
    an operator needs to find the shape-perturbing call site."""
    if len(old_avals) != len(new_avals):
        return (
            f"arity changed: {len(old_avals)} -> {len(new_avals)} leaves"
        )
    for path, a, b in zip(new_paths, old_avals, new_avals):
        if a != b:
            return f"{path or 'arg'}: {_leaf_str(a)} -> {_leaf_str(b)}"
    olds = dict(old_statics)
    for k, v in new_statics:
        if k not in olds:
            return f"static {k} added: {v!r}"
        if olds[k] != v:
            return f"static {k}: {olds[k]!r} -> {v!r}"
    return "signature changed (treedef)"


def _cost_summary(compiled: Any) -> dict[str, float]:
    """flops / bytes-accessed from ``cost_analysis()`` — tolerant of the
    per-version shape (list of dicts on 0.4.x, dict on newer, None on
    backends that don't report)."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return {}
    out: dict[str, float] = {}
    for key, name in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
        v = cost.get(key)
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out


def _memory_summary(compiled: Any) -> dict[str, int]:
    """temp/argument/output/code bytes from ``memory_analysis()`` (absent
    on backends that don't report it)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    out: dict[str, int] = {}
    for attr, name in (
        ("temp_size_in_bytes", "temp_bytes"),
        ("argument_size_in_bytes", "argument_bytes"),
        ("output_size_in_bytes", "output_bytes"),
        ("generated_code_size_in_bytes", "generated_code_bytes"),
    ):
        v = getattr(mem, attr, None)
        if isinstance(v, (int, float)):
            out[name] = int(v)
    return out


class ObservedFunction:
    """One ``jax.jit`` entry point under observation (see module doc).

    Owns a bounded signature→compiled-executable map. A signature MISS is
    a compile event (measured, traced, counted); a miss on a warm
    function is additionally a RETRACE (named and reported) unless the
    signature was seen before and merely evicted. Statics
    follow jit's contract: ``static_argnums`` positionally,
    ``static_argnames`` by keyword — both join the signature key and are
    dropped from the compiled call (XLA bakes them in).
    """

    def __init__(
        self,
        name: str,
        fun: Callable[..., Any],
        *,
        static_argnums: tuple[int, ...] = (),
        static_argnames: tuple[str, ...] = (),
        sweep_statics: tuple[str, ...] = (),
        **jit_kwargs: Any,
    ):
        self.name = name
        self._static_argnums = tuple(static_argnums)
        self._static_argnames = tuple(static_argnames)
        # statics a caller legitimately SWEEPS (e.g. the fused round
        # program's n_rounds): a compile whose signature differs from a
        # previously seen one ONLY in these keys is a planned new
        # executable, not a retrace — it must not feed recompile_storm
        self._sweep_statics = frozenset(sweep_statics)
        jit_kw: dict[str, Any] = dict(jit_kwargs)
        if self._static_argnums:
            jit_kw["static_argnums"] = self._static_argnums
        if self._static_argnames:
            jit_kw["static_argnames"] = self._static_argnames
        self._jit = jax.jit(fun, **jit_kw)
        self._lock = threading.Lock()
        # serializes _compile: two threads racing the same NEW signature
        # must not both pay the XLA compile, and the loser must not
        # record a phantom "retrace" against an identical signature
        self._compile_lock = threading.Lock()
        # guarded-by: _lock — insertion-ordered for FIFO eviction
        self._sigs: "OrderedDict[tuple, Any]" = OrderedDict()
        # guarded-by: _lock — every signature EVER compiled (bounded,
        # keys only). Distinguishes a true retrace (genuinely new
        # signature — the storm the alert hunts) from recompiling one the
        # FIFO evicted: a workload legitimately rotating through more
        # live shapes than max_signatures pays the compile but must not
        # feed recompile_storm, or the observatory would alert on churn
        # it created itself.
        self._seen_sigs: "OrderedDict[tuple, None]" = OrderedDict()
        # guarded-by: _lock — signatures with sweep statics STRIPPED:
        # membership here means "this shape was seen at SOME swept static
        # value", the evidence that a new (avals, other-statics) miss is a
        # static sweep rather than a shape-perturbing caller
        self._seen_swept: "OrderedDict[tuple, None]" = OrderedDict()
        self._last_sig: tuple | None = None
        self._last_paths: list[str] = []
        self._last_avals: tuple = ()
        self._last_statics: tuple = ()
        self.compiles = 0
        self.retraces = 0
        self.static_sweeps = 0
        self.dispatches = 0
        self.fallbacks = 0
        self.evictions = 0
        self.last_compile: dict[str, Any] = {}

    # ------------------------------------------------------------ plumbing
    def lower(self, *args: Any, **kwargs: Any):
        """AOT escape hatch — identical to ``jax.jit(fun).lower``."""
        return self._jit.lower(*args, **kwargs)

    def _split(self, args: tuple, kwargs: dict) -> tuple[tuple, dict, tuple]:
        """(dynamic args, dynamic kwargs, hashable statics key)."""
        statics: list[tuple[str, Any]] = []
        dyn_args = []
        for i, a in enumerate(args):
            if i in self._static_argnums:
                statics.append((f"arg{i}", a))
            else:
                dyn_args.append(a)
        dyn_kwargs = {}
        for k, v in kwargs.items():
            if k in self._static_argnames:
                statics.append((k, v))
            else:
                dyn_kwargs[k] = v
        return tuple(dyn_args), dyn_kwargs, tuple(sorted(
            statics, key=lambda kv: kv[0]
        ))

    def _swept_key(self, key: tuple) -> tuple | None:
        """``key`` with the sweep statics stripped, or None when this
        function declares none (or the key carries none of them)."""
        if not self._sweep_statics:
            return None
        reduced = tuple(
            kv for kv in key[2] if kv[0] not in self._sweep_statics
        )
        if reduced == key[2]:  # no swept static present in this call
            return None
        return (key[0], key[1], reduced)

    # ------------------------------------------------------------ dispatch
    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        obs = DEVICE_OBS
        if not obs.enabled:
            return self._jit(*args, **kwargs)
        dyn_args, dyn_kwargs, statics = self._split(args, kwargs)
        leaves, treedef = jax.tree.flatten((dyn_args, dyn_kwargs))
        if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
            # called inside an outer trace: inline like any jitted fn —
            # the OUTER entry point owns this compile's attribution
            return self._jit(*args, **kwargs)
        avals = tuple(_abstractify(leaf) for leaf in leaves)
        try:
            key = (avals, treedef, statics)
            hash(key)
        except TypeError:
            # unhashable static (a list-valued kwarg, ...): observe
            # nothing rather than crash the call
            self.fallbacks += 1
            REGISTRY.counter("v6t_jit_fallbacks_total").inc()
            return self._jit(*args, **kwargs)
        self.dispatches += 1
        REGISTRY.counter("v6t_jit_dispatches_total").inc()
        with self._lock:
            compiled = self._sigs.get(key)
        if compiled is None:
            compiled = self._compile(key, args, kwargs, avals, dyn_args,
                                     dyn_kwargs)
            if compiled is None:  # AOT path unavailable — plain jit
                return self._jit(*args, **kwargs)
        try:
            return compiled(*dyn_args, **dyn_kwargs)
        except (TypeError, ValueError):
            # sharding/pytree mismatch the abstract key couldn't see —
            # raised while PROCESSING arguments, before any buffer is
            # donated, so retrying via jit's own dispatch is safe.
            # Execution failures (XlaRuntimeError: OOM mid-scan, ...)
            # propagate: a retry would re-run the whole computation, and
            # with donated inputs would mask the real error behind
            # "Array has been deleted".
            self.fallbacks += 1
            REGISTRY.counter("v6t_jit_fallbacks_total").inc()
            return self._jit(*args, **kwargs)

    def _compile(
        self, key: tuple, args: tuple, kwargs: dict, avals: tuple,
        dyn_args: tuple, dyn_kwargs: dict,
    ) -> Any:
        """Measured lower+compile of one new signature: the
        ``device.compile`` span, the retrace naming, the telemetry.
        One compile at a time per function (compiles are rare; a loser
        of the dispatch race reuses the winner's executable)."""
        with self._compile_lock:
            with self._lock:
                cached = self._sigs.get(key)
            if cached is not None:
                return cached
            return self._compile_locked(
                key, args, kwargs, avals, dyn_args, dyn_kwargs
            )

    def _compile_locked(
        self, key: tuple, args: tuple, kwargs: dict, avals: tuple,
        dyn_args: tuple, dyn_kwargs: dict,
    ) -> Any:
        paths: list[str] = []
        try:
            flat, _ = jax.tree_util.tree_flatten_with_path(
                (dyn_args, dyn_kwargs)
            )
            paths = [jax.tree_util.keystr(p) for p, _ in flat]
        except Exception:
            paths = [f"leaf[{i}]" for i in range(len(avals))]
        swept_key = self._swept_key(key)
        with self._lock:
            warm = bool(self._sigs) or self._last_sig is not None
            seen_before = key in self._seen_sigs
            swept_before = (
                swept_key is not None and swept_key in self._seen_swept
            )
            old_paths, old_avals = self._last_paths, self._last_avals
            old_statics = self._last_statics
        retrace = warm and not seen_before
        # a miss that matches a seen signature after stripping the SWEEP
        # statics is a planned executable for a new static value (the
        # fused program compiling for a new n_rounds) — real compile
        # cost, attributed on the span, but NOT a retrace
        static_sweep = retrace and swept_before
        if static_sweep:
            retrace = False
        changed = (
            _signature_diff(old_paths, old_avals, paths, avals,
                            old_statics, key[2])
            if (retrace or static_sweep) else None
        )
        attrs: dict[str, Any] = {
            "function": self.name,
            "n_leaves": len(avals),
            "retrace": retrace,
        }
        if static_sweep:
            attrs["static_sweep"] = True
        if seen_before:
            # recompiling a signature the FIFO evicted — raise
            # max_signatures (V6T_DEVICE_OBS_SIGS) if this is frequent
            attrs["evicted_recompile"] = True
        if changed:
            attrs["changed"] = changed
        with TRACER.span("device.compile", kind="device", attrs=attrs) as sp:
            t0 = time.perf_counter()
            try:
                lowered = self._jit.lower(*args, **kwargs)
                t1 = time.perf_counter()
                compiled = lowered.compile()
                t2 = time.perf_counter()
            except Exception as e:
                # an AOT-unloweable call (e.g. a jax version quirk):
                # record the failure, let the caller use plain jit
                sp.set_status("error")
                sp.set_attr(error=repr(e))
                self.fallbacks += 1
                REGISTRY.counter("v6t_jit_fallbacks_total").inc()
                return None
            lower_s, compile_s = t1 - t0, t2 - t1
            mem = _memory_summary(compiled)
            cost = _cost_summary(compiled)
            sp.set_attr(
                lower_ms=round(lower_s * 1e3, 3),
                compile_ms=round(compile_s * 1e3, 3),
                **mem, **cost,
            )
        self.compiles += 1
        REGISTRY.counter("v6t_jit_compiles_total").inc()
        REGISTRY.counter("v6t_jit_lower_seconds_total").inc(lower_s)
        REGISTRY.counter("v6t_jit_compile_seconds_total").inc(compile_s)
        if mem.get("temp_bytes") is not None:
            REGISTRY.gauge("v6t_jit_compile_temp_bytes").set(
                mem["temp_bytes"]
            )
        if cost.get("flops") is not None:
            REGISTRY.gauge("v6t_jit_compile_flops").set(cost["flops"])
        self.last_compile = {
            "ts": time.time(),
            "lower_s": lower_s,
            "compile_s": compile_s,
            "retrace": retrace,
            "changed": changed,
            **mem, **cost,
        }
        if retrace:
            self.retraces += 1
            REGISTRY.counter("v6t_jit_retraces_total").inc()
            DEVICE_OBS.record_retrace(self.name, changed or "?")
        if static_sweep:
            self.static_sweeps += 1
            REGISTRY.counter("v6t_jit_static_sweeps_total").inc()
        with self._lock:
            self._sigs[key] = compiled
            self._seen_sigs[key] = None
            self._seen_sigs.move_to_end(key)
            while len(self._seen_sigs) > 1024:
                self._seen_sigs.popitem(last=False)
            if swept_key is not None:
                self._seen_swept[swept_key] = None
                self._seen_swept.move_to_end(swept_key)
                while len(self._seen_swept) > 1024:
                    self._seen_swept.popitem(last=False)
            self._last_sig = key
            self._last_paths, self._last_avals = paths, avals
            self._last_statics = key[2]
            while len(self._sigs) > DEVICE_OBS.max_signatures:
                self._sigs.popitem(last=False)
                self.evictions += 1
                REGISTRY.counter("v6t_jit_cache_evictions_total").inc()
        return compiled

    # ------------------------------------------------------------- queries
    def n_signatures(self) -> int:
        with self._lock:
            return len(self._sigs)

    def clear(self) -> None:
        with self._lock:
            self._sigs.clear()
            self._seen_sigs.clear()
            self._seen_swept.clear()
            self._last_sig = None
            self._last_paths, self._last_avals = [], ()
            self._last_statics = ()

    def stats(self) -> dict[str, Any]:
        return {
            "function": self.name,
            "signatures": self.n_signatures(),
            "compiles": self.compiles,
            "retraces": self.retraces,
            "static_sweeps": self.static_sweeps,
            "dispatches": self.dispatches,
            "fallbacks": self.fallbacks,
            "evictions": self.evictions,
            "last_compile": dict(self.last_compile),
        }


class DeviceObservatory:
    """Process-wide registry of observed functions + the device-plane
    state the watchdog feed and tools read. Env knobs (read once;
    ``configure()`` overrides live): ``V6T_DEVICE_OBS=0`` disables,
    ``V6T_DEVICE_OBS_SIGS`` caps live signatures per function."""

    def __init__(self):
        self._lock = threading.Lock()
        # weak refs: an observed function lives exactly as long as its
        # owner's reference (a FedAvg instance's self._round, a module-
        # level runner cache). A per-instance wrapper must not be pinned
        # here for process lifetime — that is the "host references
        # pinning device arrays" leak this module's own runbook warns
        # about. A SET, not a name-keyed map: two live instances sharing
        # a name (two FedAvg engines both registering "fedavg.round")
        # must BOTH stay tracked, or clear() misses one's executables and
        # the v6t_jit_signatures gauge undercounts live programs.
        self._functions: "weakref.WeakSet[ObservedFunction]" = weakref.WeakSet()
        # recent retrace events, newest last (watchdog feed + doctor)
        self._retraces: deque[dict[str, Any]] = deque(maxlen=64)
        self._engine_caches: dict[str, dict[str, int]] = {}
        self.enabled = os.environ.get("V6T_DEVICE_OBS", "1") != "0"
        self.max_signatures = max(1, env_int("V6T_DEVICE_OBS_SIGS", 32))

    def configure(
        self, enabled: bool | None = None, max_signatures: int | None = None
    ) -> "DeviceObservatory":
        if enabled is not None:
            self.enabled = bool(enabled)
        if max_signatures is not None:
            self.max_signatures = max(1, int(max_signatures))
        return self

    # ------------------------------------------------------------ registry
    def register(self, fn: ObservedFunction) -> ObservedFunction:
        with self._lock:
            self._functions.add(fn)
        return fn

    def functions(self) -> list[ObservedFunction]:
        with self._lock:
            return list(self._functions)

    def record_retrace(self, function: str, changed: str) -> None:
        rec = {"ts": time.time(), "function": function, "changed": changed}
        with self._lock:
            self._retraces.append(rec)
        try:
            from vantage6_tpu.common.flight import FLIGHT

            FLIGHT.note("retrace", function=function, changed=changed)
        except Exception:  # pragma: no cover - recorder must stay optional
            pass

    def recent_retraces(self, limit: int = 16) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._retraces)[-limit:]

    # -------------------------------------------------------- engine caches
    def engine_cache_event(
        self, cache: str, hit: bool, entries: int | None = None
    ) -> None:
        """One lookup against a ``mesh.fingerprint()``-keyed runner cache
        (glm/quantile/device_engine): counted process-wide AND per-cache,
        so `/metrics` answers "does the executable cache work at all" and
        :meth:`stats` answers "which one doesn't"."""
        if not self.enabled:
            # V6T_DEVICE_OBS=0 promises the WHOLE layer off — the cache
            # counters must not keep emitting behind the operator's back
            return
        with self._lock:
            st = self._engine_caches.setdefault(
                cache, {"hits": 0, "misses": 0, "entries": 0}
            )
            st["hits" if hit else "misses"] += 1
            if entries is not None:
                st["entries"] = int(entries)
            total_entries = sum(
                c["entries"] for c in self._engine_caches.values()
            )
        REGISTRY.counter(
            "v6t_engine_cache_hits_total" if hit
            else "v6t_engine_cache_misses_total"
        ).inc()
        REGISTRY.gauge("v6t_engine_cache_entries").set(total_entries)

    def engine_cache_stats(self) -> dict[str, dict[str, int]]:
        with self._lock:
            return {k: dict(v) for k, v in self._engine_caches.items()}

    # --------------------------------------------------------------- output
    def stats(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "functions": [f.stats() for f in self.functions()],
            "engine_caches": self.engine_cache_stats(),
            "recent_retraces": self.recent_retraces(),
        }

    def clear(self) -> None:
        """Drop compiled executables + retrace/engine-cache history (test
        and bench-arm isolation; the plain ``jax.jit`` twins keep their
        own caches, so clearing never causes a recompile storm)."""
        for fn in self.functions():
            fn.clear()
        with self._lock:
            self._retraces.clear()
            self._engine_caches.clear()

    def watchdog_feed(self) -> dict[str, Any]:
        """The ``recompile_storm`` rule's evidence: recent retrace events
        as feed items, newest last."""
        return {"retraces": self.recent_retraces()}


DEVICE_OBS = DeviceObservatory()


def observed_jit(
    name: str,
    fun: Callable[..., Any],
    *,
    static_argnums: tuple[int, ...] = (),
    static_argnames: tuple[str, ...] = (),
    sweep_statics: tuple[str, ...] = (),
    **jit_kwargs: Any,
) -> ObservedFunction:
    """``jax.jit`` with the device observatory attached (module doc).
    ``name`` is the low-cardinality label every compile span, retrace
    note and alert uses — name the OPERATION (``fedavg.round``), not the
    call site. ``sweep_statics`` names statics the caller legitimately
    sweeps (the fused program's ``n_rounds``): compiles differing only in
    those are counted as ``static_sweeps``, not retraces."""
    return DEVICE_OBS.register(ObservedFunction(
        name, fun, static_argnums=static_argnums,
        static_argnames=static_argnames, sweep_statics=sweep_statics,
        **jit_kwargs,
    ))


# ------------------------------------------------------------ module-level
def engine_cache_event(
    cache: str, hit: bool, entries: int | None = None
) -> None:
    """Convenience forwarder to :meth:`DeviceObservatory.engine_cache_event`
    (the glm/quantile/device_engine runner caches call this)."""
    DEVICE_OBS.engine_cache_event(cache, hit, entries=entries)


class RunnerCache:
    """FIFO-bounded get-or-create cache for ``mesh.fingerprint()``-keyed
    observed runners — the ONE implementation behind the glm / quantile /
    device_engine / collectives caches. Every lookup is reported through
    :func:`engine_cache_event` under the cache's name; the bound matters
    because keys legitimately carry sweepable values (n_iter, lr, flat
    length), and an unbounded runner cache would BE the executable leak
    the observatory exists to catch. Evicted runners drop out of the
    weak function registry with their executables."""

    def __init__(self, name: str, max_entries: int = 32):
        self.name = name
        self.max_entries = max_entries
        self._lock = threading.Lock()
        # guarded-by: _lock — insertion-ordered for FIFO eviction
        self._runners: "OrderedDict[Any, Any]" = OrderedDict()

    def get_or_create(self, key: Any, factory: Callable[[], Any]) -> Any:
        with self._lock:
            fn = self._runners.get(key)
        hit = fn is not None
        if not hit:
            # factory() runs unlocked (it may trigger tracing/compiles);
            # a rare duplicate build is benign — last writer wins
            fn = factory()
            with self._lock:
                self._runners[key] = fn
                while len(self._runners) > self.max_entries:
                    self._runners.popitem(last=False)
        engine_cache_event(self.name, hit, entries=len(self._runners))
        return fn

    def __len__(self) -> int:
        with self._lock:
            return len(self._runners)

    def clear(self) -> None:
        with self._lock:
            self._runners.clear()


# ----------------------------------------------------------- device memory
def _device_mem_collector() -> dict[str, float]:
    """Per-device memory as telemetry gauges: bytes-in-use summed over all
    local devices, worst-device peak, device count. Empty on backends
    that report no memory stats (CPU) — an absent series, never a fake
    zero the ``device_mem_growth`` trend rule would chew on."""
    from vantage6_tpu.runtime.metrics import device_memory_all

    per = device_memory_all()
    if not per:
        return {}
    out = {
        "v6t_device_count": float(len(per)),
        "v6t_device_mem_bytes_in_use": float(
            sum(d.get("bytes_in_use") or 0 for d in per)
        ),
    }
    peaks = [d.get("peak_bytes") for d in per if d.get("peak_bytes")]
    if peaks:
        out["v6t_device_mem_peak_bytes"] = float(max(peaks))
    return out


REGISTRY.register_collector("device_mem", _device_mem_collector)


# ---------------------------------------------------------- profile windows
class ProfileBusyError(RuntimeError):
    """A profiling window is already open (jax.profiler sessions cannot
    nest); retry after it closes."""


_PROFILE_LOCK = threading.Lock()

PROFILE_MAX_SECONDS = 30.0


def profile_window(
    seconds: float = 1.0, log_dir: str | None = None
) -> dict[str, Any]:
    """Run one bounded ``jax.profiler`` sampling window NOW and return
    ``{"path", "seconds", "trace_id"}``.

    The window is recorded as a ``device.profile`` span — parented on the
    caller's active trace when there is one (the ``POST
    /api/debug/profile`` handler runs inside the joined request span, so
    a client-initiated window lands in the requesting trace) — and the
    artifact path is registered in the flight recorder (note kind
    ``profile_window``), so a later ``doctor`` of the bundle names where
    the Perfetto session lives. One window at a time per process
    (:class:`ProfileBusyError` otherwise); duration is clamped to
    ``(0.05, PROFILE_MAX_SECONDS)`` — an unbounded window from a REST
    handler would hold the worker hostage.
    """
    seconds = min(PROFILE_MAX_SECONDS, max(0.05, float(seconds)))
    if log_dir is None:
        base = os.environ.get("V6T_PROFILE_DIR") or None
        if base is None:
            import tempfile

            base = tempfile.gettempdir()
        log_dir = os.path.join(
            base, f"v6t-profile-{os.getpid()}-{int(time.time() * 1000)}"
        )
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise ProfileBusyError(
            "a profiling window is already open in this process"
        )
    try:
        with TRACER.span(
            "device.profile", kind="device",
            attrs={"log_dir": str(log_dir), "seconds": seconds,
                   "source": "profile_window"},
        ) as sp:
            ctx = getattr(sp, "context", None)
            trace_id = ctx.trace_id if ctx is not None else None
            jax.profiler.start_trace(str(log_dir))
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
    finally:
        _PROFILE_LOCK.release()
    try:
        from vantage6_tpu.common.flight import FLIGHT

        FLIGHT.note(
            "profile_window", path=str(log_dir), seconds=seconds,
            trace_id=trace_id,
        )
    except Exception:  # pragma: no cover - recorder must stay optional
        pass
    return {"path": str(log_dir), "seconds": seconds, "trace_id": trace_id}


# --------------------------------------------------------------- telemetry
def _observatory_collector() -> dict[str, float]:
    """The v6t_jit_functions / v6t_jit_signatures gauges: computed at
    snapshot time (collectors run on every scrape/dump/watchdog pass), so
    they always reflect the LIVE registry — evictions, clears, and
    garbage-collected functions included."""
    fns = DEVICE_OBS.functions()
    return {
        "v6t_jit_functions": float(len(fns)),
        "v6t_jit_signatures": float(
            sum(f.n_signatures() for f in fns)
        ),
    }


REGISTRY.register_collector("device_obs", _observatory_collector)


# -------------------------------------------------------------- watchdog
try:
    from vantage6_tpu.runtime.watchdog import WATCHDOG as _WATCHDOG

    _WATCHDOG.register_feed("device_plane", DEVICE_OBS.watchdog_feed)
except Exception:  # pragma: no cover - watchdog must stay optional here
    pass
